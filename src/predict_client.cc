// Standalone C++ predict client — the reference's amalgamation/predict
// story (image-classification/predict-cpp) re-done for the TPU artifact:
// load a Predictor.export blob through the MXPred* C ABI (predict_api.cc),
// read a batch of raw float32 records through the RecordIO C ABI
// (recordio.cc), classify, print per-record argmax.  No Python written by
// the consumer.
//
// Usage: predict_client <artifact> <recfile> <nrecords> <dim...>
//   records hold raw little-endian float32 payloads of prod(dim) elements;
//   the artifact's single input is named "data" with shape
//   (nrecords, dim...).
//
// Build (see tests/test_predict_client.py):
//   g++ -O2 -std=c++17 predict_client.cc predict_api.cc recordio.cc \
//       $(python3-config --embed --cflags --libs) -o predict_client

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

extern "C" {
const char *MXGetLastError();
int MXPredCreate(const char *, uint64_t, int, int, uint32_t, const char **,
                 const uint32_t *, const uint32_t *, void **);
int MXPredSetInput(void *, const char *, const float *, uint32_t,
                   const uint32_t *, uint32_t);
int MXPredForward(void *);
int MXPredGetOutputShape(void *, uint32_t, uint32_t **, uint32_t *);
int MXPredGetOutput(void *, uint32_t, float *, uint32_t);
int MXPredFree(void *);

const char *rio_last_error();
void *rio_reader_open(const char *);
int rio_reader_next(void *, const void **, uint64_t *);
int rio_reader_close(void *);
}

namespace {

int die(const char *what, const char *detail) {
  std::fprintf(stderr, "predict_client: %s: %s\n", what, detail);
  return 1;
}

}  // namespace

int main(int argc, char **argv) {
  if (argc < 4) {
    std::fprintf(stderr,
                 "usage: %s <artifact> <recfile> <nrecords> <dim...>\n",
                 argv[0]);
    return 2;
  }
  const char *artifact_path = argv[1];
  const char *rec_path = argv[2];
  uint32_t nrec = static_cast<uint32_t>(std::atoi(argv[3]));
  std::vector<uint32_t> dims;
  uint64_t per_rec = 1;
  for (int i = 4; i < argc; ++i) {
    dims.push_back(static_cast<uint32_t>(std::atoi(argv[i])));
    per_rec *= dims.back();
  }

  // ---- artifact bytes
  std::FILE *f = std::fopen(artifact_path, "rb");
  if (!f) return die("open artifact", artifact_path);
  std::fseek(f, 0, SEEK_END);
  long len = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  std::vector<char> blob(len);
  if (std::fread(blob.data(), 1, len, f) != static_cast<size_t>(len)) {
    std::fclose(f);
    return die("read artifact", artifact_path);
  }
  std::fclose(f);

  // ---- batch from recordio (raw float32 payloads)
  void *reader = rio_reader_open(rec_path);
  if (!reader) return die("open recordio", rio_last_error());
  std::vector<float> batch(static_cast<size_t>(nrec) * per_rec);
  for (uint32_t i = 0; i < nrec; ++i) {
    const void *data = nullptr;
    uint64_t dlen = 0;
    if (rio_reader_next(reader, &data, &dlen) != 1) {
      return die("read record", rio_last_error());
    }
    if (dlen != per_rec * 4) {
      std::fprintf(stderr, "record %u: %llu bytes, want %llu\n", i,
                   (unsigned long long)dlen,
                   (unsigned long long)(per_rec * 4));
      return 1;
    }
    std::memcpy(batch.data() + static_cast<size_t>(i) * per_rec, data,
                dlen);
  }
  rio_reader_close(reader);

  // ---- predict through the C ABI
  std::vector<uint32_t> shape;
  shape.push_back(nrec);
  shape.insert(shape.end(), dims.begin(), dims.end());
  uint32_t indptr[2] = {0, static_cast<uint32_t>(shape.size())};
  const char *keys[1] = {"data"};
  void *h = nullptr;
  if (MXPredCreate(blob.data(), blob.size(), 1, 0, 1, keys, indptr,
                   shape.data(), &h) != 0) {
    return die("MXPredCreate", MXGetLastError());
  }
  if (MXPredSetInput(h, "data", batch.data(),
                     static_cast<uint32_t>(batch.size()), shape.data(),
                     static_cast<uint32_t>(shape.size())) != 0) {
    return die("MXPredSetInput", MXGetLastError());
  }
  if (MXPredForward(h) != 0) return die("MXPredForward", MXGetLastError());

  uint32_t *oshape = nullptr;
  uint32_t ondim = 0;
  if (MXPredGetOutputShape(h, 0, &oshape, &ondim) != 0) {
    return die("MXPredGetOutputShape", MXGetLastError());
  }
  uint64_t osize = 1;
  for (uint32_t i = 0; i < ondim; ++i) osize *= oshape[i];
  std::vector<float> out(osize);
  if (MXPredGetOutput(h, 0, out.data(),
                      static_cast<uint32_t>(osize)) != 0) {
    return die("MXPredGetOutput", MXGetLastError());
  }

  uint64_t classes = (ondim >= 2) ? osize / oshape[0] : 1;
  for (uint32_t i = 0; i < nrec; ++i) {
    const float *row = out.data() + static_cast<size_t>(i) * classes;
    uint64_t best = 0;
    for (uint64_t c = 1; c < classes; ++c) {
      if (row[c] > row[best]) best = c;
    }
    std::printf("record %u: class %llu prob %.4f\n", i,
                (unsigned long long)best, row[best]);
  }
  MXPredFree(h);
  return 0;
}
