// MXPred* C ABI — the reference's standalone predictor surface
// (include/mxnet/c_predict_api.h:59-169: MXPredCreate / MXPredSetInput /
// MXPredForward / MXPredGetOutputShape / MXPredGetOutput / MXPredFree)
// re-hosted over the TPU framework's deployment artifact.
//
// The reference's client links libmxnet and feeds it symbol JSON + param
// blobs; here the artifact is a serialized `jax.export` program
// (Predictor.export) with the weights folded in, and the runtime hosted
// behind this ABI is XLA via an embedded CPython — consumers of the C ABI
// (this repo's predict_client.cc, or any language's FFI) never touch
// Python themselves.  Deviations from the reference signature: the
// artifact replaces (symbol_json, param_bytes), and input keys must be
// given in the artifact's export feed order.
//
// Build: g++ -O2 -std=c++17 -shared -fPIC predict_api.cc \
//          $(python3-config --embed --cflags --libs) -o libmxtpu_predict.so

#include <Python.h>

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <string>
#include <vector>

namespace {

thread_local std::string g_error;

void set_error(const std::string &m) { g_error = m; }

// Helper module executed inside the embedded interpreter: owns the
// deserialized executables and the staging buffers.
const char *kHelperSrc = R"PY(
import jax
try:
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass  # backend may already be initialized by the embedding process
import numpy as np
from jax import export as jax_export

_handles = {}
_next = [1]

def create(blob, keys, shapes):
    exp = jax_export.deserialize(bytearray(blob))
    avals = list(exp.in_avals)
    if len(keys) != len(avals):
        raise ValueError("artifact expects %d inputs, %d keys declared"
                         % (len(avals), len(keys)))
    if shapes is not None:
        # the caller declared per-input shapes (c_predict_api.h CSR
        # contract): honor them by checking against the artifact rather
        # than silently ignoring them
        if len(shapes) != len(avals):
            raise ValueError("declared %d input shapes, artifact expects %d"
                             % (len(shapes), len(avals)))
        for key, shp, av in zip(keys, shapes, avals):
            if tuple(shp) != tuple(av.shape):
                raise ValueError(
                    "declared shape %s for input %r does not match the "
                    "artifact's %s" % (tuple(shp), key, tuple(av.shape)))
    h = _next[0]; _next[0] += 1
    _handles[h] = {"exp": exp, "keys": list(keys), "in": {}, "out": None}
    return h

def set_input(h, key, mv, shape):
    d = _handles[h]
    if key not in d["keys"]:
        raise KeyError("unknown input %r (artifact inputs: %s)"
                       % (key, d["keys"]))
    d["in"][key] = np.frombuffer(mv, np.float32).reshape(shape).copy()

def forward(h):
    d = _handles[h]
    missing = [k for k in d["keys"] if k not in d["in"]]
    if missing:
        raise ValueError("inputs not set: %s" % missing)
    args = [d["in"][k] for k in d["keys"]]
    d["out"] = [np.asarray(o, dtype=np.float32) for o in
                d["exp"].call(*args)]

def out_ndim(h, i):
    return len(_handles[h]["out"][i].shape)

def out_shape(h, i):
    return list(_handles[h]["out"][i].shape)

def get_output(h, i, mv):
    out = _handles[h]["out"][i].ravel()
    dst = np.frombuffer(mv, np.float32)
    if dst.size != out.size:
        raise ValueError("output buffer size %d != %d" % (dst.size, out.size))
    dst[:] = out

def free(h):
    _handles.pop(h, None)
)PY";

std::atomic<PyObject *> g_helper{nullptr};
std::mutex g_init_mu;
// Guarded by the GIL (read/modified only between a PyGILState_Ensure and
// the next potential GIL release).  No C++ mutex may be held across the
// helper exec: PyRun_String imports jax/numpy, whose file I/O drops and
// re-acquires the GIL internally — a mutex held there deadlocks against
// any host thread that calls in with the GIL held (ctypes.PyDLL).
std::atomic<bool> g_init_in_progress{false};

// First-call initialization must be race-free: the ABI promises
// thread-safe use, and two FFI threads hitting a naked null check could
// both run Py_InitializeEx (UB) or leak a helper module.  A failed init
// does NOT latch: a later call retries — e.g. after the caller fixes
// PYTHONPATH, as the error message suggests.
bool ensure_python() {
  if (g_helper.load(std::memory_order_acquire) != nullptr) return true;
  {
    // interpreter bring-up only; no GIL interplay inside the lock (if
    // another thread holds the GIL the interpreter is already
    // initialized and this section is a no-op)
    std::lock_guard<std::mutex> lock(g_init_mu);
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      // release the GIL the initializing thread holds, so MXPred* calls
      // from ANY thread can PyGILState_Ensure without deadlocking
      PyEval_SaveThread();
    }
  }
  PyGILState_STATE gs = PyGILState_Ensure();
  // Serialize the helper exec with a GIL-guarded claim: between the
  // check and the store below the GIL is never released, so exactly one
  // thread claims; waiters sleep WITHOUT holding the GIL or any lock.
  while (g_init_in_progress.load(std::memory_order_relaxed)) {
    Py_BEGIN_ALLOW_THREADS
    std::this_thread::sleep_for(std::chrono::milliseconds(5));
    Py_END_ALLOW_THREADS
  }
  if (g_helper.load(std::memory_order_acquire) != nullptr) {
    PyGILState_Release(gs);
    return true;
  }
  g_init_in_progress.store(true, std::memory_order_relaxed);
  bool ok = false;
  PyObject *mod = PyModule_New("_mxtpu_predict_embed");
  PyObject *dict = PyModule_GetDict(mod);
  PyDict_SetItemString(dict, "__builtins__", PyEval_GetBuiltins());
  PyObject *res = PyRun_String(kHelperSrc, Py_file_input, dict, dict);
  if (res == nullptr) {
    PyErr_Print();
    set_error("failed to initialize embedded predict runtime "
              "(is jax importable? set PYTHONPATH to the site-packages "
              "that hold jax)");
    Py_DECREF(mod);
  } else {
    Py_DECREF(res);
    g_helper.store(mod, std::memory_order_release);
    ok = true;
  }
  g_init_in_progress.store(false, std::memory_order_relaxed);
  PyGILState_Release(gs);
  return ok;
}

// Build an argument tuple from already-owned references; PyTuple_SetItem
// STEALS each reference, so nothing here leaks (PyTuple_Pack would add
// its own references on top of the fresh ones, leaking one per call).
PyObject *pack_args(std::initializer_list<PyObject *> items) {
  PyObject *t = PyTuple_New(static_cast<Py_ssize_t>(items.size()));
  Py_ssize_t i = 0;
  for (PyObject *o : items) PyTuple_SetItem(t, i++, o);
  return t;
}

// Call helper.<name>(args...); returns new ref or nullptr (error set).
PyObject *call(const char *name, PyObject *args) {
  PyObject *helper = g_helper.load(std::memory_order_acquire);
  if (helper == nullptr) {
    set_error("predict runtime not initialized");
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject *fn = PyObject_GetAttrString(helper, name);
  if (fn == nullptr) {
    set_error(std::string("helper missing ") + name);
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject *out = PyObject_CallObject(fn, args);
  Py_DECREF(fn);
  Py_XDECREF(args);
  if (out == nullptr) {
    PyObject *t, *v, *tb;
    PyErr_Fetch(&t, &v, &tb);
    PyObject *s = v ? PyObject_Str(v) : nullptr;
    set_error(s ? PyUnicode_AsUTF8(s) : "embedded call failed");
    Py_XDECREF(s);
    Py_XDECREF(t); Py_XDECREF(v); Py_XDECREF(tb);
    return nullptr;
  }
  return out;
}

struct Pred {
  long handle = 0;
  std::vector<uint32_t> last_shape;
};

}  // namespace

extern "C" {

const char *MXGetLastError() { return g_error.c_str(); }

// artifact: serialized jax.export blob (Predictor.export).  input_keys
// must list the artifact's inputs in export feed order; shapes are given
// CSR-style via indptr exactly as the reference's MXPredCreate
// (c_predict_api.h:59-103) and are VALIDATED against the artifact — a
// mismatch fails here with a clean error instead of at forward.  Passing
// nullptr for both shape arrays skips the check (shapes then come from
// MXPredSetInput).
int MXPredCreate(const char *artifact, uint64_t artifact_len,
                 int dev_type, int dev_id, uint32_t num_input_nodes,
                 const char **input_keys, const uint32_t *input_shape_indptr,
                 const uint32_t *input_shape_data, void **out) {
  (void)dev_type; (void)dev_id;
  if (!ensure_python()) return -1;
  PyGILState_STATE gs = PyGILState_Ensure();
  PyObject *blob = PyBytes_FromStringAndSize(artifact,
                                             static_cast<Py_ssize_t>(artifact_len));
  PyObject *keys = PyList_New(num_input_nodes);
  for (uint32_t i = 0; i < num_input_nodes; ++i) {
    PyList_SetItem(keys, i, PyUnicode_FromString(input_keys[i]));
  }
  PyObject *shapes;
  if (input_shape_indptr != nullptr && input_shape_data != nullptr) {
    shapes = PyList_New(num_input_nodes);
    for (uint32_t i = 0; i < num_input_nodes; ++i) {
      uint32_t lo = input_shape_indptr[i], hi = input_shape_indptr[i + 1];
      PyObject *shp = PyTuple_New(hi - lo);
      for (uint32_t j = lo; j < hi; ++j) {
        PyTuple_SetItem(shp, j - lo,
                        PyLong_FromUnsignedLong(input_shape_data[j]));
      }
      PyList_SetItem(shapes, i, shp);
    }
  } else {
    shapes = Py_None;
    Py_INCREF(Py_None);
  }
  PyObject *res = call("create", pack_args({blob, keys, shapes}));
  int rc = -1;
  if (res != nullptr) {
    Pred *p = new Pred();
    p->handle = PyLong_AsLong(res);
    Py_DECREF(res);
    *out = p;
    rc = 0;
  }
  PyGILState_Release(gs);
  return rc;
}

int MXPredSetInput(void *handle, const char *key, const float *data,
                   uint32_t size, const uint32_t *shape, uint32_t ndim) {
  Pred *p = static_cast<Pred *>(handle);
  PyGILState_STATE gs = PyGILState_Ensure();
  PyObject *mv = PyMemoryView_FromMemory(
      reinterpret_cast<char *>(const_cast<float *>(data)),
      static_cast<Py_ssize_t>(size) * 4, PyBUF_READ);
  PyObject *shp = PyTuple_New(ndim);
  for (uint32_t i = 0; i < ndim; ++i) {
    PyTuple_SetItem(shp, i, PyLong_FromUnsignedLong(shape[i]));
  }
  PyObject *res = call("set_input",
                       pack_args({PyLong_FromLong(p->handle),
                                  PyUnicode_FromString(key), mv, shp}));
  int rc = res ? 0 : -1;
  Py_XDECREF(res);
  PyGILState_Release(gs);
  return rc;
}

int MXPredForward(void *handle) {
  Pred *p = static_cast<Pred *>(handle);
  PyGILState_STATE gs = PyGILState_Ensure();
  PyObject *res = call("forward",
                       pack_args({PyLong_FromLong(p->handle)}));
  int rc = res ? 0 : -1;
  Py_XDECREF(res);
  PyGILState_Release(gs);
  return rc;
}

int MXPredGetOutputShape(void *handle, uint32_t index,
                         uint32_t **shape_data, uint32_t *shape_ndim) {
  Pred *p = static_cast<Pred *>(handle);
  PyGILState_STATE gs = PyGILState_Ensure();
  PyObject *res = call("out_shape",
                       pack_args({PyLong_FromLong(p->handle),
                                  PyLong_FromUnsignedLong(index)}));
  int rc = -1;
  if (res != nullptr) {
    Py_ssize_t n = PyList_Size(res);
    p->last_shape.resize(n);
    for (Py_ssize_t i = 0; i < n; ++i) {
      p->last_shape[i] = static_cast<uint32_t>(
          PyLong_AsLong(PyList_GetItem(res, i)));
    }
    Py_DECREF(res);
    *shape_data = p->last_shape.data();
    *shape_ndim = static_cast<uint32_t>(n);
    rc = 0;
  }
  PyGILState_Release(gs);
  return rc;
}

int MXPredGetOutput(void *handle, uint32_t index, float *data,
                    uint32_t size) {
  Pred *p = static_cast<Pred *>(handle);
  PyGILState_STATE gs = PyGILState_Ensure();
  PyObject *mv = PyMemoryView_FromMemory(reinterpret_cast<char *>(data),
                                         static_cast<Py_ssize_t>(size) * 4,
                                         PyBUF_WRITE);
  PyObject *res = call("get_output",
                       pack_args({PyLong_FromLong(p->handle),
                                  PyLong_FromUnsignedLong(index), mv}));
  int rc = res ? 0 : -1;
  Py_XDECREF(res);
  PyGILState_Release(gs);
  return rc;
}

int MXPredFree(void *handle) {
  Pred *p = static_cast<Pred *>(handle);
  if (g_helper.load(std::memory_order_acquire) != nullptr) {
    PyGILState_STATE gs = PyGILState_Ensure();
    PyObject *res = call("free",
                         pack_args({PyLong_FromLong(p->handle)}));
    Py_XDECREF(res);
    PyGILState_Release(gs);
  }
  delete p;
  return 0;
}

}  // extern "C"
