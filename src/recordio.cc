// Native RecordIO codec — the TPU framework's analog of dmlc-core's
// recordio + the reference's src/io record readers (iter_image_recordio.cc
// reads this format through dmlc::RecordIOReader).
//
// On-disk format (byte-compatible with the reference so .rec files
// interoperate both ways):
//   record  := [kMagic:u32le][(cflag<<29)|len:u32le][data:len][pad to 4B]
//   cflag   := 0 whole record | 1 first part | 2 middle part | 3 last part
// Split records (cflag 1/2/3) arise when data contains the magic at a
// 4-byte-aligned position: the writer splits there and DROPS the embedded
// magic bytes (the next part's header magic stands in for them), so
// magic-scanning chunk readers always land on real frame boundaries.  The
// reader re-inserts the magic between parts while reassembling.  Both
// directions match the reference's dmlc writer/reader, so .rec files
// interoperate both ways — including with its partitioned
// RecordIOChunkReader.
//
// Exposed as a C ABI consumed by mxnet_tpu/_native.py over ctypes.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xCED7230A;
constexpr uint32_t kLenMask = 0x1FFFFFFF;

thread_local std::string g_error;

void set_error(const std::string &msg) { g_error = msg; }

struct Writer {
  FILE *fp = nullptr;
  explicit Writer(const char *path) { fp = std::fopen(path, "wb"); }
  ~Writer() {
    if (fp) std::fclose(fp);
  }
};

struct Reader {
  FILE *fp = nullptr;
  std::vector<uint8_t> buf;   // last record's reassembled payload
  explicit Reader(const char *path) { fp = std::fopen(path, "rb"); }
  ~Reader() {
    if (fp) std::fclose(fp);
  }
};

// Reads one framed chunk. Returns 1 on success, 0 on clean EOF, -1 on error.
int read_chunk(FILE *fp, std::vector<uint8_t> *out, uint32_t *cflag) {
  uint32_t header[2];
  size_t n = std::fread(header, 1, sizeof(header), fp);
  if (n == 0) return 0;
  if (n != sizeof(header)) {
    set_error("truncated record header");
    return -1;
  }
  if (header[0] != kMagic) {
    set_error("bad RecordIO magic");
    return -1;
  }
  *cflag = header[1] >> 29;
  uint32_t len = header[1] & kLenMask;
  size_t old = out->size();
  out->resize(old + len);
  if (len && std::fread(out->data() + old, 1, len, fp) != len) {
    set_error("truncated record payload");
    return -1;
  }
  uint32_t pad = (4u - (len & 3u)) & 3u;
  if (pad) {
    uint8_t scratch[4];
    if (std::fread(scratch, 1, pad, fp) != pad) {
      set_error("truncated record padding");
      return -1;
    }
  }
  return 1;
}

}  // namespace

extern "C" {

const char *rio_last_error() { return g_error.c_str(); }

// ---------------------------------------------------------------- writer --
void *rio_writer_open(const char *path) {
  Writer *w = new Writer(path);
  if (!w->fp) {
    set_error(std::string("cannot open for write: ") + path);
    delete w;
    return nullptr;
  }
  return w;
}

int64_t rio_writer_tell(void *h) {
  return static_cast<int64_t>(std::ftell(static_cast<Writer *>(h)->fp));
}

namespace {

// One framed part: [magic][(cflag<<29)|len][data][pad]. Returns 0 ok, -1 err.
int write_part(FILE *fp, uint32_t cflag, const uint8_t *data, uint32_t len) {
  uint32_t header[2] = {kMagic, (cflag << 29) | (len & kLenMask)};
  if (std::fwrite(header, 1, sizeof(header), fp) != sizeof(header) ||
      (len && std::fwrite(data, 1, len, fp) != len)) {
    set_error("short write");
    return -1;
  }
  uint32_t pad = (4u - (len & 3u)) & 3u;
  if (pad) {
    const uint8_t zeros[4] = {0, 0, 0, 0};
    if (std::fwrite(zeros, 1, pad, fp) != pad) {
      set_error("short write (pad)");
      return -1;
    }
  }
  return 0;
}

}  // namespace

// Returns the record's start offset (for indexing), or -1 on error.
// Payloads embedding the magic at aligned positions are split there, the
// magic bytes replaced by the following part's header (dmlc framing).
int64_t rio_writer_write(void *h, const void *data, uint64_t len) {
  Writer *w = static_cast<Writer *>(h);
  if (len > kLenMask) {
    set_error("record too large (max 2^29-1 bytes per frame)");
    return -1;
  }
  const uint8_t *bytes = static_cast<const uint8_t *>(data);
  int64_t start = std::ftell(w->fp);

  std::vector<uint64_t> magics;
  for (uint64_t i = 0; i + 4 <= len; i += 4) {
    if (std::memcmp(bytes + i, &kMagic, 4) == 0) magics.push_back(i);
  }
  if (magics.empty()) {
    if (write_part(w->fp, 0, bytes, static_cast<uint32_t>(len)) != 0)
      return -1;
    return start;
  }
  uint64_t begin = 0;
  for (size_t k = 0; k < magics.size(); ++k) {
    uint32_t cflag = (k == 0) ? 1u : 2u;
    if (write_part(w->fp, cflag, bytes + begin,
                   static_cast<uint32_t>(magics[k] - begin)) != 0)
      return -1;
    begin = magics[k] + 4;  // the dropped magic: restored by the reader
  }
  if (write_part(w->fp, 3, bytes + begin,
                 static_cast<uint32_t>(len - begin)) != 0)
    return -1;
  return start;
}

int rio_writer_close(void *h) {
  delete static_cast<Writer *>(h);
  return 0;
}

// ---------------------------------------------------------------- reader --
void *rio_reader_open(const char *path) {
  Reader *r = new Reader(path);
  if (!r->fp) {
    set_error(std::string("cannot open for read: ") + path);
    delete r;
    return nullptr;
  }
  return r;
}

int rio_reader_seek(void *h, int64_t offset) {
  Reader *r = static_cast<Reader *>(h);
  if (std::fseek(r->fp, static_cast<long>(offset), SEEK_SET) != 0) {
    set_error("seek failed");
    return -1;
  }
  return 0;
}

int64_t rio_reader_tell(void *h) {
  return static_cast<int64_t>(std::ftell(static_cast<Reader *>(h)->fp));
}

// Next whole (reassembled) record. 1 ok (data/len valid until next call),
// 0 EOF, -1 error.
int rio_reader_next(void *h, const void **data, uint64_t *len) {
  Reader *r = static_cast<Reader *>(h);
  r->buf.clear();
  uint32_t cflag = 0;
  int rc = read_chunk(r->fp, &r->buf, &cflag);
  if (rc <= 0) return rc;
  if (cflag == 1) {  // split record: keep consuming until the closing part
    for (;;) {
      // the writer dropped the embedded magic at each split point; the
      // continuation's header magic stands in for it — restore it here
      const uint8_t *m = reinterpret_cast<const uint8_t *>(&kMagic);
      r->buf.insert(r->buf.end(), m, m + 4);
      rc = read_chunk(r->fp, &r->buf, &cflag);
      if (rc <= 0) {
        set_error("unterminated split record");
        return -1;
      }
      if (cflag == 3) break;
      if (cflag != 2) {
        set_error("corrupt split-record chain");
        return -1;
      }
    }
  } else if (cflag != 0) {
    set_error("unexpected continuation frame");
    return -1;
  }
  *data = r->buf.data();
  *len = r->buf.size();
  return 1;
}

int rio_reader_close(void *h) {
  delete static_cast<Reader *>(h);
  return 0;
}

// ------------------------------------------------------------------ index --
// Scans the file and returns every record's start offset (caller frees via
// rio_free). Returns record count, or -1 on error.
int64_t rio_build_index(const char *path, int64_t **offsets_out) {
  Reader r(path);
  if (!r.fp) {
    set_error(std::string("cannot open: ") + path);
    return -1;
  }
  std::vector<int64_t> offsets;
  std::vector<uint8_t> scratch;
  for (;;) {
    int64_t pos = std::ftell(r.fp);
    scratch.clear();
    uint32_t cflag = 0;
    int rc = read_chunk(r.fp, &scratch, &cflag);
    if (rc == 0) break;
    if (rc < 0) return -1;
    if (cflag == 0) {
      offsets.push_back(pos);
    } else if (cflag == 1) {
      offsets.push_back(pos);
      for (;;) {
        scratch.clear();
        rc = read_chunk(r.fp, &scratch, &cflag);
        if (rc <= 0) {
          set_error("unterminated split record");
          return -1;
        }
        if (cflag == 3) break;
      }
    } else {
      set_error("index scan hit continuation frame out of sequence");
      return -1;
    }
  }
  auto *arr = static_cast<int64_t *>(
      std::malloc(sizeof(int64_t) * (offsets.empty() ? 1 : offsets.size())));
  std::memcpy(arr, offsets.data(), sizeof(int64_t) * offsets.size());
  *offsets_out = arr;
  return static_cast<int64_t>(offsets.size());
}

void rio_free(void *p) { std::free(p); }

}  // extern "C"
