#!/usr/bin/env python
"""mxlint — static program-analysis lint over the framework's canonical
compiled programs.

Builds the thirteen canonical programs on the current backend (``--smoke``
forces the 8-virtual-device CPU platform so the ring×TP and
expert-parallel MoE mesh programs exist on one box; the speculative
trio — draft_step / verify_step / decode_step_q — is driven by a real
mixed-length speculative serve, the paged pair — paged_decode_step /
paged_verify_step — by a real shared-prefix paged serve, ckpt_train_step
by a real fit under async fenced checkpointing, and moe_train_step by a
real top-2 capacity-routed MoE LM step whose explicit all-to-all
dispatch the collective pass budgets), snapshots each as a
:class:`~mxnet_tpu.analysis.artifact.ProgramArtifact` (jaxpr + lowered
StableHLO + compiled HLO + donation/retrace/dtype/cache metadata), and
runs the six analysis passes against the committed budget file:

==================  =====================================================
pass                invariant it pins
==================  =====================================================
donation            donated buffers alias in compiled input_output_alias
collective-budget   collective counts/bytes <= benchmarks/budgets.json
retrace             one jit trace per program shape (no cache-key drift)
host-sync           no host-callback primitives / host-transfer HLO ops
flop-dtype          dot_flops coverage; no f32 dots in bf16 programs
cache-bytes         decode KV-cache bytes <= ceiling; quantized configs
                    store narrow data planes
==================  =====================================================

Output follows the bench.py contract: ONE json line on stdout —
``{"metric": "mxlint_unsuppressed_findings", "value", "unit",
"vs_baseline", ...}`` — with per-finding detail json on stderr, one line
each.  Exit is nonzero when any unsuppressed *error* finding survives,
so CI fails on a dropped donation / budget overrun / retrace the same
way it fails on a broken test.

Workflow (docs/static_analysis.md):

* ``tools/mxlint.py --smoke``           — the tier-1 CI entry
  (tests/test_bench_contract.py invokes it);
* ``tools/mxlint.py --update-budgets``  — re-measure and rewrite the
  budget ceilings after an *intentional* sharding/collective change
  (preserves the file's suppressions list);
* ``tools/mxlint.py --programs decode_step --text``  — human-readable
  audit of a subset while iterating.

Suppressions: ``pass[:program[:code]]`` globs, from the budget file's
``suppressions`` list, ``MXNET_ANALYSIS_SUPPRESS``, or ``--suppress``.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

SMOKE = "--smoke" in sys.argv

# the virtual-device mesh must exist BEFORE jax initializes its backend
# (same dance as benchmarks/bench_long_context.py / tests/conftest.py)
if SMOKE:
    os.environ["JAX_PLATFORMS"] = "cpu"
if os.environ.get("JAX_PLATFORMS", "") == "cpu" and \
        "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()
if SMOKE:
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")
    try:
        _jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        pass


def _parse_args(argv):
    ap = argparse.ArgumentParser(
        prog="mxlint", description="static analysis over the canonical "
        "compiled programs (see docs/static_analysis.md)")
    ap.add_argument("--smoke", action="store_true",
                    help="tier-1 CI mode: force the 8-virtual-device CPU "
                    "platform and audit all twelve programs")
    ap.add_argument("--programs", default="",
                    help="comma-filter of canonical programs (default all)")
    ap.add_argument("--budgets", default="",
                    help="budget file path (default: MXNET_ANALYSIS_BUDGETS "
                    "or benchmarks/budgets.json)")
    ap.add_argument("--suppress", default="",
                    help="extra suppression patterns, comma-separated")
    ap.add_argument("--update-budgets", action="store_true",
                    help="rewrite the budget file's per-program collective "
                    "ceilings from this run's measurements and exit")
    ap.add_argument("--text", action="store_true",
                    help="human-readable report on stderr instead of "
                    "per-finding json lines")
    ap.add_argument("--list", action="store_true", dest="list_only",
                    help="list canonical programs and passes, then exit")
    return ap.parse_args(argv)


def main(argv=None):
    args = _parse_args(argv if argv is not None else sys.argv[1:])

    if args.smoke and not SMOKE:
        # platform forcing happens at import, keyed off sys.argv; a
        # programmatic main(["--smoke"]) after the backend initialized
        # cannot deliver the promised 8-device CPU audit — fail loudly
        # instead of silently skipping ring_tp_step
        import jax

        if jax.devices()[0].platform != "cpu" or len(jax.devices()) < 8:
            sys.exit("--smoke requires the 8-virtual-device CPU platform, "
                     "which must be forced before jax initializes: run "
                     "tools/mxlint.py as a script, not via main()")

    from mxnet_tpu import analysis
    from mxnet_tpu.analysis.hlo_parse import collective_stats
    from mxnet_tpu.programs import registry as progreg
    import mxnet_tpu.analysis.programs  # noqa: F401 — registers the
    # canonical builder groups with the program registry; --list,
    # --programs and the audit below all enumerate the registry
    import bench as _bench

    if args.list_only:
        for name in progreg.canonical_names():
            print("program:", name)
        for p in analysis.default_passes():
            print("pass:", p.name)
        return 0

    names = [n for n in args.programs.split(",") if n] or None
    artifacts, notes = progreg.build_canonical(names)
    for prog, reason in notes.items():
        print(json.dumps({"skipped_program": prog, "reason": reason}),
              file=sys.stderr)

    budgets_path = args.budgets or None
    budgets = analysis.load_budgets(budgets_path)

    if args.update_budgets:
        # same resolution as the read above — reads and writes must agree
        path = analysis.resolve_budgets_path(budgets_path)
        programs = budgets.setdefault("programs", {})
        for art in artifacts:
            if art.meta.get("cache_bytes") is not None:
                programs.setdefault(art.name, {})["cache_bytes"] = \
                    art.meta["cache_bytes"]
            if art.compiled_text is None:
                continue
            stats = collective_stats(art.compiled_text)
            ceilings = {op: dict(v) for op, v in stats.items()
                        if op != "overlappable"}
            programs.setdefault(art.name, {})["collectives"] = ceilings
        with open(path, "w") as f:
            json.dump(budgets, f, indent=2, sort_keys=True)
            f.write("\n")
        print(json.dumps({"updated": os.path.relpath(path),
                          "programs": sorted(p for p in programs)}),
              file=sys.stderr)
        return 0

    report = analysis.run_passes(artifacts, budgets=budgets,
                                 suppressions=args.suppress)
    if args.text:
        print(report.format_text(), file=sys.stderr)
    else:
        for f in report.findings:
            print(json.dumps(f.to_dict()), file=sys.stderr)

    s = report.summary()
    unsup = len(report.unsuppressed)
    print(_bench.contract_line(
        "mxlint_unsuppressed_findings", unsup, "findings",
        1.0 if unsup == 0 else 0.0,
        errors=s["errors"], warnings=s["warnings"],
        suppressed=s["suppressed"], programs=s["programs"],
        passes=s["passes"], skipped_programs=sorted(notes)))
    return 1 if report.errors else 0


if __name__ == "__main__":
    sys.exit(main())
