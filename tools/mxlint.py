#!/usr/bin/env python
"""mxlint — static program-analysis lint over the framework's canonical
compiled programs.

Builds the thirteen canonical programs on the current backend (``--smoke``
forces the 8-virtual-device CPU platform so the ring×TP and
expert-parallel MoE mesh programs exist on one box; the speculative
trio — draft_step / verify_step / decode_step_q — is driven by a real
mixed-length speculative serve, the paged pair — paged_decode_step /
paged_verify_step — by a real shared-prefix paged serve, ckpt_train_step
by a real fit under async fenced checkpointing, and moe_train_step by a
real top-2 capacity-routed MoE LM step whose explicit all-to-all
dispatch the collective pass budgets), snapshots each as a
:class:`~mxnet_tpu.analysis.artifact.ProgramArtifact` (jaxpr + lowered
StableHLO + compiled HLO + donation/retrace/dtype/cache metadata), and
runs the ten analysis passes against the committed budget file:

==================  =====================================================
pass                invariant it pins
==================  =====================================================
donation            donated buffers alias in compiled input_output_alias
collective-budget   collective counts/bytes <= benchmarks/budgets.json
retrace             one jit trace per program shape (no cache-key drift)
host-sync           no host-callback primitives / host-transfer HLO ops
flop-dtype          dot_flops coverage; no f32 dots in bf16 programs
cache-bytes         decode KV-cache bytes <= ceiling; quantized configs
                    store narrow data planes
tuner-coverage      Pallas block/split constants registered with the
                    autotuner (no dead hand-tuned shapes)
schedule            async -start/-done pairs matched; compute shadows
                    above the per-program ``overlap`` floors
sharding-coverage   every bound param resolves to a rule match or an
                    INTENTIONAL replicate; silent degrades are errors
drift               priced quantities (FLOPs, collective/cache bytes,
                    donation map) vs a recorded snapshot (``--check``)
==================  =====================================================

Output follows the bench.py contract: ONE json line on stdout —
``{"metric": "mxlint_unsuppressed_findings", "value", "unit",
"vs_baseline", ...}`` — with per-finding detail on stderr in the
``--format`` of choice (default ``jsonl``: one json object per line).

Exit-code contract (unit-tested in tests/test_analysis.py):

* **0** — clean, or info-only findings (info never fails a run);
* **1** — at least one unsuppressed *error* finding survived;
* **2** — usage / input error (unknown flag, unreadable or
  hash-mismatched ``--check`` snapshot), the argparse convention.

Workflow (docs/static_analysis.md):

* ``tools/mxlint.py --smoke``           — the tier-1 CI entry
  (tests/test_bench_contract.py invokes it, with ``--check`` against
  the committed ``benchmarks/mxlint_snapshot.json``);
* ``tools/mxlint.py --update-budgets``  — re-measure and rewrite the
  budget ceilings after an *intentional* sharding/collective change
  (preserves the file's suppressions list);
* ``tools/mxlint.py --smoke --record benchmarks/mxlint_snapshot.json``
  — re-record the drift baseline after an intentional perf change;
* ``tools/mxlint.py --smoke --check benchmarks/mxlint_snapshot.json``
  — the differential gate: a PR that regresses a priced quantity
  beyond tolerance fails here, naming the program and the quantity;
* ``tools/mxlint.py --programs decode_step --format text``  —
  human-readable audit of a subset while iterating;
* ``tools/mxlint.py --smoke --format github`` — CI annotations
  (``::error file=...``) on stderr for unsuppressed findings.

Suppressions: ``pass[:program[:code]]`` globs, from the budget file's
``suppressions`` list, ``MXNET_ANALYSIS_SUPPRESS``, or ``--suppress``.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

SMOKE = "--smoke" in sys.argv

# the virtual-device mesh must exist BEFORE jax initializes its backend
# (same dance as benchmarks/bench_long_context.py / tests/conftest.py)
if SMOKE:
    os.environ["JAX_PLATFORMS"] = "cpu"
if os.environ.get("JAX_PLATFORMS", "") == "cpu" and \
        "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()
if SMOKE:
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")
    try:
        _jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        pass


def _parse_args(argv):
    ap = argparse.ArgumentParser(
        prog="mxlint", description="static analysis over the canonical "
        "compiled programs (see docs/static_analysis.md)")
    ap.add_argument("--smoke", action="store_true",
                    help="tier-1 CI mode: force the 8-virtual-device CPU "
                    "platform and audit all thirteen programs")
    ap.add_argument("--programs", default="",
                    help="comma-filter of canonical programs (default all)")
    ap.add_argument("--budgets", default="",
                    help="budget file path (default: MXNET_ANALYSIS_BUDGETS "
                    "or benchmarks/budgets.json)")
    ap.add_argument("--suppress", default="",
                    help="extra suppression patterns, comma-separated")
    ap.add_argument("--update-budgets", action="store_true",
                    help="rewrite the budget file's per-program collective "
                    "ceilings from this run's measurements and exit")
    ap.add_argument("--record", default="", metavar="PATH",
                    help="write a content-addressed drift snapshot of this "
                    "run's priced quantities to PATH (the --check baseline)")
    ap.add_argument("--check", default="", metavar="PATH",
                    help="load a drift snapshot and arm the drift pass: a "
                    "priced quantity regressing beyond its tolerance is an "
                    "error naming the program and quantity")
    ap.add_argument("--format", default="", dest="fmt",
                    choices=("jsonl", "json", "github", "text"),
                    help="stderr finding format: jsonl (default; one json "
                    "object per line), json (one report document), github "
                    "(::error/::warning workflow annotations for "
                    "unsuppressed findings), text (human-readable)")
    ap.add_argument("--text", action="store_true",
                    help="alias for --format text")
    ap.add_argument("--list", action="store_true", dest="list_only",
                    help="list canonical programs and passes, then exit")
    args = ap.parse_args(argv)
    if not args.fmt:
        args.fmt = "text" if args.text else "jsonl"
    return args


def format_github(report, file="benchmarks/budgets.json"):
    """GitHub workflow-command annotation lines for every unsuppressed
    error/warning finding (info rows are advisory and stay off the PR).
    ``file`` anchors the annotation — findings describe compiled
    programs, not source lines, so the budget file (where the waiver or
    ceiling would change) is the natural place to hang them."""
    lines = []
    for f in report.unsuppressed:
        title = "%s(%s)%s" % (f.pass_name, f.program,
                              ":" + f.code if f.code else "")
        # workflow-command escaping: %, CR, LF in the data
        msg = (f.message.replace("%", "%25").replace("\r", "%0D")
               .replace("\n", "%0A"))
        lines.append("::%s file=%s,line=1,title=%s::%s"
                     % (f.severity, file, title, msg))
    return lines


def _exit_code(report):
    """The documented contract: 0 clean/info-only, 1 on unsuppressed
    errors (usage/input failures exit 2 before a report exists)."""
    return 1 if report.errors else 0


def main(argv=None):
    args = _parse_args(argv if argv is not None else sys.argv[1:])

    if args.smoke and not SMOKE:
        # platform forcing happens at import, keyed off sys.argv; a
        # programmatic main(["--smoke"]) after the backend initialized
        # cannot deliver the promised 8-device CPU audit — fail loudly
        # instead of silently skipping ring_tp_step
        import jax

        if jax.devices()[0].platform != "cpu" or len(jax.devices()) < 8:
            sys.exit("--smoke requires the 8-virtual-device CPU platform, "
                     "which must be forced before jax initializes: run "
                     "tools/mxlint.py as a script, not via main()")

    from mxnet_tpu import analysis
    from mxnet_tpu.analysis.hlo_parse import collective_stats
    from mxnet_tpu.analysis.schedule import parse_schedule
    from mxnet_tpu.programs import registry as progreg
    import mxnet_tpu.analysis.programs  # noqa: F401 — registers the
    # canonical builder groups with the program registry; --list,
    # --programs and the audit below all enumerate the registry
    import bench as _bench

    if args.list_only:
        for name in progreg.canonical_names():
            print("program:", name)
        for p in analysis.default_passes():
            print("pass:", p.name)
        return 0

    snapshot = None
    if args.check:
        try:
            snapshot = analysis.load_snapshot(args.check)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print("mxlint: --check: %s" % e, file=sys.stderr)
            return 2

    names = [n for n in args.programs.split(",") if n] or None
    artifacts, notes = progreg.build_canonical(names)
    for prog, reason in notes.items():
        print(json.dumps({"skipped_program": prog, "reason": reason}),
              file=sys.stderr)

    budgets_path = args.budgets or None
    budgets = analysis.load_budgets(budgets_path)

    if args.update_budgets:
        # same resolution as the read above — reads and writes must agree
        path = analysis.resolve_budgets_path(budgets_path)
        programs = budgets.setdefault("programs", {})
        for art in artifacts:
            if art.meta.get("cache_bytes") is not None:
                programs.setdefault(art.name, {})["cache_bytes"] = \
                    art.meta["cache_bytes"]
            if art.compiled_text is None:
                continue
            stats = collective_stats(art.compiled_text)
            ceilings = {op: dict(v) for op, v in stats.items()
                        if op != "overlappable"}
            programs.setdefault(art.name, {})["collectives"] = ceilings
        with open(path, "w") as f:
            json.dump(budgets, f, indent=2, sort_keys=True)
            f.write("\n")
        print(json.dumps({"updated": os.path.relpath(path),
                          "programs": sorted(p for p in programs)}),
              file=sys.stderr)
        return 0

    report = analysis.run_passes(artifacts, budgets=budgets,
                                 suppressions=args.suppress,
                                 snapshot=snapshot)

    if args.record:
        snap = analysis.record_snapshot(artifacts, report)
        with open(args.record, "w") as f:
            json.dump(snap, f, indent=2, sort_keys=True)
            f.write("\n")
        print(json.dumps({"recorded": args.record,
                          "programs": sorted(snap["programs"]),
                          "content_hash": snap["content_hash"]}),
              file=sys.stderr)

    if args.fmt == "text":
        print(report.format_text(), file=sys.stderr)
    elif args.fmt == "json":
        print(report.to_json(), file=sys.stderr)
    elif args.fmt == "github":
        for line in format_github(report):
            print(line, file=sys.stderr)
    else:
        for f in report.findings:
            print(json.dumps(f.to_dict()), file=sys.stderr)

    # schedule/drift aggregates for the bench contract line — mxstat
    # --diff flattens these, so overlap structure and drift state ride
    # the same trend lines as the byte ceilings
    sched = {"pairs": 0, "unpaired": 0, "serialized": 0}
    for art in artifacts:
        if art.compiled_text is not None:
            s = parse_schedule(art.compiled_text).summary()
            for k in sched:
                sched[k] += s[k]
    drifted = sum(1 for f in report.findings
                  if f.pass_name == "drift"
                  and f.code.startswith("drift:") and not f.suppressed)

    s = report.summary()
    unsup = len(report.unsuppressed)
    print(_bench.contract_line(
        "mxlint_unsuppressed_findings", unsup, "findings",
        1.0 if unsup == 0 else 0.0,
        errors=s["errors"], warnings=s["warnings"],
        suppressed=s["suppressed"], programs=s["programs"],
        passes=s["passes"], skipped_programs=sorted(notes),
        schedule_pairs=sched["pairs"],
        schedule_unpaired=sched["unpaired"],
        schedule_serialized=sched["serialized"],
        drift_checked=len(artifacts) if snapshot is not None else 0,
        drifted=drifted))
    return _exit_code(report)


if __name__ == "__main__":
    sys.exit(main())
