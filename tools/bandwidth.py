#!/usr/bin/env python
"""Communication bandwidth probe — `tools/bandwidth/measure.py` analog.

The reference measures ps-lite push/pull cost per batch; the TPU analog
measures what actually moves bytes here:

* host -> device transfer (infeed) bandwidth,
* device-to-device all-reduce (psum over the 'data' mesh axis — rides ICI
  on a real multi-chip mesh, shared memory on the virtual CPU mesh),
* all-gather over the same axis.

Run:  python tools/bandwidth.py [--devices N] [--sizes MB,MB,...]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _time(fn, *args, iters=5):
    import jax

    fn(*args)                      # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=0,
                    help="force an N-device virtual CPU mesh (0 = real)")
    ap.add_argument("--sizes", default="1,16,64,256",
                    help="payload sizes in MiB")
    args = ap.parse_args()

    import jax

    if args.devices:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.devices)

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devices = jax.devices()
    n = len(devices)
    mesh = Mesh(np.array(devices), ("data",))
    rep = NamedSharding(mesh, P())
    shard = NamedSharding(mesh, P("data"))
    print("devices: %d x %s" % (n, devices[0].device_kind), flush=True)

    sizes_mb = [float(s) for s in args.sizes.split(",")]
    for mb in sizes_mb:
        elems = int(mb * 2 ** 20 / 4)
        elems -= elems % max(n, 1)
        host = np.random.RandomState(0).rand(elems).astype(np.float32)
        nbytes = host.nbytes

        # host -> device
        t = _time(lambda h: jax.device_put(h, devices[0]), host)
        h2d = nbytes / t / 1e9

        # all-reduce: sharded input, psum'd (replicated) output
        @jax.jit
        def allreduce(x):
            return jax.lax.with_sharding_constraint(
                x * 1.0, rep)

        x = jax.device_put(host, shard)
        t = _time(allreduce, x)
        ar = nbytes / t / 1e9

        # all-gather: sharded -> replicated concat
        @jax.jit
        def allgather(x):
            return jax.lax.with_sharding_constraint(x, rep)

        t = _time(allgather, x)
        ag = nbytes / t / 1e9

        print("%8.1f MiB | h2d %7.2f GB/s | all-reduce %7.2f GB/s | "
              "all-gather %7.2f GB/s" % (mb, h2d, ar, ag), flush=True)


if __name__ == "__main__":
    main()
