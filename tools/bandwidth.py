#!/usr/bin/env python
"""Communication bandwidth probe — `tools/bandwidth/measure.py` analog.

The reference measures ps-lite push/pull cost per batch; the TPU analog
measures what actually moves bytes here:

* host -> device transfer (infeed) bandwidth,
* device-to-device all-reduce (psum over the 'data' mesh axis — rides ICI
  on a real multi-chip mesh, shared memory on the virtual CPU mesh),
* all-gather over the same axis.

Run:  python tools/bandwidth.py [--devices N] [--sizes MB,MB,...]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _time(fn, *args, iters=5):
    import jax

    fn(*args)                      # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def _mlp_case(sym):
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=256, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=256, name="fc2")
    net = sym.SoftmaxOutput(net, name="softmax")
    return net, [("data", (16, 64))], [("softmax_label", (16,))], \
        (16, 64), 4


def _attention_lm_case(sym):
    vocab, e, t, b = 1024, 256, 32, 8
    data = sym.Variable("data")
    emb = sym.Embedding(data, input_dim=vocab, output_dim=e, name="embed")
    q = sym.FullyConnected(emb, num_hidden=e, flatten=False, name="q")
    k = sym.FullyConnected(emb, num_hidden=e, flatten=False, name="k")
    v = sym.FullyConnected(emb, num_hidden=e, flatten=False, name="v")
    att = sym.dot_product_attention(q, k, v, num_heads=8, causal=True)
    out = sym.FullyConnected(att, num_hidden=e, flatten=False, name="proj")
    net = sym.FullyConnected(out, num_hidden=64, name="head")
    net = sym.SoftmaxOutput(net, name="softmax")
    return net, [("data", (b, t))], [("softmax_label", (b,))], (b, t), 64


def _conv_pool_case(sym):
    data = sym.Variable("data")
    net = sym.Convolution(data, num_filter=32, kernel=(3, 3), pad=(1, 1),
                          name="conv1")
    net = sym.Activation(net, act_type="relu")
    net = sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = sym.Convolution(net, num_filter=32, kernel=(3, 3), pad=(1, 1),
                          name="conv2")
    net = sym.Activation(net, act_type="relu")
    net = sym.Pooling(net, global_pool=True, pool_type="avg",
                      kernel=(1, 1))
    net = sym.Flatten(net)
    net = sym.FullyConnected(net, num_hidden=8, name="fc")
    net = sym.SoftmaxOutput(net, name="softmax")
    return net, [("data", (8, 3, 16, 16))], [("softmax_label", (8,))], \
        (8, 3, 16, 16), 8


def model_step_report(n_model):
    """Static comm accounting for one tensor-parallel training step.

    Compiles train steps at model=n_model under both TP plans (megatron
    pairing vs naive dim-0) — a 2-layer MLP, an attention LM (QKV column /
    out-proj row over heads), and a conv+pooling net — and prints
    collective count + payload bytes from the optimized HLO: the XLA-era
    version of the reference's per-batch push/pull cost table.
    """
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import config as _config
    from mxnet_tpu import ndarray as nd
    from mxnet_tpu import symbol as sym
    from mxnet_tpu.io import DataBatch
    from mxnet_tpu.parallel import MeshConfig
    from mxnet_tpu.parallel.hlo_stats import collective_stats

    def step_stats(case, mode):
        os.environ["MXNET_TP_MODE"] = mode
        _config.refresh("MXNET_TP_MODE")
        net, data_shapes, label_shapes, data_shape, classes = case(sym)
        mod = mx.mod.Module(net, context=[mx.cpu(i) for i in range(n_model)],
                            mesh_config=MeshConfig(data=1, model=n_model))
        mod.bind(data_shapes=data_shapes, label_shapes=label_shapes)
        mod.init_params(mx.initializer.Xavier())
        rng = np.random.RandomState(0)
        if case is _attention_lm_case:
            x = rng.randint(0, 1024, data_shape).astype(np.float32)
        else:
            x = rng.randn(*data_shape).astype(np.float32)
        y = rng.randint(0, classes, data_shape[0]).astype(np.float32)
        batch = DataBatch([nd.array(x)], [nd.array(y)])
        mod.forward(batch, is_train=True)
        mod.backward()
        hlo = mod._exec_group.exec_.compiled_hlo()
        if hlo is None:
            raise SystemExit("step ran eagerly (MXNET_ENGINE_TYPE=NaiveEngine"
                             " or group2ctx placement?) — no compiled HLO to"
                             " account; unset the eager knobs and retry")
        return collective_stats(hlo)

    for case, label in ((_mlp_case, "mlp"),
                        (_attention_lm_case, "attention_lm"),
                        (_conv_pool_case, "conv_pool")):
        for mode in ("megatron", "naive"):
            st = step_stats(case, mode)
            print("%-13s TP plan %-9s: %3d collectives, %8.1f KiB/step "
                  "moved (%.1f KiB async-overlappable)"
                  % (label, mode, st["total"]["count"],
                     st["total"]["bytes"] / 1024,
                     st["overlappable"]["bytes"] / 1024), flush=True)
            for op, e in sorted(st.items()):
                if op not in ("total", "overlappable"):
                    print("    %-19s x%-3d %8.1f KiB" %
                          (op, e["count"], e["bytes"] / 1024), flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=0,
                    help="force an N-device virtual CPU mesh (0 = real)")
    ap.add_argument("--sizes", default="1,16,64,256",
                    help="payload sizes in MiB")
    ap.add_argument("--model-step", type=int, default=0, metavar="N",
                    help="also report per-step collective count/bytes of a "
                         "2-layer MLP at tensor-parallel degree N "
                         "(megatron vs naive plan)")
    args = ap.parse_args()

    import jax

    if args.devices:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.devices)

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devices = jax.devices()
    n = len(devices)
    mesh = Mesh(np.array(devices), ("data",))
    rep = NamedSharding(mesh, P())
    shard = NamedSharding(mesh, P("data"))
    print("devices: %d x %s" % (n, devices[0].device_kind), flush=True)

    sizes_mb = [float(s) for s in args.sizes.split(",")]
    for mb in sizes_mb:
        elems = int(mb * 2 ** 20 / 4)
        elems -= elems % max(n, 1)
        host = np.random.RandomState(0).rand(elems).astype(np.float32)
        nbytes = host.nbytes

        # host -> device
        t = _time(lambda h: jax.device_put(h, devices[0]), host)
        h2d = nbytes / t / 1e9

        # all-reduce: sharded input, psum'd (replicated) output
        @jax.jit
        def allreduce(x):
            return jax.lax.with_sharding_constraint(
                x * 1.0, rep)

        x = jax.device_put(host, shard)
        t = _time(allreduce, x)
        ar = nbytes / t / 1e9

        # all-gather: sharded -> replicated concat
        @jax.jit
        def allgather(x):
            return jax.lax.with_sharding_constraint(x, rep)

        t = _time(allgather, x)
        ag = nbytes / t / 1e9

        print("%8.1f MiB | h2d %7.2f GB/s | all-reduce %7.2f GB/s | "
              "all-gather %7.2f GB/s" % (mb, h2d, ar, ag), flush=True)

    if args.model_step:
        model_step_report(args.model_step)


if __name__ == "__main__":
    main()
