#!/usr/bin/env python
"""mxstat — render and sanity-check the unified telemetry surfaces.

The CLI half of ``mxnet_tpu.obs`` (docs/observability.md): the per-program
MFU/roofline table the compiled-step dispatch wrappers accumulate
(``bench.py`` publishes it as the ``mfu_table`` field of its JSON
contract), the metrics-registry exporters (JSON-lines snapshot,
Prometheus text) and the Chrome-trace timeline export.

Usage:

* ``tools/mxstat.py BENCH.json``      — render the ``mfu_table`` found in
  a bench contract line (or any JSON object carrying one) as a text
  table; also accepts a file of JSON lines (the last line with an
  ``mfu_table`` wins, so ``bench.py --smoke > out.json`` pipes straight
  in).
* ``tools/mxstat.py --snapshot``      — print the current process-wide
  registry snapshot (mostly useful from an interactive session).
* ``tools/mxstat.py --diff A.json B.json`` — headline / MFU / bytes
  deltas between two bench JSON contracts (``BENCH_r*.json``): the
  headline metric's value, the aggregate byte-ish extras
  (``opt_update_bytes``, ``all_to_all_bytes``, ``dispatch_bytes``),
  the fleet headline fields (``bench_fleet.py``: ``p95_ttft_ms``,
  ``router_cache_hit_rate``, ``vs_round_robin``, migrated/swapped page
  counts, and the ``--cold-start`` contract's ``cold_start_s`` /
  ``cold_start_vs_jit`` / ``aot_*`` program-readiness fields) and a
  per-program join of the two ``mfu_table``s (bytes,
  flops, wall_s, mfu), with absolute and percent deltas — the perf
  trajectory across PRs as one readable table instead of two
  hand-diffed JSON blobs.
* ``tools/mxstat.py --smoke``         — tier-1 CI mode
  (tests/test_bench_contract.py invokes it): drive the registry /
  timeline / roofline machinery end to end WITHOUT jax — concurrent
  counter increments, a histogram cross-checked against numpy, a
  ring-bounded timeline exported and re-parsed as Chrome-trace JSON, a
  JSON-lines registry round-trip, a Prometheus-text render, and an MFU
  table built from synthetic timings + static costs — then emit ONE
  bench-contract JSON line on stdout (nonzero exit on any check
  failure).  The REAL pipeline (live compiled programs feeding the same
  table) is covered by ``bench.py --smoke``'s ``mfu_table`` contract;
  this smoke keeps the CLI and the exporters honest at near-zero cost.

Exit status: nonzero when a smoke check fails or no table is found.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _load_rows(path):
    """The last ``mfu_table`` found in a JSON file or JSON-lines file."""
    rows = None
    with open(path) as f:
        text = f.read()
    try:
        payloads = [json.loads(text)]
    except ValueError:
        payloads = []
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                payloads.append(json.loads(line))
            except ValueError:
                continue
    for obj in payloads:
        if isinstance(obj, dict):
            if isinstance(obj.get("mfu_table"), list):
                rows = obj["mfu_table"]
            elif obj.get("metric") and isinstance(obj.get("value"), list):
                rows = obj["value"]
    return rows


def _load_contract(path):
    """The last bench-contract object (has "metric" and "value") in a
    JSON or JSON-lines file; None when the file carries none."""
    with open(path) as f:
        text = f.read()
    try:
        payloads = [json.loads(text)]
    except ValueError:
        payloads = []
        for line in text.splitlines():
            line = line.strip()
            if line:
                try:
                    payloads.append(json.loads(line))
                except ValueError:
                    continue
    found = None
    for obj in payloads:
        if isinstance(obj, dict) and obj.get("metric") is not None \
                and "value" in obj:
            found = obj
    return found


def _delta_row(label, a, b):
    """One diff line: label, a, b, absolute delta, percent delta."""
    if not (isinstance(a, (int, float)) and isinstance(b, (int, float))):
        return [label, str(a), str(b), "-", "-"]
    d = b - a
    pct = ("%+.2f%%" % (100.0 * d / a)) if a else "-"
    fmt = "%+d" if isinstance(a, int) and isinstance(b, int) else "%+.4g"
    return [label, "%.6g" % a, "%.6g" % b, fmt % d, pct]


def _render_diff_table(rows):
    table = [["field", "a", "b", "delta", "pct"]] + rows
    widths = [max(len(r[i]) for r in table) for i in range(5)]
    lines = []
    for i, r in enumerate(table):
        lines.append("  ".join(c.rjust(w) if j else c.ljust(w)
                               for j, (c, w) in enumerate(zip(r, widths))))
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


_EXTRA_SUFFIXES = (".ratio", ".count", "_ms", "_rate", "_pages",
                   "_outs", "_prefills", "_tokens_per_sec",
                   "vs_round_robin",
                   # capacity headlines and the GQA contract
                   # (bench_decode.py): tokens/s/GB and the grouped-KV
                   # ratios; the gqa_*bytes* fields match the byte rule
                   "_per_gb", "_vs_mha", "gqa_group",
                   # the bench_fleet.py --cold-start contract: per-host
                   # program readiness, warm AOT cache vs trace+compile
                   "cold_start_s", "cold_start_jit_s", "cold_start_vs_jit",
                   "aot_hits", "aot_misses", "aot_fallbacks",
                   "programs_loaded",
                   # the mxlint schedule/drift aggregates: async overlap
                   # structure and the differential gate's verdict ride
                   # the same trend lines as the byte ceilings
                   "_pairs", "_unpaired", "_serialized", "_shadow_flops",
                   "drift_checked", "drifted")


def _flatten_bytes_extras(obj, prefix=""):
    """The byte-ish / fleet-headline scalar extras of a contract line,
    flattened: opt_update_bytes.fused_bytes, dispatch_bytes.sort.bytes,
    p95_ttft_ms, router_cache_hit_rate, migrated_pages, ..."""
    out = {}
    for key, val in sorted((obj or {}).items()):
        if key in ("mfu_table",) or key.startswith("_"):
            continue
        name = prefix + key
        if isinstance(val, dict):
            out.update(_flatten_bytes_extras(val, name + "."))
        elif isinstance(val, (int, float)) and not isinstance(val, bool) \
                and ("bytes" in name
                     or name.endswith(_EXTRA_SUFFIXES)):
            out[name] = val
    return out


def diff(a_path, b_path, out=None):
    """Print headline/MFU/bytes deltas between two bench contracts.
    Returns 0, or 1 when either file carries no contract line."""
    out = out if out is not None else sys.stdout
    a = _load_contract(a_path)
    b = _load_contract(b_path)
    if a is None or b is None:
        print("no bench contract line found in %s"
              % (a_path if a is None else b_path), file=sys.stderr)
        return 1
    rows = []
    label = a["metric"] if a["metric"] == b["metric"] else \
        "%s -> %s" % (a["metric"], b["metric"])
    rows.append(_delta_row("headline: %s [%s]" % (label,
                                                  a.get("unit", "?")),
                           a.get("value"), b.get("value")))
    if a.get("vs_baseline") is not None \
            and b.get("vs_baseline") is not None:
        rows.append(_delta_row("vs_baseline", a["vs_baseline"],
                               b["vs_baseline"]))
    fa, fb = _flatten_bytes_extras(a), _flatten_bytes_extras(b)
    keys = sorted(set(fa) | set(fb))
    for k in keys:
        rows.append(_delta_row(k, fa.get(k, "-"), fb.get(k, "-")))
    # per-program mfu_table join
    ta = {r.get("program"): r for r in a.get("mfu_table") or []}
    tb = {r.get("program"): r for r in b.get("mfu_table") or []}
    for prog in sorted(set(ta) | set(tb)):
        ra, rb = ta.get(prog, {}), tb.get(prog, {})
        for col in ("bytes", "flops", "wall_s", "mfu",
                    "collective_bytes", "gather_bytes",
                    "sort_scatter_bytes"):
            va, vb = ra.get(col), rb.get(col)
            if va is None and vb is None:
                continue
            rows.append(_delta_row("%s.%s" % (prog, col),
                                   va if va is not None else "-",
                                   vb if vb is not None else "-"))
    print(_render_diff_table(rows), file=out)
    return 0


def smoke():
    """Synthetic end-to-end drive of the obs machinery (no jax)."""
    import tempfile
    import threading

    import numpy as np

    from mxnet_tpu.obs.metrics import MetricsRegistry
    from mxnet_tpu.obs.roofline import ProgramAccounting, render_mfu_table
    from mxnet_tpu.obs.trace import TraceTimeline

    checks = {}

    # 1. concurrent counter increments sum exactly
    reg = MetricsRegistry()
    c = reg.counter("mx_smoke_ops", "smoke increments", labels=("who",))
    nthreads, per = 8, 5000

    def worker(i):
        child = c.labels(who="t%d" % (i % 2))
        for _ in range(per):
            child.inc()

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(nthreads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = sum(row["value"]
                for row in reg.snapshot()["mx_smoke_ops"]["series"])
    checks["counter_sum"] = total == nthreads * per

    # 2. histogram percentiles match numpy on random data
    h = reg.histogram("mx_smoke_latency", "smoke latencies")
    rng = np.random.RandomState(0)
    vals = rng.exponential(0.05, 1000)
    for v in vals:
        h.observe(v)
    checks["histogram_numpy"] = all(
        abs(h.percentile(q) - float(np.percentile(vals, q * 100))) < 1e-12
        for q in (0.5, 0.9, 0.95, 0.99))

    # 3. exporter round-trips + prometheus text renders the same values
    with tempfile.TemporaryDirectory(prefix="mxstat_smoke_") as tmp:
        path = os.path.join(tmp, "metrics.jsonl")
        reg.export_jsonl(path)
        with open(path) as f:
            back = json.loads(f.readlines()[-1])
        checks["jsonl_roundtrip"] = (
            back["metrics"]["mx_smoke_latency"]["series"][0]["value"]
            ["count"] == len(vals) and back["metrics"] == reg.snapshot())
        prom = reg.prometheus_text()
        checks["prometheus_text"] = (
            "mx_smoke_latency_count 1000" in prom
            and "# TYPE mx_smoke_ops counter" in prom)

        # 4. ring-bounded timeline -> valid chrome-trace JSON
        tl = TraceTimeline(capacity=256)
        for i in range(1000):
            with tl.span("step", cat="loop", args={"i": i}):
                tl.instant("tick", args={"i": i})
        checks["ring_bound"] = len(tl) == 256 and tl.dropped == 2000 - 256
        trace_path = os.path.join(tmp, "trace.json")
        tl.export(trace_path)
        with open(trace_path) as f:
            payload = json.load(f)
        evs = payload.get("traceEvents", [])
        checks["chrome_schema"] = bool(evs) and all(
            isinstance(e["name"], str) and e["ph"] in ("X", "i")
            and isinstance(e["ts"], int) and "pid" in e and "tid" in e
            and (e["ph"] != "X" or e["dur"] >= 0)
            and (e["ph"] != "i" or e.get("s") in ("t", "p", "g"))
            for e in evs)

    # 5. --diff round-trip: two synthetic bench contracts through the
    # real loader + table (jax-free), checking the joined deltas land
    import io

    with tempfile.TemporaryDirectory(prefix="mxstat_diff_") as tmp:
        a_line = {"metric": "resnet50_train_imgs_per_sec_bs256",
                  "value": 2442.6, "unit": "img/s", "vs_baseline": 13.45,
                  "opt_update_bytes": {"per_param_bytes": 1200,
                                       "fused_bytes": 1200,
                                       "ratio": 1.0},
                  "schedule_pairs": 6, "schedule_serialized": 0,
                  "drift_checked": 13, "drifted": 0,
                  "mfu_table": [{"program": "train_step", "calls": 10,
                                 "wall_s": 1.0, "flops": 100,
                                 "bytes": 1000, "mfu": 0.15}]}
        b_line = {"metric": "resnet50_train_imgs_per_sec_bs256",
                  "value": 2520.9, "unit": "img/s", "vs_baseline": 13.89,
                  "opt_update_bytes": {"per_param_bytes": 1200,
                                       "fused_bytes": 540,
                                       "ratio": 0.45},
                  "schedule_pairs": 4, "schedule_serialized": 2,
                  "drift_checked": 13, "drifted": 1,
                  "mfu_table": [{"program": "train_step", "calls": 10,
                                 "wall_s": 0.9, "flops": 100,
                                 "bytes": 800, "mfu": 0.17}]}
        pa = os.path.join(tmp, "a.json")
        pb = os.path.join(tmp, "b.json")
        with open(pa, "w") as f:
            f.write("not json\n" + json.dumps(a_line) + "\n")
        with open(pb, "w") as f:
            f.write(json.dumps(b_line))
        buf = io.StringIO()
        rc = diff(pa, pb, out=buf)
        text = buf.getvalue()
        checks["diff_exit"] = rc == 0
        checks["diff_headline"] = "+78.3" in text and "+3.21%" in text
        checks["diff_bytes"] = "opt_update_bytes.fused_bytes" in text \
            and "-660" in text and "-55.00%" in text
        checks["diff_programs"] = "train_step.bytes" in text \
            and "-200" in text
        # the mxlint schedule/drift aggregates flatten like byte fields
        checks["diff_schedule"] = "schedule_pairs" in text \
            and "schedule_serialized" in text and "+2" in text
        checks["diff_drift"] = "drifted" in text and "drift_checked" in text
        checks["diff_missing"] = diff(pa, os.devnull,
                                      out=io.StringIO()) == 1

    # 6. the MFU table joins timings with static costs
    acc = ProgramAccounting()
    for _ in range(10):
        acc.note("train_step", 0.01)
    acc.note("decode_step", 0.002)
    acc.set_static("train_step", flops=2.5e9, bytes=1.2e8)
    acc.set_static("decode_step", flops=1e7, bytes=4e6)
    rows = acc.table(peak_flops=197e12)
    by_name = {r["program"]: r for r in rows}
    checks["mfu_rows"] = all(
        r["flops"] > 0 and r["bytes"] > 0 and r["wall_s"] > 0
        and r["mfu"] is not None and 0 <= r["mfu"] <= 1
        for r in rows) and set(by_name) == {"train_step", "decode_step"}
    print(render_mfu_table(rows), file=sys.stderr)

    import bench as _bench

    failed = sorted(k for k, ok in checks.items() if not ok)
    print(_bench.contract_line(
        "mxstat_smoke_checks", len(checks), "checks",
        1.0 if not failed else 0.0, failed=failed,
        programs=len(rows)))
    return 1 if failed else 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="mxstat", description="render the per-program MFU/roofline "
        "table and telemetry exports (see docs/observability.md)")
    ap.add_argument("file", nargs="?", default=None,
                    help="JSON (or JSON-lines) file carrying an mfu_table "
                    "field, e.g. bench.py --smoke output")
    ap.add_argument("--smoke", action="store_true",
                    help="tier-1 CI mode: drive the registry/timeline/"
                    "roofline machinery synthetically and self-check")
    ap.add_argument("--snapshot", action="store_true",
                    help="print the process-wide metrics snapshot as JSON")
    ap.add_argument("--diff", nargs=2, metavar=("A.json", "B.json"),
                    default=None,
                    help="print headline/MFU/bytes deltas between two "
                    "bench JSON contracts")
    args = ap.parse_args(argv if argv is not None else sys.argv[1:])

    if args.smoke:
        return smoke()
    if args.diff:
        return diff(args.diff[0], args.diff[1])
    if args.snapshot:
        from mxnet_tpu import obs

        print(json.dumps(obs.registry.snapshot(), indent=2))
        return 0
    if args.file is None:
        ap.print_help(sys.stderr)
        return 2
    rows = _load_rows(args.file)
    if not rows:
        print("no mfu_table found in %s" % args.file, file=sys.stderr)
        return 1
    from mxnet_tpu.obs.roofline import render_mfu_table

    print(render_mfu_table(rows))
    return 0


if __name__ == "__main__":
    sys.exit(main())
