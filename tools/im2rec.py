#!/usr/bin/env python
"""im2rec — pack an image directory / list into RecordIO (.rec + .idx).

TPU-framework analog of the reference's ``tools/im2rec.py``:

  1. list mode:   python tools/im2rec.py --list prefix image_root
     Walks image_root, assigns integer labels per subdirectory, writes
     ``prefix.lst`` lines of ``index\\tlabel\\trelative_path``.
  2. pack mode:   python tools/im2rec.py prefix image_root
     Reads ``prefix.lst`` and packs ``prefix.rec`` + ``prefix.idx`` through
     the native RecordIO writer (src/recordio.cc).  Images decode with cv2
     when available; without cv2 only ``.npy`` array files are packable
     (via the raw-array codec recordio.pack_img/unpack_img share) — other
     formats are skipped with a warning rather than written undecodably.
"""
import argparse
import os
import random
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

EXTS = {".jpg", ".jpeg", ".png", ".bmp", ".npy"}


def make_list(prefix, root, shuffle=True, train_ratio=1.0, seed=0):
    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    label_map = {c: i for i, c in enumerate(classes)}
    items = []
    if classes:
        for cls in classes:
            for dirpath, _, files in os.walk(os.path.join(root, cls)):
                for fname in sorted(files):
                    if os.path.splitext(fname)[1].lower() in EXTS:
                        rel = os.path.relpath(os.path.join(dirpath, fname),
                                              root)
                        items.append((label_map[cls], rel))
    else:  # flat directory: label 0
        for fname in sorted(os.listdir(root)):
            if os.path.splitext(fname)[1].lower() in EXTS:
                items.append((0, fname))
    if shuffle:
        random.Random(seed).shuffle(items)
    n_train = int(len(items) * train_ratio)
    splits = [("", items[:n_train])]
    if train_ratio < 1.0:
        splits = [("_train", items[:n_train]), ("_val", items[n_train:])]
    for suffix, split in splits:
        with open(prefix + suffix + ".lst", "w") as fout:
            for i, (label, rel) in enumerate(split):
                fout.write("%d\t%f\t%s\n" % (i, label, rel))
    print("wrote %d entries for %s" % (len(items), prefix))


def read_list(lst_path):
    with open(lst_path) as fin:
        for line in fin:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            idx = int(parts[0])
            labels = [float(x) for x in parts[1:-1]]
            yield idx, labels, parts[-1]


def pack_records(prefix, root, quality=95, resize=0, color=1):
    import numpy as np

    from mxnet_tpu import recordio

    try:
        import cv2
    except ImportError:
        cv2 = None

    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    count = 0
    for idx, labels, rel in read_list(prefix + ".lst"):
        path = os.path.join(root, rel)
        label = labels[0] if len(labels) == 1 else labels
        header = recordio.IRHeader(0, label, idx, 0)
        if path.endswith(".npy"):
            img = np.load(path)
        elif cv2 is not None:
            img = cv2.imread(path, color)
            if img is None:
                print("skipping unreadable %s" % path, file=sys.stderr)
                continue
            if resize:
                h, w = img.shape[:2]
                scale = resize / min(h, w)
                img = cv2.resize(img, (int(w * scale), int(h * scale)))
        else:
            print("skipping %s: no cv2 to decode it (use .npy inputs for "
                  "the cv2-free path)" % path, file=sys.stderr)
            continue
        payload = recordio.pack_img(header, img, quality=quality)
        rec.write_idx(idx, payload)
        count += 1
    rec.close()
    print("packed %d records into %s.rec" % (count, prefix))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("prefix", help="output prefix (prefix.lst/.rec/.idx)")
    ap.add_argument("root", help="image root directory")
    ap.add_argument("--list", action="store_true",
                    help="generate prefix.lst instead of packing")
    ap.add_argument("--no-shuffle", action="store_true")
    ap.add_argument("--train-ratio", type=float, default=1.0)
    ap.add_argument("--quality", type=int, default=95)
    ap.add_argument("--resize", type=int, default=0)
    ap.add_argument("--color", type=int, default=1, choices=[-1, 0, 1])
    args = ap.parse_args()
    if args.list:
        make_list(args.prefix, args.root, shuffle=not args.no_shuffle,
                  train_ratio=args.train_ratio)
    else:
        pack_records(args.prefix, args.root, quality=args.quality,
                     resize=args.resize, color=args.color)


if __name__ == "__main__":
    main()
