#!/usr/bin/env python
"""Benchmark: ResNet-50 ImageNet-shape training throughput on one TPU chip.

Mirrors the reference's headline benchmark
(`example/image-classification/train_imagenet.py --benchmark 1`, bs32 —
BASELINE.md: 181.53 img/s on P100).  Synthetic data (as --benchmark 1 uses),
full training step: forward + backward through the jitted executor +
SGD-momentum update.

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""
import json
import os
import sys
import time

import numpy as np

BASELINE_IMG_S = 181.53  # ResNet-50 train bs32, P100 (docs/how_to/perf.md:188)


def main():
    import mxnet_tpu as mx
    from mxnet_tpu.models import resnet
    from mxnet_tpu.io import DataBatch
    from mxnet_tpu import ndarray as nd

    batch_size = int(os.environ.get("BENCH_BATCH", "64"))
    n_iters = int(os.environ.get("BENCH_ITERS", "20"))
    warmup = 5

    import jax

    platform = jax.devices()[0].platform
    ctx = mx.tpu() if platform != "cpu" else mx.cpu()

    net = resnet.get_symbol(num_classes=1000, num_layers=50,
                            image_shape=(3, 224, 224))
    mod = mx.mod.Module(net, context=ctx)
    mod.bind(data_shapes=[("data", (batch_size, 3, 224, 224))],
             label_shapes=[("softmax_label", (batch_size,))])
    mod.init_params(mx.initializer.Xavier(rnd_type="gaussian",
                                          factor_type="in", magnitude=2))
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                                         "wd": 1e-4})

    rng = np.random.RandomState(0)
    x = nd.array(rng.uniform(-1, 1, (batch_size, 3, 224, 224)).astype(np.float32),
                 ctx=ctx)
    y = nd.array(rng.randint(0, 1000, (batch_size,)).astype(np.float32), ctx=ctx)
    batch = DataBatch([x], [y])

    def sync():
        # on the tunneled TPU platform block_until_ready can return early;
        # fetching a value derived from the last update is a reliable fence
        import jax.numpy as jnp

        return float(jnp.sum(mod._exec_group.param_arrays[-1].data))

    for _ in range(warmup):
        mod.forward_backward(batch)
        mod.update()
    sync()

    tic = time.time()
    for _ in range(n_iters):
        mod.forward_backward(batch)
        mod.update()
    sync()
    toc = time.time()

    img_s = batch_size * n_iters / (toc - tic)
    print(json.dumps({
        "metric": "resnet50_train_imgs_per_sec_bs%d" % batch_size,
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
    }))


if __name__ == "__main__":
    main()
