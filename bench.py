#!/usr/bin/env python
"""Benchmark: ResNet-50 ImageNet-shape training throughput on one TPU chip.

Mirrors the reference's headline benchmark
(`example/image-classification/train_imagenet.py --benchmark 1` —
BASELINE.md: 181.53 img/s on P100).  Synthetic data (as --benchmark 1 uses),
full training step: forward + backward + SGD-momentum update, compiled as
ONE donated XLA program (bf16 compute, fp32 master weights) — see
mxnet_tpu/train_step.py.

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"} plus
sustained TFLOP/s and MFU on stderr.
"""
import json
import os
import sys
import time

import numpy as np

BASELINE_IMG_S = 181.53  # ResNet-50 train bs32, P100 (docs/how_to/perf.md:188)

# fwd-pass FLOPs for ResNet-50 at 224x224 (2 * MACs); backward ~= 2x forward
RESNET50_FWD_FLOPS = 4.1e9
TRAIN_FLOPS_PER_IMG = 3 * RESNET50_FWD_FLOPS

# peak bf16 FLOP/s per chip by TPU generation (public spec sheets)
PEAK_FLOPS = {
    "TPU v2": 45e12 / 2,      # per-chip: 2 cores, 22.5T each
    "TPU v3": 123e12 / 2,
    "TPU v4": 275e12,
    "TPU v5e": 197e12,
    "TPU v5 lite": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 459e12,
    "TPU v6e": 918e12,
    "TPU v6 lite": 918e12,
    "TPU7x": 2307e12,
}


def _peak_for(device):
    kind = getattr(device, "device_kind", "")
    for name, peak in PEAK_FLOPS.items():
        if kind.lower().startswith(name.lower()):
            return peak, kind
    return None, kind


def main():
    import mxnet_tpu as mx
    from mxnet_tpu.models import resnet
    from mxnet_tpu.io import DataBatch
    from mxnet_tpu import ndarray as nd

    batch_size = int(os.environ.get("BENCH_BATCH", "256"))
    n_iters = int(os.environ.get("BENCH_ITERS", "20"))
    dtype = os.environ.get("BENCH_DTYPE", "bfloat16")
    warmup = 5

    import jax

    platform = jax.devices()[0].platform
    ctx = mx.tpu() if platform != "cpu" else mx.cpu()
    if platform == "cpu":
        batch_size = int(os.environ.get("BENCH_BATCH", "8"))
        n_iters = 3
        warmup = 1

    net = resnet.get_symbol(num_classes=1000, num_layers=50,
                            image_shape=(3, 224, 224))
    mod = mx.mod.Module(net, context=ctx, compute_dtype=dtype)
    mod.bind(data_shapes=[("data", (batch_size, 3, 224, 224))],
             label_shapes=[("softmax_label", (batch_size,))])
    mod.init_params(mx.initializer.Xavier(rnd_type="gaussian",
                                          factor_type="in", magnitude=2))
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                                         "wd": 1e-4})
    if mod._fused_step is None:
        print("WARNING: fused train step not active", file=sys.stderr)

    rng = np.random.RandomState(0)
    x = nd.array(rng.uniform(-1, 1, (batch_size, 3, 224, 224)).astype(np.float32),
                 ctx=ctx)
    y = nd.array(rng.randint(0, 1000, (batch_size,)).astype(np.float32), ctx=ctx)
    batch = DataBatch([x], [y])

    def sync():
        # on the tunneled TPU platform block_until_ready can return early;
        # fetching a value derived from the last update is a reliable fence
        import jax.numpy as jnp

        if mod._fused_step is not None:
            src = next(iter(mod._fused_step.params.values()))
        else:
            src = mod._exec_group.param_arrays[-1].data
        return float(jnp.sum(src.astype(jnp.float32)))

    for _ in range(warmup):
        mod.forward_backward(batch)
        mod.update()
    sync()

    tic = time.time()
    for _ in range(n_iters):
        mod.forward_backward(batch)
        mod.update()
    sync()
    toc = time.time()

    img_s = batch_size * n_iters / (toc - tic)
    tflops = img_s * TRAIN_FLOPS_PER_IMG / 1e12
    peak, kind = _peak_for(jax.devices()[0])
    mfu = tflops * 1e12 / peak if peak else None
    print(json.dumps({
        "device": kind, "dtype": dtype, "batch": batch_size,
        "sustained_tflops": round(tflops, 2),
        "mfu": round(mfu, 4) if mfu is not None else None,
    }), file=sys.stderr)
    print(json.dumps({
        "metric": "resnet50_train_imgs_per_sec_bs%d" % batch_size,
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
    }))


if __name__ == "__main__":
    main()
