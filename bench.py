#!/usr/bin/env python
"""Benchmark: ResNet-50 ImageNet-shape training throughput on one TPU chip.

Mirrors the reference's headline benchmark
(`example/image-classification/train_imagenet.py --benchmark 1` —
BASELINE.md: 181.53 img/s on P100).  Synthetic data (as --benchmark 1 uses),
full training step: forward + backward + SGD-momentum update, compiled as
ONE donated XLA program (bf16 compute, fp32 master weights) — see
mxnet_tpu/train_step.py.

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"} plus the
async-loop accounting fields {"input_stall_fraction", "host_syncs_per_step"}
(profiler.step_stats); sustained TFLOP/s and MFU go to stderr.

``--smoke``: tiny-MLP fit through the FULL async training loop (device-side
metrics + device prefetch + bounded in-flight dispatch) on the CPU harness —
the tier-1 hook that keeps the loop-accounting contract honest
(tests/test_bench_contract.py).
"""
import json
import os
import sys
import time

import numpy as np

BASELINE_IMG_S = 181.53  # ResNet-50 train bs32, P100 (docs/how_to/perf.md:188)

# fwd-pass FLOPs for ResNet-50 at 224x224 (2 * MACs); backward ~= 2x forward
RESNET50_FWD_FLOPS = 4.1e9
TRAIN_FLOPS_PER_IMG = 3 * RESNET50_FWD_FLOPS

def contract_line(metric, value, unit, vs_baseline, **extra):
    """The one-line stdout JSON contract every bench emits — and now the
    analysis CLI too (tools/mxlint.py), so CI consumes one schema:
    {"metric", "value", "unit", "vs_baseline", ...extras}."""
    row = {"metric": metric, "value": value, "unit": unit,
           "vs_baseline": vs_baseline}
    row.update(extra)
    return json.dumps(row)


def _peak_for(device):
    """(peak_flops_or_None, device_kind) — the spec-sheet table now lives
    with the telemetry subsystem (obs.roofline.PEAK_FLOPS) so the bench
    and the per-program MFU table share one map."""
    from mxnet_tpu.obs.roofline import peak_flops_for

    return peak_flops_for(device)


def _make_recordio_dataset(n_images, tmpdir):
    """Synthetic JPEG .rec (cached): the real-data input path."""
    import cv2

    from mxnet_tpu import recordio

    rec = os.path.join(tmpdir, "bench_%d.rec" % n_images)
    idx = os.path.join(tmpdir, "bench_%d.idx" % n_images)
    if os.path.exists(rec) and os.path.exists(idx):
        return rec, idx
    # write under per-process temp names and publish atomically: neither an
    # interrupted nor a concurrent generation may leave a pair the
    # existence check accepts
    rng = np.random.RandomState(0)
    tmp_rec = "%s.%d.tmp" % (rec, os.getpid())
    tmp_idx = "%s.%d.tmp" % (idx, os.getpid())
    w = recordio.MXIndexedRecordIO(tmp_idx, tmp_rec, "w")
    for i in range(n_images):
        img = cv2.blur(rng.randint(0, 255, (256, 256, 3), np.uint8), (4, 4))
        ok, buf = cv2.imencode(".jpg", img,
                               [int(cv2.IMWRITE_JPEG_QUALITY), 90])
        w.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i % 1000), i, 0), buf.tobytes()))
    w.close()
    os.replace(tmp_rec, rec)
    os.replace(tmp_idx, idx)
    return rec, idx


def main():
    import mxnet_tpu as mx
    from mxnet_tpu.models import resnet
    from mxnet_tpu.io import DataBatch
    from mxnet_tpu import ndarray as nd

    batch_size = int(os.environ.get("BENCH_BATCH", "256"))
    n_iters = int(os.environ.get("BENCH_ITERS", "20"))
    dtype = os.environ.get("BENCH_DTYPE", "bfloat16")
    warmup = 5
    # --recordio / BENCH_RECORDIO=1: feed real decoded JPEG batches through
    # ImageRecordIter (RecordIO read + cv2 decode + augment + prefetch)
    # instead of a resident synthetic batch — measures the end-to-end
    # real-data rate, which benchmarks/bench_input_pipeline.py showed is
    # input-bound on few-core hosts (the reference's C++ decode threads
    # have the same per-core ceiling; they scale with cores, as does
    # preprocess_threads here since cv2 releases the GIL)
    use_recordio = "--recordio" in sys.argv or \
        os.environ.get("BENCH_RECORDIO", "0") == "1"

    import jax

    platform = jax.devices()[0].platform
    ctx = mx.tpu() if platform != "cpu" else mx.cpu()
    if platform == "cpu":
        batch_size = int(os.environ.get("BENCH_BATCH", "8"))
        n_iters = 3
        warmup = 1

    from mxnet_tpu.io import DataDesc

    net = resnet.get_symbol(num_classes=1000, num_layers=50,
                            image_shape=(3, 224, 224))
    mod = mx.mod.Module(net, context=ctx, compute_dtype=dtype)
    # recordio mode binds uint8 data: batches ship compact and the
    # compiled step casts to the compute dtype on device
    data_desc = DataDesc("data", (batch_size, 3, 224, 224),
                         dtype=np.uint8 if use_recordio else np.float32)
    mod.bind(data_shapes=[data_desc],
             label_shapes=[("softmax_label", (batch_size,))])
    mod.init_params(mx.initializer.Xavier(rnd_type="gaussian",
                                          factor_type="in", magnitude=2))
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                                         "wd": 1e-4})
    if mod._fused_step is None:
        print("WARNING: fused train step not active", file=sys.stderr)

    rng = np.random.RandomState(0)
    if use_recordio:
        import tempfile

        from mxnet_tpu import image as img_mod

        import getpass

        cache = os.path.join(tempfile.gettempdir(),
                             "mxtpu_bench_rec_" + getpass.getuser())
        os.makedirs(cache, exist_ok=True)
        rec, idx = _make_recordio_dataset(
            max(batch_size * 4, 512), cache)
        rec_iter = img_mod.ImageRecordIter(
            path_imgrec=rec, path_imgidx=idx, data_shape=(3, 224, 224),
            batch_size=batch_size, shuffle=True, rand_crop=True,
            rand_mirror=True, seed=0, dtype="uint8",
            preprocess_threads=max(os.cpu_count() or 1, 1))

        def batches():
            while True:
                try:
                    yield next(rec_iter)
                except StopIteration:
                    rec_iter.reset()

        batch_stream = batches()
    else:
        x = nd.array(rng.uniform(-1, 1, (batch_size, 3, 224, 224))
                     .astype(np.float32), ctx=ctx)
        y = nd.array(rng.randint(0, 1000, (batch_size,)).astype(np.float32),
                     ctx=ctx)
        resident = DataBatch([x], [y])

        def batches():
            while True:
                yield resident

        batch_stream = batches()

    def sync():
        # on the tunneled TPU platform block_until_ready can return early;
        # fetching a value derived from the last update is a reliable fence
        import jax.numpy as jnp

        if mod._fused_step is not None:
            src = next(iter(mod._fused_step.params.values()))
        else:
            src = mod._exec_group.param_arrays[-1].data
        return float(jnp.sum(src.astype(jnp.float32)))

    from mxnet_tpu import profiler

    for _ in range(warmup):
        mod.forward_backward(next(batch_stream))
        mod.update()
    sync()

    profiler.reset_step_stats()
    tic = time.time()
    for _ in range(n_iters):
        t0 = time.perf_counter()
        batch = next(batch_stream)
        profiler.record_input_wait(time.perf_counter() - t0)
        mod.forward_backward(batch)
        mod.update()
        profiler.record_step()
    t0 = time.perf_counter()
    sync()
    profiler.record_host_wait(time.perf_counter() - t0)
    toc = time.time()
    stats = profiler.step_stats()

    img_s = batch_size * n_iters / (toc - tic)
    tflops = img_s * TRAIN_FLOPS_PER_IMG / 1e12
    peak, kind = _peak_for(jax.devices()[0])
    mfu = tflops * 1e12 / peak if peak else None
    print(json.dumps({
        "device": kind, "dtype": dtype, "batch": batch_size,
        "sustained_tflops": round(tflops, 2),
        "mfu": round(mfu, 4) if mfu is not None else None,
    }), file=sys.stderr)
    # the per-program roofline join (obs.mfu_table): measured dispatch
    # wall over the timed window vs static dot FLOPs / traffic bytes —
    # the per-kernel view of the aggregate MFU above (tools/mxstat.py
    # renders it; statically-counted FLOPs, not the analytic estimate)
    from mxnet_tpu import obs

    mfu_rows = obs.mfu_table()
    print(obs.render_mfu_table(mfu_rows), file=sys.stderr)
    # optimizer-phase HBM bytes per step, priced for BOTH update paths
    # (ops.pallas_update.priced_update_cost_for_step) — the tentpole's
    # "HBM diet" claim as a published, asserted number.  The fused
    # multi-tensor kernel must read+write the param/grad/slot traffic at
    # most once; the per-parameter chain's engine-op floor is ~5 round
    # trips — anything above 0.5x means the kernel stopped fusing.
    opt_bytes = None
    if mod._fused_step is not None:
        from mxnet_tpu.ops.pallas_update import (UPDATE_PATH,
                                                 priced_update_cost_for_step)

        opt_bytes = priced_update_cost_for_step(mod._fused_step)
        if opt_bytes is not None:
            opt_bytes["path"] = UPDATE_PATH["last"]
            # the halving claim is a bf16-headline claim: without the
            # cast/recast phases a pure-f32 chain floors at 5/9 of the
            # per-param bytes even when the kernel fuses perfectly
            if dtype == "bfloat16":
                assert opt_bytes["fused_bytes"] <= \
                    0.5 * opt_bytes["per_param_bytes"], \
                    "fused optimizer update must halve the per-parameter " \
                    "path's priced HBM bytes at the headline config: %r" \
                    % opt_bytes
    metric = "resnet50_train_imgs_per_sec_bs%d" % batch_size
    if use_recordio:
        metric = "resnet50_recordio_train_imgs_per_sec_bs%d" % batch_size
    print(contract_line(
        metric, round(img_s, 2), "img/s",
        round(img_s / BASELINE_IMG_S, 3),
        input_stall_fraction=round(stats["input_stall_fraction"], 4),
        host_syncs_per_step=round(stats["host_syncs_per_step"], 4),
        opt_update_bytes=opt_bytes,
        mfu_table=mfu_rows))


def smoke():
    """Tier-1 smoke: a small MLP fit on the CPU harness through the full
    async loop (device metrics, device prefetch, bounded in-flight
    dispatch) UNDER async fenced checkpointing, reporting the
    loop-accounting contract fields — including the elastic trio
    (checkpoint_stall_fraction / last_ckpt_ms / recoveries, whose
    deterministic halves tests/test_bench_contract.py pins: writes
    happened, no recovery on a clean run) — plus the per-program
    ``mfu_table`` roofline rows: the fit drives train_step, a score()
    pass drives eval_step, and a tiny KV-cached generate drives
    prefill + decode_step, so every canonical program the smoke touches
    gets a row joining measured dispatch wall against static
    FLOPs/bytes (flops, bytes, wall_s, mfu — mfu is null on the CPU
    harness, where no spec peak exists)."""
    import shutil
    import tempfile

    import jax

    jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    from mxnet_tpu import elastic, obs, profiler

    batch, steps_per_epoch, epochs = 32, 25, 2
    rng = np.random.RandomState(0)
    X = rng.uniform(-1, 1, (batch * steps_per_epoch, 64)).astype(np.float32)
    y = rng.randint(0, 8, (batch * steps_per_epoch,)).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=batch)

    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=128, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=8, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())

    ckpt_dir = tempfile.mkdtemp(prefix="mxtpu_bench_ckpt_")
    ctl = elastic.ElasticController(checkpointer=elastic.Checkpointer(
        ckpt_dir, period=max(steps_per_epoch // 2, 1), async_write=True))
    profiler.reset_step_stats()
    tic = time.time()
    try:
        mod.fit(it, eval_metric="acc", num_epoch=epochs, optimizer="sgd",
                optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
                initializer=mx.initializer.Xavier(), elastic=ctl)
        toc = time.time()
        # loop-accounting snapshot AT the fit boundary: the contract's
        # stall fractions / host_syncs_per_step describe the fit, not
        # the extra program drives below
        stats = profiler.step_stats()
        ckpt_writes = ctl.checkpointer.writes
        steps_during_write = ctl.checkpointer.steps_during_write
    finally:
        shutil.rmtree(ckpt_dir, ignore_errors=True)
    if mod._fused_step is None:
        print("WARNING: fused train step not active", file=sys.stderr)

    # eval_step row: one device-metric score() pass over the same data
    mod.score(it, "acc")
    # prefill/decode_step rows: a tiny KV-cached generate (the canonical
    # attention-LM dims the analysis programs use)
    from mxnet_tpu.analysis.programs import _lm_params, _lm_symbol
    from mxnet_tpu.decode import DecodePredictor

    sym = _lm_symbol()
    pred = DecodePredictor(sym, _lm_params(sym, 2, 16), cache_len=16,
                           temperature=0.0, kv_dtype="", paged=False)
    pred.generate(rng.randint(0, 32, (2, 8)).astype(np.float32),
                  prompt_len=8, max_new_tokens=5)
    mfu_rows = obs.mfu_table()
    print(obs.render_mfu_table(mfu_rows), file=sys.stderr)
    # publish (no assert here — the non-smoke headline asserts) the
    # priced optimizer-phase bytes per path, same field as main()
    opt_bytes = None
    if mod._fused_step is not None:
        from mxnet_tpu.ops.pallas_update import (UPDATE_PATH,
                                                 priced_update_cost_for_step)

        opt_bytes = priced_update_cost_for_step(mod._fused_step)
        if opt_bytes is not None:
            opt_bytes["path"] = UPDATE_PATH["last"]
    print(json.dumps({"loop_stats": {k: stats[k] for k in
                                     ("steps", "host_wait_s", "input_wait_s",
                                      "metric_d2h", "metric_syncs",
                                      "ckpt_stall_s", "ckpt_writes",
                                      "recoveries")}}),
          file=sys.stderr)
    n = max(stats["steps"], 1)
    print(contract_line(
        "async_fit_mlp_imgs_per_sec_bs%d" % batch,
        round(batch * n / (toc - tic), 2), "img/s", 1.0,
        input_stall_fraction=round(stats["input_stall_fraction"], 4),
        host_syncs_per_step=round(stats["host_syncs_per_step"], 4),
        checkpoint_stall_fraction=round(stats["checkpoint_stall_fraction"],
                                        4),
        last_ckpt_ms=round(stats["last_ckpt_ms"], 2),
        ckpt_writes=ckpt_writes,
        ckpt_steps_during_write=steps_during_write,
        recoveries=stats["recoveries"],
        opt_update_bytes=opt_bytes,
        mfu_table=mfu_rows))


if __name__ == "__main__":
    if "--smoke" in sys.argv:
        smoke()
    else:
        main()
