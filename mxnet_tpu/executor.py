"""Executor — binds a Symbol to devices and runs it.

TPU-native re-design of GraphExecutor (`src/executor/graph_executor.cc`) and
`python/mxnet/executor.py`.  Where the reference runs a hand-built pipeline
(Gradient pass → PlaceDevice → InferShape → PlanMemory → per-node engine
ops), here the whole graph lowers into ONE jitted XLA program:

* forward  = jit(run_graph)                          — XLA fuses + plans memory
* backward = jit(vjp(run_graph)) w.r.t. grad-args    — the nnvm Gradient pass
* bulk-exec segments (graph_executor.cc:678) are implicit: the entire
  program is a single segment.
* grad_req add/write = functional accumulate, write-back into grad buffers.
* data-parallelism lives one level up: executor_group device_puts the batch
  with a mesh NamedSharding and replicates params, and jit propagates those
  committed input shardings — XLA inserts the psum collectives that the
  reference's KVStore Reduce performed.  The executor itself is
  sharding-agnostic.

Training forward runs the combined (outputs, grads, new_aux) program with
ones head-gradients — loss heads carry custom_vjp so this reproduces the
reference's Backward() semantics; ``backward(out_grads)`` with explicit head
gradients re-runs the combined program with those cotangents.
"""
from __future__ import annotations

import numpy as np

from .base import MXNetError
from .context import Context, current_context
from .registry import OpContext
from . import ndarray as nd

__all__ = ["Executor"]


class Executor:
    def __init__(self, symbol, ctx, args, args_grad=None, grad_req="write",
                 aux_states=None, group2ctx=None, shared_exec=None):
        self._symbol = symbol
        self._ctx = ctx if isinstance(ctx, Context) else Context(ctx)
        self._group2ctx = group2ctx or {}
        self._placement = self._plan_placement(symbol, self._group2ctx)
        self._monitor_callback = None

        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()

        # -- argument arrays
        if isinstance(args, dict):
            self.arg_dict = {n: args[n] for n in arg_names}
        else:
            if len(args) != len(arg_names):
                raise MXNetError("Length of args does not match arguments: %s"
                                 % arg_names)
            self.arg_dict = dict(zip(arg_names, args))
        self.arg_arrays = [self.arg_dict[n] for n in arg_names]

        # -- gradient request
        if isinstance(grad_req, str):
            self.grad_req = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self.grad_req = dict(zip(arg_names, grad_req))
        else:
            self.grad_req = {n: grad_req.get(n, "null") for n in arg_names}

        # -- gradient arrays
        if args_grad is None:
            self.grad_dict = {}
        elif isinstance(args_grad, dict):
            self.grad_dict = dict(args_grad)
        else:
            self.grad_dict = {n: g for n, g in zip(arg_names, args_grad)
                              if g is not None}
        for n in arg_names:
            if self.grad_req.get(n, "null") != "null" and n not in self.grad_dict:
                self.grad_req[n] = "null"
        self.grad_arrays = [self.grad_dict.get(n) for n in arg_names]

        # -- aux arrays
        if aux_states is None:
            aux_states = {}
        if isinstance(aux_states, dict):
            self.aux_dict = {n: aux_states[n] for n in aux_names}
        else:
            self.aux_dict = dict(zip(aux_names, aux_states))
        self.aux_arrays = [self.aux_dict[n] for n in aux_names]

        if self._placement:
            self._place_buffers()
        self._arg_names = arg_names
        self._aux_names = aux_names
        self._grad_names = [n for n in arg_names
                            if self.grad_req.get(n, "null") != "null"]
        self._outputs = None
        self._cached_grads = None
        self._fn_cache = {}
        self.outputs_ready = False

    # ------------------------------------------------------------------
    # model-parallel placement (group2ctx)
    # ------------------------------------------------------------------
    @staticmethod
    def _plan_placement(symbol, group2ctx):
        """Map node name -> jax.Device from ``ctx_group`` attrs.

        The reference's AssignContext/PlaceDevice pass
        (graph_executor.cc:242-331): nodes carrying a ``ctx_group`` attr run
        on the mapped device and ``_CrossDeviceCopy`` is inserted at cut
        edges — here the copies are ``jax.device_put`` at op boundaries
        (see _run_graph), and XLA async dispatch provides the cross-device
        overlap the reference got from its engine.  A group with no mapping
        raises rather than silently replicating.  Returns None when no
        placement is requested.
        """
        if not group2ctx:
            return None
        placement = {}
        for node in symbol._topo():
            group = node.attrs.get("ctx_group") if node.attrs else None
            if group is None:
                continue
            if group not in group2ctx:
                raise MXNetError(
                    "ctx_group %r on node %r has no entry in group2ctx "
                    "(mapped groups: %s)" % (group, node.name,
                                             sorted(group2ctx)))
            ctx = group2ctx[group]
            ctx = ctx if isinstance(ctx, Context) else Context(ctx)
            placement[node.name] = ctx.jax_device
        return placement or None

    @property
    def _default_device(self):
        """Device for nodes with no ctx_group under placement."""
        dev = getattr(self, "_default_dev_cache", None)
        if dev is None:
            dev = self._ctx.jax_device
            self._default_dev_cache = dev
        return dev

    def _place_buffers(self):
        """Make parameter/gradient NDArrays resident on their placed device
        so steady-state steps do no cross-device parameter traffic (the
        reference allocates each node's arrays on its assigned device)."""
        import jax

        for pool in (self.arg_dict, self.grad_dict, self.aux_dict):
            for name, arr in pool.items():
                dev = self._placement.get(name)
                if dev is not None and arr.data.devices() != {dev}:
                    arr._set_data(jax.device_put(arr.data, dev))

    # ------------------------------------------------------------------
    # graph execution as a pure function
    # ------------------------------------------------------------------
    def _run_graph(self, env_args, env_aux, rng, is_train, tap=None):
        """Topologically execute the node DAG on jnp values.

        ``tap(name, value)``, when given, is invoked with every node
        output — the analog of the reference's per-node monitor callback
        (`graph_executor.cc:758-778`).  Taps only make sense outside jit
        (eager execution), where intermediate values are materialized.
        """
        import jax

        from . import profiler as _prof

        sym = self._symbol
        # per-node profiler spans are only meaningful when executing
        # eagerly on concrete values (under jit this loop runs once, at
        # trace time); XLA-side op attribution comes from named_scope
        spans = False
        if _prof.is_running():
            probe = next(iter(env_args.values()), None)
            try:
                spans = not isinstance(probe, jax.core.Tracer)
            except AttributeError:
                spans = False
        values = {}
        new_aux = dict(env_aux)
        for seq, node in enumerate(sym._topo()):
            if node.is_variable:
                if node.is_aux_var:
                    values[(id(node), 0)] = env_aux[node.name]
                else:
                    values[(id(node), 0)] = env_args[node.name]
                continue
            attrs = node.parsed_attrs()
            n_args = node.op.n_inputs(attrs)
            ins = [values[(id(s), i)] for s, i in node.inputs[:n_args]]
            aux_ins = [values[(id(s), i)] for s, i in node.inputs[n_args:]]
            node_rng = jax.random.fold_in(rng, seq) if rng is not None \
                else None
            if self._placement is not None:
                # cut-edge transfer (the _CrossDeviceCopy analog): inputs
                # move to this node's device — unannotated nodes run on the
                # bind ctx, like the reference's PlaceDevice default.
                # device_put is a no-op for values already in place, and
                # its transpose moves cotangents back, so backward
                # transfers fall out of vjp
                dev = self._placement.get(node.name, self._default_device)
                ins = [jax.device_put(v, dev) for v in ins]
                aux_ins = [jax.device_put(v, dev) for v in aux_ins]
                if node_rng is not None:
                    node_rng = jax.device_put(node_rng, dev)
            octx = OpContext(is_train=is_train, rng=node_rng,
                             mesh_active=getattr(self, "_mesh_active",
                                                 False),
                             mesh=getattr(self, "_mesh", None))
            with jax.named_scope(node.name):
                if spans:
                    with _prof.Scope(node.name):
                        outs, node_new_aux = node.op.fcompute(
                            attrs, ins, aux_ins, octx)
                else:
                    outs, node_new_aux = node.op.fcompute(
                        attrs, ins, aux_ins, octx)
            for i, o in enumerate(outs):
                values[(id(node), i)] = o
            if tap is not None:
                onames = node.op.list_outputs(attrs)
                for i in range(node.op.n_visible_outputs(attrs)):
                    suffix = onames[i] if i < len(onames) else str(i)
                    tap("%s_%s" % (node.name, suffix), outs[i])
            for (anode, _), val in zip(node.inputs[n_args:], node_new_aux):
                new_aux[anode.name] = val
        outputs = [values[(id(n), i)] for n, i in sym._outputs]
        return outputs, new_aux

    def _cast_u8(self, vals):
        """uint8 DATA inputs are compactly-shipped image bytes (ImageIter
        dtype='uint8'): cast to float at the graph boundary — same rule as
        the fused train step's on-device cast (train_step.py).  Only names
        in ``_u8_cast_names`` (set by the executor group from the bound
        data descriptors) are touched, so deliberately-integral uint8
        args (masks, custom-op bytes) keep their dtype."""
        import jax.numpy as jnp

        names = getattr(self, "_u8_cast_names", ())
        if not names:
            return vals
        return [v.astype(jnp.float32)
                if n in names and v.dtype == jnp.uint8 else v
                for n, v in zip(self._arg_names, vals)]

    def _fwd_impl(self, arg_vals, aux_vals, rng, is_train, tap=None):
        env_args = dict(zip(self._arg_names, self._cast_u8(arg_vals)))
        env_aux = dict(zip(self._aux_names, aux_vals))
        outs, new_aux = self._run_graph(env_args, env_aux, rng, is_train, tap)
        return outs, [new_aux[n] for n in self._aux_names]

    def _combined_impl(self, arg_vals, aux_vals, old_grads, head_grads, rng,
                       tap=None):
        import jax

        from . import config as _config

        grad_names = self._grad_names
        arg_names = self._arg_names
        aux_names = self._aux_names
        reqs = self.grad_req
        env_aux_in = dict(zip(aux_names, aux_vals))
        arg_vals = self._cast_u8(arg_vals)
        nograd = {n: v for n, v in zip(arg_names, arg_vals)
                  if n not in set(grad_names)}

        def fwd(gvals):
            env_args = dict(nograd)
            env_args.update(zip(grad_names, gvals))
            outs, new_aux = self._run_graph(env_args, env_aux_in, rng, True,
                                            tap)
            return outs, [new_aux[n] for n in aux_names]

        if tap is None and _config.get("MXNET_BACKWARD_DO_MIRROR"):
            # memonger analog: rematerialize activations in the backward
            # pass instead of keeping them live (reference mirror option)
            fwd = jax.checkpoint(fwd)
        gvals = [v for n, v in zip(arg_names, arg_vals) if n in set(grad_names)]
        outs, vjp_fn, new_aux = jax.vjp(fwd, gvals, has_aux=True)
        if head_grads is None:
            import jax.numpy as jnp

            cts = [jnp.ones_like(o) for o in outs]
        else:
            cts = list(head_grads)
        (grads,) = vjp_fn(cts)
        out_grads = []
        for gname, g in zip(grad_names, grads):
            if reqs[gname] == "add":
                out_grads.append(old_grads[grad_names.index(gname)] + g)
            else:
                out_grads.append(g)
        return outs, new_aux, out_grads

    def _get_fn(self, kind):
        """kind: 'fwd_test' | 'fwd_train' | 'combined'"""
        fn = self._fn_cache.get(kind)
        if fn is not None:
            return fn
        import jax

        from . import config as _config

        # MXNET_ENGINE_TYPE=NaiveEngine: run everything eagerly op-by-op
        # (the reference's debugging engine); bulk-exec-inference off does
        # the same for inference graphs only.  group2ctx placement also
        # runs eagerly: each op dispatches async onto its own device (the
        # engine-overlap model), since one jit program owns one device set.
        compiled = _config.get("MXNET_ENGINE_TYPE") != "NaiveEngine" \
            and self._placement is None
        if kind == "fwd_test" and not _config.get("MXNET_EXEC_BULK_EXEC_INFERENCE"):
            compiled = False

        if kind in ("fwd_test", "fwd_train"):
            is_train = kind == "fwd_train"

            def run(arg_vals, aux_vals, rng):
                return self._fwd_impl(arg_vals, aux_vals, rng, is_train)

            fn = jax.jit(run) if compiled else run
        else:
            def combined(arg_vals, aux_vals, old_grads, head_grads, rng):
                return self._combined_impl(arg_vals, aux_vals, old_grads,
                                           head_grads, rng)

            fn = jax.jit(combined) if compiled else combined
        self._fn_cache[kind] = fn
        return fn

    # ------------------------------------------------------------------
    # public API (reference: python/mxnet/executor.py)
    # ------------------------------------------------------------------
    def forward(self, is_train=False, **kwargs):
        import jax

        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError("Unknown argument %s" % k)
            self.arg_dict[k]._set_data(
                v.data if isinstance(v, nd.NDArray) else v)

        arg_vals = [self.arg_dict[n].data for n in self._arg_names]
        aux_vals = [self.aux_dict[n].data for n in self._aux_names]
        from . import random as _rnd

        rng = _rnd.split_key()
        self._last_rng = rng  # reused by backward(out_grads): same dropout masks

        tap = None
        if self._monitor_callback is not None and \
                getattr(self._monitor_callback, "active", True):
            # monitored runs execute eagerly (the NaiveEngine analog) so
            # every op's output exists to be observed — reference taps each
            # node in graph_executor.cc:758-778.  A disarmed tap (Monitor
            # between intervals) keeps the fast jitted path.
            cb = self._monitor_callback

            def tap(name, value):
                cb(name, nd.NDArray(value, self._ctx))

        from . import profiler as _prof

        if is_train and self._grad_names:
            old_grads = [self.grad_dict[n].data for n in self._grad_names]
            if tap is not None:
                # vjp tracing would hand the tap abstract tracers, so the
                # observation pass runs separately on concrete values
                self._fwd_impl(arg_vals, aux_vals, rng, True, tap)
            with _prof.Scope("forward_backward", "executor"):
                outs, new_aux, grads = self._get_fn("combined")(
                    arg_vals, aux_vals, old_grads, None, rng)
            self._cached_grads = grads
        else:
            if tap is not None:
                outs, new_aux = self._fwd_impl(arg_vals, aux_vals, rng,
                                               is_train, tap)
            else:
                with _prof.Scope("forward", "executor"):
                    outs, new_aux = self._get_fn(
                        "fwd_train" if is_train else "fwd_test")(
                        arg_vals, aux_vals, rng)
            self._cached_grads = None
        for n, v in zip(self._aux_names, new_aux):
            self.aux_dict[n]._set_data(v)
        self._outputs = [nd.NDArray(o, self._ctx) for o in outs]
        self.outputs_ready = True
        return self._outputs

    def backward(self, out_grads=None):
        if not self._grad_names:
            return
        if out_grads is not None:
            if isinstance(out_grads, nd.NDArray):
                out_grads = [out_grads]
            import jax

            arg_vals = [self.arg_dict[n].data for n in self._arg_names]
            aux_vals = [self.aux_dict[n].data for n in self._aux_names]
            old_grads = [self.grad_dict[n].data for n in self._grad_names]
            # reuse the forward pass's key so stochastic ops (Dropout) apply
            # the same mask the caller's observed outputs came from
            rng = getattr(self, "_last_rng", None)
            if rng is None:
                from . import random as _rnd

                rng = _rnd.split_key()
            fn = self._get_fn("combined")
            outs, new_aux, grads = fn(arg_vals, aux_vals, old_grads,
                                      [g.data for g in out_grads], rng)
        else:
            if self._cached_grads is None:
                raise MXNetError(
                    "backward() called before forward(is_train=True)")
            grads = self._cached_grads
        for n, g in zip(self._grad_names, grads):
            self.grad_dict[n]._set_data(g.astype(self.grad_dict[n].data.dtype))
        self._cached_grads = None

    @property
    def outputs(self):
        if self._outputs is None:
            raise MXNetError("Executor has not been run")
        return self._outputs

    def compiled_hlo(self, kind="combined"):
        """Optimized-HLO text of a cached compiled step (None when eager).

        The XLA-era analog of the reference's bandwidth probe: collectives
        are explicit ops in the compiled program, so communication per step
        is statically countable — feed this to
        ``parallel.hlo_stats.collective_stats``.  Avals (+shardings) are
        rebuilt from the live buffers at call time, so nothing is retained
        on the training hot path for this probe.
        """
        import jax

        fn = self._fn_cache.get(kind)
        if fn is None or not hasattr(fn, "lower"):
            return None
        rng = getattr(self, "_last_rng", None)
        if rng is None:
            return None

        def _aval(x):
            return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                        sharding=x.sharding)

        arg_vals = [_aval(self.arg_dict[n].data) for n in self._arg_names]
        aux_vals = [_aval(self.aux_dict[n].data) for n in self._aux_names]
        if kind == "combined":
            old_grads = [_aval(self.grad_dict[n].data)
                         for n in self._grad_names]
            args = (arg_vals, aux_vals, old_grads, None, _aval(rng))
        else:
            args = (arg_vals, aux_vals, _aval(rng))
        return fn.lower(*args).compile().as_text()

    def set_monitor_callback(self, callback):
        self._monitor_callback = callback

    def copy_params_from(self, arg_params, aux_params=None, allow_extra_params=False):
        for name, array in arg_params.items():
            if name in self.arg_dict:
                array.copyto(self.arg_dict[name])
            elif not allow_extra_params:
                raise MXNetError("Found name %r not in arguments" % name)
        if aux_params:
            for name, array in aux_params.items():
                if name in self.aux_dict:
                    array.copyto(self.aux_dict[name])
                elif not allow_extra_params:
                    raise MXNetError("Found name %r not in aux states" % name)

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Rebind with new input shapes; jit specializes per shape the same
        way bucketing shares memory pools in the reference.

        Contract (reference executor.py reshape): shapes of arguments *not*
        named in kwargs may only change when ``partial_shaping`` is set, and
        any array may only grow when ``allow_up_sizing`` is set (the
        reference reuses the old buffer's memory, so growth needs opt-in;
        here growth allocates a fresh buffer but the contract is enforced
        identically so programs behave the same on both frameworks).
        """
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**kwargs)
        new_args, new_grads = {}, {}
        for name, shape, arr in zip(self._arg_names, arg_shapes, self.arg_arrays):
            if tuple(shape) == arr.shape:
                new_args[name] = arr
                if name in self.grad_dict:
                    new_grads[name] = self.grad_dict[name]
            else:
                if not partial_shaping and name not in kwargs:
                    raise MXNetError(
                        "Shape of unspecified argument %r changed (%s -> %s);"
                        " pass partial_shaping=True to allow this" %
                        (name, arr.shape, tuple(shape)))
                if not allow_up_sizing and \
                        int(np.prod(shape)) > int(np.prod(arr.shape)):
                    raise MXNetError(
                        "New shape of %r is larger than the original (%s -> "
                        "%s); pass allow_up_sizing=True to allow this" %
                        (name, arr.shape, tuple(shape)))
                new_args[name] = nd.zeros(shape, self._ctx, dtype=arr.dtype)
                if name in self.grad_dict:
                    new_grads[name] = nd.zeros(shape, self._ctx, dtype=arr.dtype)
        new_aux = {}
        for name, shape, arr in zip(self._aux_names, aux_shapes, self.aux_arrays):
            new_aux[name] = arr if tuple(shape) == arr.shape else \
                nd.zeros(shape, self._ctx, dtype=arr.dtype)
        return Executor(self._symbol, self._ctx, new_args, new_grads,
                        self.grad_req, new_aux, group2ctx=self._group2ctx)

    # ------------------------------------------------------------------
    @staticmethod
    def simple_bind(symbol, ctx, grad_req="write", type_dict=None,
                    group2ctx=None, shared_exec=None, **kwargs):
        arg_shapes, out_shapes, aux_shapes = symbol.infer_shape(**kwargs)
        if arg_shapes is None:
            raise MXNetError("Cannot infer shapes with inputs %s" % kwargs)
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        # propagate dtypes through the graph from the (optional) type_dict
        # seeds, so int inputs stay int and fp16/bf16 flows into weights
        # instead of every buffer defaulting to float32
        arg_types, _, aux_types = symbol.infer_type(**(type_dict or {}))
        type_dict = dict(zip(arg_names, arg_types))
        type_dict.update(zip(aux_names, aux_types))
        args = {}
        grads = {}
        if isinstance(grad_req, str):
            req = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, (list, tuple)):
            req = dict(zip(arg_names, grad_req))
        else:
            req = {n: grad_req.get(n, "null") for n in arg_names}
        for name, shape in zip(arg_names, arg_shapes):
            dtype = type_dict.get(name, np.float32)
            # reuse shared executor buffers when shapes match (bucketing)
            if shared_exec is not None and name in shared_exec.arg_dict and \
                    shared_exec.arg_dict[name].shape == tuple(shape):
                args[name] = shared_exec.arg_dict[name]
                if name in shared_exec.grad_dict and req.get(name, "null") != "null":
                    grads[name] = shared_exec.grad_dict[name]
                    continue
            else:
                args[name] = nd.zeros(shape, ctx, dtype=dtype)
            if req.get(name, "null") != "null":
                grads[name] = nd.zeros(shape, ctx, dtype=dtype)
        aux = {}
        for name, shape in zip(aux_names, aux_shapes):
            dtype = type_dict.get(name, np.float32)
            if shared_exec is not None and name in shared_exec.aux_dict and \
                    shared_exec.aux_dict[name].shape == tuple(shape):
                aux[name] = shared_exec.aux_dict[name]
            else:
                aux[name] = nd.zeros(shape, ctx, dtype=dtype)
        return Executor(symbol, ctx, args, grads, req, aux, group2ctx=group2ctx)
