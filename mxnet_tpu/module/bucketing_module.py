"""BucketingModule — variable-length (bucketed) training.

Capability parity with the reference's ``module/bucketing_module.py``.
Buckets are load-bearing on XLA exactly as in the reference (SURVEY §7c):
every bucket key is a shape specialization with its own compiled program,
while parameters live in ONE set of buffers shared through shared-module
binding.

Layout here: a ``_primary`` module (default bucket) owns params and the
optimizer; ``switch_bucket`` lazily binds per-key modules against it.  All
buckets share ONE fused-train-step master-weight store (each bucket gets a
shape-specialized compiled program inside it), so variable-length LSTM/LM
workloads train on the fused path, not a per-bucket eager fallback.
"""
from __future__ import annotations

import logging

from .base_module import BaseModule
from .module import Module


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None):
        super().__init__(logger=logger)
        assert default_bucket_key is not None
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._module_kwargs = dict(
            logger=logger, context=context, work_load_list=work_load_list,
            fixed_param_names=fixed_param_names)
        self._clear()

    def _clear(self):
        self._by_key = {}
        self._active = None
        self._active_key = None
        self._params_dirty = False
        self._fit_metric = None

    @property
    def _primary(self):
        return self._by_key.get(self._default_bucket_key)

    @property
    def _curr_module(self):
        # reference-compatible accessor (tests and user code reach for it)
        return self._active

    @property
    def _buckets(self):
        return self._by_key

    def _new_module(self, bucket_key):
        symbol, data_names, label_names = self._sym_gen(bucket_key)
        return Module(symbol, data_names, label_names, **self._module_kwargs)

    # ------------------------------------------------------------------
    @property
    def data_names(self):
        if self._active is not None:
            return self._active.data_names
        return self._sym_gen(self._default_bucket_key)[1]

    @property
    def output_names(self):
        if self._active is not None:
            return self._active.output_names
        return self._sym_gen(self._default_bucket_key)[0].list_outputs()

    @property
    def data_shapes(self):
        assert self.binded
        return self._active.data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._active.label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._active.output_shapes

    @property
    def symbol(self):
        assert self.binded
        return self._active.symbol

    # ------------------------------------------------------------------
    def get_params(self):
        assert self.binded and self.params_initialized
        self._active._params_dirty = self._params_dirty
        params = self._active.get_params()
        self._params_dirty = False
        return params

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before initializing the parameters"
        self._active.init_params(initializer=initializer,
                                 arg_params=arg_params, aux_params=aux_params,
                                 allow_missing=allow_missing,
                                 force_init=force_init)
        self._params_dirty = False
        self.params_initialized = True

    # ------------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        assert shared_module is None, \
            "shared_module for BucketingModule is not supported"
        snapshot = self.get_params() if self.params_initialized else None
        if force_rebind:
            self._clear()
            self.binded = False
        if self.binded:
            self.logger.warning("Already binded, ignoring bind()")
            return

        self.binded = True
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad

        primary = self._new_module(self._default_bucket_key)
        primary.bind(data_shapes, label_shapes, for_training,
                     inputs_need_grad, grad_req=grad_req)
        self._by_key = {self._default_bucket_key: primary}
        self._active = primary
        self._active_key = self._default_bucket_key

        if snapshot is not None:
            self.set_params(*snapshot)

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """Make ``bucket_key`` the active specialization, binding a new
        module against the primary's buffers on first use."""
        assert self.binded, "call bind before switching bucket"
        module = self._by_key.get(bucket_key)
        if module is None:
            module = self._new_module(bucket_key)
            module.bind(data_shapes, label_shapes,
                        self._primary.for_training,
                        self._primary.inputs_need_grad,
                        shared_module=self._primary)
            if self.optimizer_initialized:
                module.borrow_optimizer(self._primary)
                self._ensure_fused_compat(module)
            if self._fit_metric is not None:
                module._bind_metric(self._fit_metric)
            self._by_key[bucket_key] = module
        self._active = module
        self._active_key = bucket_key

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring.")
            return
        primary = self._primary
        primary.init_optimizer(kvstore, optimizer, optimizer_params,
                               force_init=force_init)
        # every bucket adopts the primary's update path — including its
        # fused step when one compiled: the step is ONE master-weight store
        # that compiles a per-bucket program on first use, so LSTM/LM
        # workloads get the fused-path throughput on all buckets
        for module in self._by_key.values():
            if module is not primary:
                module.borrow_optimizer(primary)
                self._ensure_fused_compat(module)
        self.optimizer_initialized = True

    def _ensure_fused_compat(self, module):
        """Buckets whose parameter set is only partially shared with the
        primary (shape-varying params get per-bucket storage, matching the
        reference) cannot ride the shared fused store — demote ALL buckets
        to the eager update path so every path sees one source of truth."""
        primary = self._primary
        step = primary._fused_step
        if step is None or step.compatible(module._exec_group):
            return
        self.logger.info(
            "bucket parameters are not fully shared with the primary; "
            "using the eager update path for all buckets")
        primary._handoff_fused_to_eager()
        for m in self._by_key.values():
            m._fused_step = None
            m._opt_owner = "eager"
        module._fused_step = None
        module._opt_owner = "eager"

    # ------------------------------------------------------------------
    def forward_backward(self, data_batch):
        """Route training batches through the active bucket's own
        ``forward_backward`` so each bucket reaches the shared fused step
        (a plain forward+backward here would silently force eager)."""
        assert self.binded and self.params_initialized
        self.switch_bucket(data_batch.bucket_key, data_batch.provide_data,
                           data_batch.provide_label)
        self._active.forward_backward(data_batch)

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        self.switch_bucket(data_batch.bucket_key, data_batch.provide_data,
                           data_batch.provide_label)
        self._active.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._active.backward(out_grads=out_grads)

    def update(self):
        assert self.binded and self.params_initialized and self.optimizer_initialized
        self._params_dirty = True
        self._active.update()

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._active.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized and self.inputs_need_grad
        return self._active.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        assert self.binded and self.params_initialized
        self._active.update_metric(eval_metric, labels)

    def _bind_metric(self, eval_metric):
        # every bucket shares ONE fused store, so attaching through any
        # bucket module arms accumulation for all of them; remember the
        # metric for buckets bound later in the epoch
        self._fit_metric = eval_metric
        for module in self._by_key.values():
            module._bind_metric(eval_metric)

    def _dispatch_fence(self):
        if self._active is None:
            return None
        return self._active._dispatch_fence()

    def install_monitor(self, mon):
        assert self.binded
        for module in self._by_key.values():
            module.install_monitor(mon)
