"""SequentialModule — a pipeline of modules executed back-to-back.

API parity with the reference's ``module/sequential_module.py`` (``add``
with ``take_labels``/``auto_wiring`` metadata, BaseModule surface), built
around an explicit ``_Stage`` record per child and one shape-chaining
helper instead of inline meta-dict plumbing.
"""
from __future__ import annotations

import logging

from .base_module import BaseModule


class _Stage:
    """One link of the chain: a module plus its wiring flags."""

    __slots__ = ("module", "takes_labels", "auto_wire")

    def __init__(self, module, takes_labels=False, auto_wire=False):
        self.module = module
        self.takes_labels = takes_labels
        self.auto_wire = auto_wire


class SequentialModule(BaseModule):
    META_TAKE_LABELS = "take_labels"
    META_AUTO_WIRING = "auto_wiring"

    def __init__(self, logger=logging):
        super().__init__(logger=logger)
        self._stages = []
        self._label_shapes = None
        self._data_shapes = None

    def add(self, module, **kwargs):
        unknown = set(kwargs) - {self.META_TAKE_LABELS, self.META_AUTO_WIRING}
        assert not unknown, "Unknown meta %s" % sorted(unknown)
        self._stages.append(_Stage(
            module,
            takes_labels=bool(kwargs.get(self.META_TAKE_LABELS)),
            auto_wire=bool(kwargs.get(self.META_AUTO_WIRING))))
        # adding a layer invalidates any previous setup
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        return self

    @property
    def _modules(self):
        return [s.module for s in self._stages]

    # ------------------------------------------------------------------
    @property
    def data_names(self):
        return self._stages[0].module.data_names if self._stages else []

    @property
    def output_names(self):
        return self._stages[-1].module.output_names if self._stages else []

    @property
    def data_shapes(self):
        assert self.binded
        return self._stages[0].module.data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._stages[-1].module.output_shapes

    # ------------------------------------------------------------------
    def get_params(self):
        assert self.binded and self.params_initialized
        args, auxs = {}, {}
        for stage in self._stages:
            a, x = stage.module.get_params()
            args.update(a)
            auxs.update(x)
        return args, auxs

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded
        for stage in self._stages:
            stage.module.init_params(
                initializer=initializer, arg_params=arg_params,
                aux_params=aux_params, allow_missing=allow_missing,
                force_init=force_init)
        self._assert_unique_params()
        self.params_initialized = True

    def _assert_unique_params(self):
        owners = {}
        for i, stage in enumerate(self._stages):
            for group in stage.module.get_params():
                for name in group:
                    if name in owners:
                        raise AssertionError(
                            "Duplicated parameter name %s: layer %d (%s) and "
                            "layer %d (%s)" % (
                                name, i, type(stage.module),
                                owners[name], type(self._modules[owners[name]])))
                    owners[name] = i

    # ------------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already binded, ignoring bind()")
            return
        if inputs_need_grad:
            assert for_training
        assert shared_module is None, "Shared module is not supported"
        assert self._stages, "Attempting to bind an empty SequentialModule"

        self.binded = True
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._data_shapes = data_shapes
        self._label_shapes = label_shapes if any(
            s.takes_labels for s in self._stages) else None

        shapes = list(data_shapes)
        for i, stage in enumerate(self._stages):
            if stage.auto_wire:
                # adopt the child's own input names for the incoming shapes
                names = stage.module.data_names
                assert len(names) == len(shapes)
                shapes = [(n, s[1]) for n, s in zip(names, shapes)]
            stage.module.bind(
                data_shapes=shapes,
                label_shapes=label_shapes if stage.takes_labels else None,
                for_training=for_training,
                inputs_need_grad=bool(for_training and
                                      (inputs_need_grad or i > 0)),
                force_rebind=force_rebind, grad_req=grad_req)
            shapes = self._outgoing_shapes(stage.module, shapes)

    @staticmethod
    def _outgoing_shapes(module, incoming):
        """Output (name, shape) pairs of a bound child, which become the
        next child's data shapes."""
        if getattr(module, "symbol", None) is None:
            # symbol-less children (PythonModule) declare their own
            return [(d.name, tuple(d.shape)) for d in module.output_shapes]
        _, out_shapes, _ = module.symbol.infer_shape(
            **{name: shape for name, shape in incoming})
        return [(name, tuple(shape))
                for name, shape in zip(module.output_names, out_shapes)]

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring.")
            return
        for stage in self._stages:
            stage.module.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                                        optimizer_params=optimizer_params,
                                        force_init=force_init)
        self.optimizer_initialized = True

    # ------------------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        from ..io import DataBatch

        batch = data_batch
        for stage, nxt in zip(self._stages, self._stages[1:] + [None]):
            stage.module.forward(batch, is_train=is_train)
            if nxt is None:
                break
            batch = DataBatch(
                data=stage.module.get_outputs(),
                label=data_batch.label if nxt.takes_labels else None,
                pad=data_batch.pad, index=data_batch.index)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        for i in range(len(self._stages) - 1, -1, -1):
            self._stages[i].module.backward(out_grads=out_grads)
            if i:
                out_grads = self._stages[i].module.get_input_grads()

    def update(self):
        assert self.binded and self.params_initialized and self.optimizer_initialized
        for stage in self._stages:
            stage.module.update()

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._stages[-1].module.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized and self.inputs_need_grad
        return self._stages[0].module.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        assert self.binded and self.params_initialized
        for stage in self._stages:
            if stage.takes_labels:
                stage.module.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        assert self.binded
        for stage in self._stages:
            stage.module.install_monitor(mon)
