"""DataParallelExecutorGroup — data parallelism over a device mesh.

Reference: `python/mxnet/module/executor_group.py` (651 LoC): one executor
per device, batch sliced along axis 0 (`decide_slices`:207), gradients
reduced through KVStore.  TPU-native re-design: ONE executor jitted over a
``jax.sharding.Mesh`` whose 'data' axis spans the bound contexts; the batch
is device_put with a NamedSharding on axis 0 and parameters are replicated.
XLA's SPMD partitioner then inserts the psum collectives over ICI that the
reference's Comm::Reduce/Broadcast performed explicitly — gradients arrive
at `update()` already globally summed.

Note one intentional deviation: BatchNorm statistics are computed over the
global (mesh-wide) batch, i.e. sync-BN, where the reference normalizes
per-device (SURVEY §7f).  For contexts==1 they coincide.
"""
from __future__ import annotations

import logging

import numpy as np

from ..base import MXNetError
from .. import ndarray as nd
from ..executor import Executor
from ..io import DataDesc


def _as_desc_list(shapes):
    out = []
    for s in shapes or []:
        if isinstance(s, DataDesc):
            out.append(s)
        else:
            name, shape = s[0], s[1]
            out.append(DataDesc(name, shape))
    return out


class DataParallelExecutorGroup:
    def __init__(self, symbol, contexts, workload, data_shapes, label_shapes,
                 param_names, for_training, inputs_need_grad, shared_group=None,
                 logger=logging, fixed_param_names=None, grad_req="write",
                 state_names=None, mesh_config=None):
        self.symbol = symbol
        self.contexts = contexts
        self.mesh_config = mesh_config
        self.param_names = param_names
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.fixed_param_names = set(fixed_param_names or [])
        self.logger = logger

        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()

        self.data_shapes = _as_desc_list(data_shapes)
        self.label_shapes = _as_desc_list(label_shapes) if label_shapes else []
        self.data_names = [d.name for d in self.data_shapes]
        self.label_names = [d.name for d in self.label_shapes]

        self.batch_size = self.data_shapes[0].shape[0]
        self._data_par = len(contexts)
        if mesh_config is not None:
            sizes = mesh_config.resolve(len(contexts))
            self._data_par = sizes[mesh_config.names.index("data")]
        if self.batch_size % max(1, self._data_par) != 0:
            raise MXNetError("batch size %d must be divisible by the data-"
                             "parallel degree %d" % (self.batch_size,
                                                     self._data_par))

        # gradient requests
        if isinstance(grad_req, str):
            base_req = grad_req
        else:
            base_req = None
        self.grad_req = {}
        for name in self.arg_names:
            if name in self.param_names:
                req = (base_req or (grad_req.get(name, "write")
                                    if isinstance(grad_req, dict) else "write"))
                if not for_training or name in self.fixed_param_names:
                    req = "null"
            elif name in self.data_names:
                req = "write" if (for_training and inputs_need_grad) else "null"
            else:
                req = "null"
            self.grad_req[name] = req

        self._mesh = None
        self._data_sharding = None
        self._rep_sharding = None
        self._input_shardings = {}
        self._param_mesh_axes = {}
        self._model_par = 1
        self._seq_par = 1
        self._expert_par = 1
        # params (and their aux/grads) eligible for tensor-parallel
        # annotation; inputs/labels never are
        self._tp_param_names = set(self.param_names) | set(self.aux_names)
        if len(contexts) > 1:
            self._build_mesh()

        self._bind_exec(shared_group)

    # ------------------------------------------------------------------
    def _build_mesh(self):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        devices = [c.jax_device for c in self.contexts]
        if len(set(devices)) != len(devices):
            # fake multi-context on one physical device (reference test trick):
            # fall back to single-device execution, semantics unchanged
            self.logger.debug("contexts map to %d physical device(s); running "
                              "unsharded", len(set(devices)))
            return
        if self.mesh_config is not None:
            from ..parallel.mesh import build_mesh

            self._mesh = build_mesh(self.mesh_config, devices)
            axis_sizes = dict(zip(self.mesh_config.names,
                                  self.mesh_config.resolve(len(devices))))
            self._model_par = axis_sizes["model"]
            self._seq_par = axis_sizes.get("seq", 1)
            self._expert_par = axis_sizes.get("expert", 1)
        else:
            self._mesh = Mesh(np.array(devices), ("data",))
            self._model_par = 1
            self._seq_par = 1
            self._expert_par = 1
        self._data_sharding = NamedSharding(self._mesh, P("data"))
        self._rep_sharding = NamedSharding(self._mesh, P())
        # per-input shardings from the DataDesc layouts, fixed at bind time:
        # the batch axis (N) shards on 'data'; with seq>1 the time axis (T)
        # shards on 'seq' — sequence/context parallelism, GSPMD inserting
        # the collectives (leapfrogs SURVEY §2.5 'Sequence-length scaling':
        # the reference buckets, the TPU build shards time)
        self._input_shardings = {}
        for desc in self.data_shapes + (self.label_shapes or []):
            layout = getattr(desc, "layout", None) or ""
            if self._seq_par > 1 and "T" in layout and "N" in layout:
                spec = [None] * len(desc.shape)
                spec[layout.index("N")] = "data"
                spec[layout.index("T")] = "seq"
                self._input_shardings[desc.name] = \
                    NamedSharding(self._mesh, P(*spec))
        # op-declared param mesh axes (OpDef.mesh_axes, e.g. MoE expert
        # stacks): walk the graph once and map each variable that feeds such
        # an argument to its axis
        axis_sizes = dict(self._mesh.shape)
        # per-param placement records for the sharding-coverage lint
        # pass (analysis.passes.ShardingCoveragePass): which params a
        # plan claimed, which silently degraded to replication
        self._sharding_coverage = {}
        self._param_mesh_axes = {}
        for node in self.symbol._topo():
            if node.is_variable or not node.op.mesh_axes:
                continue
            arg_names = node.op.list_arguments(node.parsed_attrs())
            for (inode, _), arg in zip(node.inputs, arg_names):
                axis = node.op.mesh_axes.get(arg)
                if axis and inode.is_variable \
                        and axis_sizes.get(axis, 1) > 1:
                    self._param_mesh_axes[inode.name] = axis
        # Megatron column/row pairing for the 'model' axis, derived from one
        # graph walk (parallel/tp_rules.py) — one psum per FC/Conv pair
        # instead of the naive plan's per-layer all-gathers
        # None = planner didn't run (naive mode); {} = planner ran and found
        # nothing shardable (replicate, do NOT fall back to the naive
        # per-layer all-gather plan megatron mode exists to avoid)
        self._tp_plan = None
        if self._model_par > 1:
            from .. import config as _config

            if _config.get("MXNET_TP_MODE") != "naive":
                from ..parallel.tp_rules import plan_tensor_parallel

                self._tp_plan = plan_tensor_parallel(self.symbol)

    def _input_sharding(self, name):
        return self._input_shardings.get(name, self._data_sharding)

    def _param_sharding(self, name, shape):
        """Tensor-parallel sharding rule over the 'model' mesh axis.

        The scaling-book recipe rather than hand-written psums: weights are
        annotated and the GSPMD partitioner derives activation shardings and
        inserts the collectives.  Which weights, and along which dim, comes
        from per-op graph metadata — OpDef.mesh_axes (expert stacks) first,
        then the Megatron column/row plan (parallel/tp_rules.py) that pairs
        FC1-column with FC2-row so one psum per pair replaces per-layer
        all-gathers.  MXNET_TP_MODE=naive restores the round-3 blanket
        dim-0 heuristic for A/B measurement.  Params whose sharded dim
        doesn't divide the axis stay replicated (correctness unaffected).
        """
        from jax.sharding import NamedSharding, PartitionSpec as P

        # coverage record for the sharding-coverage pass: every exit
        # below stamps what happened to this param (matched spec,
        # intentional replicate, or a silent degrade)
        rec = {"shape": [int(d) for d in shape or ()],
               "source": "scalar" if not shape else "default"}
        self._sharding_coverage[name] = rec
        # op-declared axes first (OpDef.mesh_axes — e.g. MoE expert stacks
        # shard dim 0 on 'expert'); graph metadata, not name matching
        axis = self._param_mesh_axes.get(name)
        if axis is not None and shape:
            if shape[0] % dict(self._mesh.shape)[axis] == 0:
                spec = [axis] + [None] * (len(shape) - 1)
                rec["source"], rec["spec"] = "mesh_axes", list(spec)
                return NamedSharding(self._mesh, P(*spec))
            # the op DECLARED this axis — losing it to divisibility is
            # the silent degrade the coverage pass turns into an error
            rec["source"], rec["degrade"] = "mesh_axes", "indivisible"
        if self._model_par <= 1 or not shape:
            return self._rep_sharding
        if self._tp_plan is not None:
            spec = self._tp_plan.get(name)
            if spec is None:
                return self._rep_sharding
            if len(spec) != len(shape):
                rec["source"], rec["degrade"] = "plan", "rank-mismatch"
                return self._rep_sharding
            for dim, ax in enumerate(spec):
                if ax is not None and shape[dim] % self._model_par != 0:
                    rec["source"], rec["degrade"] = "plan", "indivisible"
                    return self._rep_sharding  # unshardable: replicate
            rec["source"], rec["spec"] = "plan", list(spec)
            rec.pop("degrade", None)
            return NamedSharding(self._mesh, P(*spec))
        # naive mode: blanket dim-0 column sharding
        if shape[0] % self._model_par != 0:
            return self._rep_sharding
        spec = ["model"] + [None] * (len(shape) - 1)
        if rec.get("degrade") is None:
            rec["source"], rec["spec"] = "naive", list(spec)
        return NamedSharding(self._mesh, P(*spec))

    def _place(self, arr, sharded, name=None):
        """device_put an NDArray's buffer onto the bound device(s): data
        sharding for batch inputs, the tensor-parallel rule for named
        params (replicated when model==1), else replicated.  No-op when
        already placed."""
        import jax

        if self._mesh is None:
            target = self.contexts[0].jax_device
        elif sharded:
            target = self._input_sharding(name) if name is not None \
                else self._data_sharding
        elif name is not None and (self._model_par > 1
                                   or self._param_mesh_axes) \
                and name in self._tp_param_names:
            target = self._param_sharding(name, arr.shape)
        else:
            target = self._rep_sharding
        arr._set_data(jax.device_put(arr.data, target))
        return arr

    # ------------------------------------------------------------------
    def _bind_exec(self, shared_group):
        kwargs = {d.name: d.shape for d in self.data_shapes + self.label_shapes}
        type_dict = {d.name: d.dtype for d in self.data_shapes + self.label_shapes}
        shared_exec = shared_group.execs[0] if shared_group is not None else None
        ctx = self.contexts[0]
        exec_ = Executor.simple_bind(self.symbol, ctx, grad_req=self.grad_req,
                                     type_dict=type_dict, shared_exec=shared_exec,
                                     **kwargs)
        # ops with GSPMD-opaque fast paths (pallas kernels) must fall back
        # when this executor's buffers are mesh-sharded; ops with
        # mesh-aware shardings (sparse MoE dispatch) get the mesh itself
        exec_._mesh_active = self._mesh is not None
        exec_._mesh = self._mesh
        # uint8 DATA inputs (compact image batches) cast to float at the
        # graph boundary; other uint8 args keep their dtype
        exec_._u8_cast_names = set(self.data_names)
        # shard data args on the mesh; params replicate (or shard on the
        # model axis under tensor parallelism), grads/aux follow their param
        for name, arr in exec_.arg_dict.items():
            self._place(arr, sharded=name in self.data_names
                        or name in self.label_names, name=name)
        for name, arr in exec_.aux_dict.items():
            self._place(arr, sharded=False, name=name)
        for name, arr in exec_.grad_dict.items():
            self._place(arr, sharded=False, name=name)
        self.execs = [exec_]
        self.exec_ = exec_
        self.data_arrays = [exec_.arg_dict[n] for n in self.data_names]
        self.label_arrays = [exec_.arg_dict[n] for n in self.label_names
                             if n in exec_.arg_dict]
        self.param_arrays = [exec_.arg_dict[n] for n in self.param_names]
        self.grad_arrays = [exec_.grad_dict.get(n) for n in self.param_names]
        self.aux_arrays = [exec_.aux_dict[n] for n in self.aux_names]
        self.input_grad_arrays = [exec_.grad_dict.get(n) for n in self.data_names] \
            if self.inputs_need_grad else []

    # ------------------------------------------------------------------
    def reshape(self, data_shapes, label_shapes):
        if _as_desc_list(data_shapes) == self.data_shapes and \
                _as_desc_list(label_shapes or []) == self.label_shapes:
            return

        # share the old executor so parameter buffers (same shapes) carry
        # over — only shape-changed inputs/outputs are reallocated
        class _Shared:
            pass

        shared = _Shared()
        shared.execs = list(self.execs)
        self.__init__(self.symbol, self.contexts, None, data_shapes, label_shapes,
                      self.param_names, self.for_training, self.inputs_need_grad,
                      shared_group=shared,
                      fixed_param_names=self.fixed_param_names,
                      grad_req=self.grad_req, mesh_config=self.mesh_config)

    def set_params(self, arg_params, aux_params):
        for name, arr in arg_params.items():
            if name in self.exec_.arg_dict:
                arr.copyto(self.exec_.arg_dict[name])
                self._place(self.exec_.arg_dict[name], sharded=False,
                            name=name)
        for name, arr in (aux_params or {}).items():
            if name in self.exec_.aux_dict:
                arr.copyto(self.exec_.aux_dict[name])
                self._place(self.exec_.aux_dict[name], sharded=False,
                            name=name)

    def get_params(self, arg_params, aux_params):
        for name in self.param_names:
            self.exec_.arg_dict[name].copyto(arg_params[name])
        for name in self.aux_names:
            self.exec_.aux_dict[name].copyto(aux_params[name])

    # ------------------------------------------------------------------
    def load_data_batch(self, data_batch):
        for name, arr in zip(self.data_names, data_batch.data):
            dst = self.exec_.arg_dict[name]
            dst._set_data(arr.data.astype(dst.dtype) if arr.dtype != dst.dtype
                          else arr.data)
            self._place(dst, sharded=True, name=name)
        if self.label_names and data_batch.label:
            for name, arr in zip(self.label_names, data_batch.label):
                if name in self.exec_.arg_dict:
                    dst = self.exec_.arg_dict[name]
                    dst._set_data(arr.data.astype(dst.dtype)
                                  if arr.dtype != dst.dtype else arr.data)
                    self._place(dst, sharded=True, name=name)

    def _ensure_placement(self):
        """Re-pin params/grads/aux to the mesh (replicated).  Eager optimizer
        updates and kvstore pulls commit results to a single device; this
        restores the mesh sharding before the next compiled step.  device_put
        with an unchanged sharding is a no-op, so the steady-state cost is
        nil."""
        if self._mesh is None:
            return
        for name, arr in zip(self.param_names + self.aux_names,
                             self.param_arrays + self.aux_arrays):
            self._place(arr, sharded=False, name=name)
        for name, arr in zip(self.param_names + self.data_names,
                             self.grad_arrays + self.input_grad_arrays):
            if arr is not None:
                self._place(arr, sharded=False, name=name)

    def forward(self, data_batch, is_train=None):
        self.load_data_batch(data_batch)
        self._ensure_placement()
        if is_train is None:
            is_train = self.for_training
        self.exec_.forward(is_train=is_train)

    def backward(self, out_grads=None):
        assert self.for_training, "re-bind with for_training=True to run backward"
        self.exec_.backward(out_grads)

    def get_outputs(self, merge_multi_context=True):
        return list(self.exec_.outputs)

    def get_input_grads(self, merge_multi_context=True):
        return [self.exec_.grad_dict[n] for n in self.data_names]

    def update_metric(self, eval_metric, labels):
        from .. import metric as metric_mod

        # pull only the output heads the metric actually consumes
        # (metric.output_indices); every head it doesn't name stays an
        # unmaterialized device array instead of riding a d2h transfer
        eval_metric.update(
            labels, list(metric_mod.select_outputs(eval_metric,
                                                   self.exec_.outputs)))

    def install_monitor(self, mon):
        for exe in self.execs:
            mon.install(exe)
