"""PipelineModule — GPipe pipeline parallelism through the Module API.

The reference reaches model parallelism through a user-facing API
(``group2ctx`` stage annotations driven from ``Module``,
``example/model-parallel-lstm/lstm.py:48-112``); round 3 left the TPU
pipeline engine (``parallel/pipeline.py``) as a library function reachable
only from raw ``shard_map``.  This module closes that gap: the user
describes a pipeline with Symbols and trains it with the ordinary
``Module.fit`` workflow (bind / init_params / init_optimizer / fit /
score), while the module compiles ONE donated XLA program per step that
runs embed -> GPipe fill-drain schedule over the 'pipe' mesh axis ->
head, with the optimizer update fused in (the reference's
update-per-batch, as one program).

Pipeline model:

* ``stage_symbol`` — ONE stage's computation, input variable ``data``,
  single output of the same shape (e.g. an LSTM/transformer block).  The
  module stacks its parameters ``num_stages`` times with a leading stage
  axis sharded on 'pipe' — each device owns one stage's weights, stage s
  applies slice s.  HETEROGENEOUS stages: pass a LIST of per-stage
  symbols instead — they must share the same graph structure and
  parameter names, but internal widths may differ per stage (the
  reference pipelines arbitrary group2ctx graphs; here different-width
  stages cover the common case).  Each parameter is zero-padded to the
  max shape across stages before stacking; the padding is EXACT — padded
  weight columns/rows are zero, so padded activation lanes contribute
  nothing through the next projection and receive zero gradients —
  provided the ops between a stage's projections are lane-local AND
  zero-preserving (relu/tanh/softsign activations, Dropout, adds —
  sigmoid maps the padded zeros to 0.5, which the optimizer then turns
  into live phantom lanes).  bind rejects stages whose structures differ
  and non-zero-preserving Activation types; a feature-reducing op inside
  the padded region (LayerNorm over the hidden dim) remains the caller's
  contract to avoid.
* ``embed_symbol`` (optional) — maps the raw batch to the stage
  activation shape (e.g. Embedding); runs data-parallel before the pipe.
* ``head_symbol`` — consumes the pipeline output (input ``data``) plus
  label variables and ends in a loss op (e.g. SoftmaxOutput); runs
  data-parallel after the pipe.

The batch (axis 0) is split into ``num_microbatches`` microbatches that
flow through stages via ``lax.ppermute``; devices along the mesh's 'data'
axis additionally shard every microbatch (data parallelism composes).
Backward needs no schedule of its own: the fill-drain scan is
differentiable, so ``jax.vjp`` of the whole step yields the reverse
pipeline (parallel/pipeline.py).

Constraints (raised at bind): symbols must be free of auxiliary state
(use LayerNorm-style ops, not BatchNorm, inside stages) and the optimizer
must provide a fused kernel (all first-party ones do).
"""
from __future__ import annotations

import logging

import numpy as np

from ..base import MXNetError
from ..context import Context
from ..registry import OpContext
from .. import ndarray as nd
from .. import optimizer as opt_mod
from .base_module import BaseModule
from ..io import DataDesc

__all__ = ["PipelineModule"]


def _symbol_fn(symbol):
    """Compile a Symbol into a pure function {name: jnp} -> [outputs].

    A trimmed executor walk (no aux, no placement): PipelineModule symbols
    are stateless by contract, so the graph is a pure function suitable
    for use inside shard_map/scan.
    """
    if symbol.list_auxiliary_states():
        raise MXNetError(
            "PipelineModule symbols must not carry auxiliary state "
            "(BatchNorm moving stats etc.); use stateless normalization "
            "inside pipeline stages")
    nodes = list(symbol._topo())
    outputs = symbol._outputs

    def fn(env, is_train, rng=None):
        import jax

        values = {}
        for seq, node in enumerate(nodes):
            if node.is_variable:
                values[(id(node), 0)] = env[node.name]
                continue
            attrs = node.parsed_attrs()
            n_args = node.op.n_inputs(attrs)
            ins = [values[(id(s), i)] for s, i in node.inputs[:n_args]]
            node_rng = jax.random.fold_in(rng, seq) if rng is not None \
                else None
            octx = OpContext(is_train=is_train, rng=node_rng,
                             mesh_active=True)
            outs, _ = node.op.fcompute(attrs, ins, [], octx)
            for i, o in enumerate(outs):
                values[(id(node), i)] = o
        return [values[(id(n), i)] for n, i in outputs]

    return fn


# attrs that set a layer's WIDTH — the one thing heterogeneous stages are
# allowed to vary; everything else (op kinds, activation types, wiring)
# must match because execution traces stage 0's graph for all stages
_WIDTH_ATTRS = frozenset(["num_hidden", "num_filter", "hidden_size"])


def _stage_structure_signature(symbol):
    """Hashable (op, non-width attrs, wiring) sequence of a stage graph."""
    nodes = list(symbol._topo())
    index = {id(n): i for i, n in enumerate(nodes)}
    sig = []
    for n in nodes:
        if n.is_variable:
            sig.append(("var", n.name))
            continue
        attrs = {k: v for k, v in sorted(n.parsed_attrs().items())
                 if k not in _WIDTH_ATTRS}
        wiring = tuple((index[id(s)], i) for s, i in n.inputs)
        sig.append((n.op.name, tuple(attrs.items()), wiring))
    return tuple(sig)


# ---------------------------------------------------------------------------
# zero-preservation scan for width-padded heterogeneous stages
# ---------------------------------------------------------------------------
# Padded weight columns/rows are zero, so padded activation lanes stay zero
# through the projections — but only while every elementwise op in between
# maps 0 -> 0 (and finitely).  The guard used to inspect `Activation` nodes
# only, so elementwise ops registered under their own names (sym.sigmoid,
# sym.exp, sym.cos, softrelu, SoftmaxActivation, _plus_scalar, ...) slipped
# past the bind-time rejection and silently animated the padded lanes.  The
# scan now covers the whole elementwise universe: an allowlist of known
# f(0)=0 ops, attr-conditional checks for the handful whose behaviour at 0
# depends on parameters, and fail-closed rejection for every other
# elementwise-family name (so a newly registered f(0)!=0 op is caught here
# rather than corrupting training).

# elementwise ops with f(0) = 0 and finite, unconditionally safe on padded
# lanes (LeakyReLU: every act_type — leaky/elu/prelu/rrelu — fixes 0).
# Two-input forms are listed when f(0, 0) = 0 and finite — both operands
# of an in-stage binary op carry the same zeroed padded lanes (the stage's
# lane-locality contract): add/sub/mul/max/min/hypot qualify; div and mod
# (0/0 = nan), power (0^0 = 1), and the =/>=/<= comparisons (f(0,0) = 1)
# do not and are caught fail-closed below.
_ZERO_PRESERVING_ELEMWISE = frozenset({
    "abs", "sign", "rint", "ceil", "floor", "trunc", "fix", "round",
    "square", "sqrt", "cbrt", "expm1", "log1p", "sin", "tan", "arcsin",
    "arctan", "sinh", "tanh", "arcsinh", "arctanh", "degrees", "radians",
    "erf", "negative", "relu", "softsign", "smooth_l1",
    "_copy", "Cast", "Dropout", "LeakyReLU", "BlockGrad",
    "_mul_scalar", "_div_scalar", "_mod_scalar",
    "_plus", "_minus", "_mul", "_maximum", "_minimum", "_hypot",
    "broadcast_add", "broadcast_sub", "broadcast_mul",
    "broadcast_maximum", "broadcast_minimum", "broadcast_hypot",
    "broadcast_not_equal", "broadcast_greater", "broadcast_lesser",
    "add_n", "_grad_add",
})

# Activation act_types with f(0) = 0
_ZERO_PRESERVING_ACT_TYPES = ("relu", "tanh", "softsign")


_ELEMWISE_FAMILY = None  # computed once, first padded-stage bind


def _elementwise_family():
    """Every registered elementwise-family op name: the live unary table,
    the two-tensor/broadcast/scalar binary and logic forms, and the nn
    activation wrappers.  Built from the op tables themselves so new
    elementwise registrations are covered without touching this module."""
    global _ELEMWISE_FAMILY
    if _ELEMWISE_FAMILY is not None:
        return _ELEMWISE_FAMILY
    from ..ops.elemwise import _unary_table

    names = set(_unary_table())
    binary = ("plus", "minus", "mul", "div", "mod", "power", "maximum",
              "minimum", "hypot")
    logic = ("equal", "not_equal", "greater", "greater_equal", "lesser",
             "lesser_equal")
    # canonical registered names (aliases resolve to these): the
    # two-tensor form is _<name>, the broadcast form broadcast_<canon>
    # for arithmetic and broadcast_<name> for logic
    canon = {"plus": "add", "minus": "sub"}
    names.update("_%s" % n for n in binary)
    names.update("broadcast_%s" % canon.get(n, n) for n in binary)
    names.update("_%s_scalar" % n for n in binary + logic)
    names.update("_r%s_scalar" % n for n in ("minus", "div", "power", "mod"))
    names.update("broadcast_%s" % n for n in logic)
    names.update({"Activation", "LeakyReLU", "SoftmaxActivation", "clip",
                  "smooth_l1", "Cast", "_copy", "Dropout", "BlockGrad",
                  "add_n", "_grad_add"})
    _ELEMWISE_FAMILY = frozenset(names)
    return _ELEMWISE_FAMILY


def _zero_preservation_violation(node):
    """Why this node breaks f(0)=0 on padded lanes, or None when safe.

    Non-elementwise ops (projections, reshapes, reductions) return None
    too: they are governed by the stage-structure / lane-locality contract
    in the class docstring, not by this scan.
    """
    name = node.op.name
    attrs = node.parsed_attrs()
    if name == "Activation":
        act = attrs.get("act_type", "relu")
        if act in _ZERO_PRESERVING_ACT_TYPES:
            return None
        return "Activation act_type=%r" % act
    if name == "clip":
        lo, hi = attrs.get("a_min", 0.0), attrs.get("a_max", 0.0)
        return None if lo <= 0.0 <= hi else \
            "clip bounds [%s, %s] excluding 0" % (lo, hi)
    if name == "_power_scalar":
        c = attrs.get("scalar", 0.0)
        return None if c > 0 else "_power_scalar exponent %s" % c
    if name == "_maximum_scalar":
        c = attrs.get("scalar", 0.0)
        return None if c <= 0 else "_maximum_scalar with scalar %s" % c
    if name == "_minimum_scalar":
        c = attrs.get("scalar", 0.0)
        return None if c >= 0 else "_minimum_scalar with scalar %s" % c
    if name in _ZERO_PRESERVING_ELEMWISE:
        return None
    if name in _elementwise_family():
        return "%r (f(0) != 0)" % name
    return None


class PipelineModule(BaseModule):
    def __init__(self, stage_symbol, head_symbol, num_stages,
                 num_microbatches, embed_symbol=None, context=None,
                 remat=False, logger=logging):
        """``remat=True`` checkpoints each GPipe schedule step: backward
        recomputes the stage body instead of storing its internals for
        all M + S - 1 steps — measured 2.6x lower temp memory on a deep
        stage at identical gradients (the scan-compatible answer to
        1F1B's memory motivation), for ~1 extra forward of compute."""
        super().__init__(logger=logger)
        self._remat = bool(remat)
        if isinstance(stage_symbol, (list, tuple)):
            if len(stage_symbol) != int(num_stages):
                raise MXNetError(
                    "heterogeneous pipeline: %d stage symbols for "
                    "num_stages=%d" % (len(stage_symbol), num_stages))
            self._stage_syms = list(stage_symbol)
            stage_symbol = self._stage_syms[0]
        else:
            self._stage_syms = None      # homogeneous: one symbol stacked
        self._stage_sym = stage_symbol
        self._head_sym = head_symbol
        self._embed_sym = embed_symbol
        self._num_stages = int(num_stages)
        self._num_micro = int(num_microbatches)
        if context is None:
            context = [Context("cpu", i) for i in range(num_stages)]
        if not isinstance(context, (list, tuple)):
            context = [context]
        self._context = [c if isinstance(c, Context) else Context(c)
                         for c in context]
        if len(self._context) % self._num_stages:
            raise MXNetError("need a multiple of num_stages devices "
                             "(%d given for %d stages)"
                             % (len(self._context), self._num_stages))
        self._data_par = len(self._context) // self._num_stages

        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._outputs = None
        # async-loop state: device-side metric accumulation folded into the
        # pipelined step program (metric.DeviceMetricAccumulator) + a step
        # counter for MXNET_METRIC_SYNC_PERIOD
        self._pending_metric = None
        self._metric_acc = None
        self._metric_traced = False
        self._num_steps = 0
        # whether _outputs came from a train step (device-accumulated) or a
        # forward-only program (score/predict: metrics update on the host)
        self._outputs_from_step = False

    # ------------------------------------------------------------------
    @property
    def data_names(self):
        return ["data"]

    @property
    def output_names(self):
        return self._head_sym.list_outputs()

    @property
    def symbol(self):
        return self._head_sym

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._output_shapes

    # ------------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        self._data_shapes = [d if isinstance(d, DataDesc) else
                             DataDesc(d[0], d[1]) for d in data_shapes]
        self._label_shapes = [d if isinstance(d, DataDesc) else
                              DataDesc(d[0], d[1])
                              for d in (label_shapes or [])]
        batch = self._data_shapes[0].shape[0]
        if batch % self._num_micro:
            raise MXNetError("batch %d not divisible by num_microbatches %d"
                             % (batch, self._num_micro))
        mb = batch // self._num_micro
        if mb % self._data_par:
            raise MXNetError("microbatch %d not divisible by data-parallel "
                             "degree %d" % (mb, self._data_par))
        self._batch = batch
        self._mb = mb

        # shape inference through the three sections
        in_shape = self._data_shapes[0].shape
        if self._embed_sym is not None:
            eargs, eout, _ = self._embed_sym.infer_shape(
                data=(mb,) + in_shape[1:])
            act_shape = eout[0]
            self._embed_shapes = dict(zip(self._embed_sym.list_arguments(),
                                          eargs))
            self._embed_shapes.pop("data")
        else:
            act_shape = (mb,) + in_shape[1:]
            self._embed_shapes = {}
        self._act_shape = tuple(act_shape)
        if self._stage_syms is None:
            sargs, souts, _ = self._stage_sym.infer_shape(data=act_shape)
            if tuple(souts[0]) != tuple(act_shape):
                raise MXNetError("stage must preserve the activation shape "
                                 "(got %s from %s)" % (souts[0], act_shape))
            self._stage_shapes = dict(zip(self._stage_sym.list_arguments(),
                                          sargs))
            self._stage_shapes.pop("data")
            self._stage_true_shapes = None
        else:
            # heterogeneous: same structure/arg names required; params pad
            # to the per-name max shape across stages
            names0 = self._stage_syms[0].list_arguments()
            sig0 = _stage_structure_signature(self._stage_syms[0])
            per_stage = []
            for k, s in enumerate(self._stage_syms):
                if s.list_arguments() != names0:
                    raise MXNetError(
                        "heterogeneous pipeline stages must share parameter"
                        " structure: stage %d has args %s, stage 0 has %s"
                        % (k, s.list_arguments(), names0))
                sig = _stage_structure_signature(s)
                if sig != sig0:
                    raise MXNetError(
                        "heterogeneous pipeline stages must share graph "
                        "STRUCTURE (ops, attrs, wiring) — only widths may "
                        "differ; stage %d diverges from stage 0:\n  %s\n"
                        "  vs\n  %s" % (k, sig, sig0))
                sargs, souts, _ = s.infer_shape(data=act_shape)
                if tuple(souts[0]) != tuple(act_shape):
                    raise MXNetError(
                        "stage %d must preserve the activation shape "
                        "(got %s from %s)" % (k, souts[0], act_shape))
                shapes = dict(zip(names0, sargs))
                shapes.pop("data")
                per_stage.append(shapes)
            self._stage_true_shapes = per_stage
            self._stage_shapes = {}
            for name in per_stage[0]:
                dims = {len(sh[name]) for sh in per_stage}
                if len(dims) != 1:
                    raise MXNetError(
                        "stage param %r rank differs across stages" % name)
                self._stage_shapes[name] = tuple(
                    max(sh[name][i] for sh in per_stage)
                    for i in range(dims.pop()))
            # the zero-preserving-activation constraint only binds for
            # stages that actually carry padded lanes (a same-width list,
            # or the widest stage of a mixed one, has none)
            for k, s in enumerate(self._stage_syms):
                padded = any(tuple(per_stage[k][n]) != self._stage_shapes[n]
                             for n in per_stage[k])
                if not padded:
                    continue
                for node in s._topo():
                    if node.is_variable:
                        continue
                    why = _zero_preservation_violation(node)
                    if why is not None:
                        raise MXNetError(
                            "heterogeneous pipeline stage %d is width-"
                            "padded and needs zero-preserving elementwise"
                            " ops (f(0)=0, e.g. relu/tanh/softsign); %s "
                            "would turn the zero padding into live lanes"
                            % (k, why))

        head_kwargs = {"data": (batch,) + tuple(act_shape[1:])}
        for d in self._label_shapes:
            head_kwargs[d.name] = d.shape
        hargs, houts, _ = self._head_sym.infer_shape(**head_kwargs)
        self._head_shapes = dict(zip(self._head_sym.list_arguments(), hargs))
        self._head_shapes.pop("data")
        head_args = set(self._head_sym.list_arguments())
        self._label_names = [d.name for d in self._label_shapes
                             if d.name in head_args]
        self._label_shape_map = {d.name: tuple(d.shape)
                                 for d in self._label_shapes}
        # any head variable that is neither data nor a parameter we size
        # (e.g. an auto-created loss label) gets zeros when no label is fed
        for n in self._label_names:
            self._head_shapes.pop(n, None)
        self._extra_head_vars = {
            n: tuple(s) for n, s in zip(self._head_sym.list_arguments(),
                                        hargs)
            if n not in self._head_shapes and n != "data"}
        self._output_shapes = list(zip(self._head_sym.list_outputs(),
                                       [tuple(s) for s in houts]))

        # mesh: (pipe, data)
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        devices = [c.jax_device for c in self._context]
        if len(set(devices)) != len(devices):
            raise MXNetError("PipelineModule needs distinct devices (use "
                             "the 8-virtual-CPU test mesh or real chips)")
        self._mesh = Mesh(np.array(devices).reshape(
            self._num_stages, self._data_par), ("pipe", "data"))
        self._stage_sharding = {
            n: NamedSharding(self._mesh, P(*(("pipe",) + (None,) * len(s))))
            for n, s in self._stage_shapes.items()}
        self._rep_sharding = NamedSharding(self._mesh, P())
        self._x_sharding = NamedSharding(
            self._mesh, P("data", *([None] * (len(in_shape) - 1))))

        self._stage_fn = _symbol_fn(self._stage_sym)
        self._head_fn = _symbol_fn(self._head_sym)
        self._embed_fn = (_symbol_fn(self._embed_sym)
                          if self._embed_sym is not None else None)
        self.for_training = for_training
        self._step = None
        self._fwd_fns = {}
        self._hyper_cache = None
        self._detach_metric()
        self.binded = True

    # ------------------------------------------------------------------
    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False,
                    allow_extra=False):
        assert self.binded
        if self.params_initialized and not force_init:
            return
        from ..initializer import InitDesc, Uniform

        initializer = initializer or Uniform(0.01)
        import jax

        def make(name, shape):
            arr = nd.zeros(shape)
            initializer(InitDesc(name), arr)
            return np.asarray(arr.asnumpy())

        params = {}
        for name, shape in self._stage_shapes.items():
            if arg_params and name in arg_params:
                stacked = arg_params[name].asnumpy()
                self._check_padding_invariant(name, stacked)
            elif self._stage_true_shapes is None:
                stacked = np.stack([make(name, shape)
                                    for _ in range(self._num_stages)])
            else:
                # heterogeneous: initialize each stage at its TRUE shape
                # inside a zero block — the zero padding is what makes the
                # max-shape stacking exact (see module docstring)
                stacked = np.zeros((self._num_stages,) + tuple(shape),
                                   np.float32)
                for k, true in enumerate(self._stage_true_shapes):
                    block = make(name, true[name])
                    idx = (k,) + tuple(slice(0, d) for d in true[name])
                    stacked[idx] = block
            params[name] = jax.device_put(stacked.astype(np.float32),
                                          self._stage_sharding[name])
        for shapes in (self._embed_shapes, self._head_shapes):
            for name, shape in shapes.items():
                if arg_params and name in arg_params:
                    host = arg_params[name].asnumpy()
                else:
                    host = make(name, shape)
                params[name] = jax.device_put(host.astype(np.float32),
                                              self._rep_sharding)
        self._params = params
        self.params_initialized = True

    def _check_padding_invariant(self, name, stacked):
        """Heterogeneous stacking is exact ONLY with zero padding; reject
        caller-supplied stage params (init_params AND set_params /
        checkpoint loads) that violate it instead of silently computing a
        different network."""
        if self._stage_true_shapes is None or \
                name not in self._stage_shapes:
            return
        for k, true in enumerate(self._stage_true_shapes):
            block = np.array(stacked[k], copy=True)
            block[tuple(slice(0, d) for d in true[name])] = 0
            if np.any(block):
                raise MXNetError(
                    "heterogeneous pipeline param %r stage %d has nonzero "
                    "values outside its true shape %s — the zero-padding "
                    "invariant would be violated" % (name, k, true[name]))

    def get_params(self):
        return ({n: nd.array(np.asarray(v)) for n, v in self._params.items()},
                {})

    def set_params(self, arg_params, aux_params=None, allow_missing=False,
                   force_init=True, allow_extra=False):
        import jax

        for n, v in (arg_params or {}).items():
            if n not in self._params:
                if not allow_extra:
                    raise MXNetError("unknown param %r" % n)
                continue
            host = v.asnumpy().astype(np.float32)
            self._check_padding_invariant(n, host)
            sh = (self._stage_sharding[n] if n in self._stage_shapes
                  else self._rep_sharding)
            self._params[n] = jax.device_put(host, sh)
        self.params_initialized = True

    # ------------------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        if isinstance(optimizer, str):
            optimizer_params = dict(optimizer_params)
            optimizer_params.setdefault("rescale_grad", 1.0 / self._batch)
            idx2name = {i: n for i, n in enumerate(sorted(self._params))}
            optimizer = opt_mod.create(optimizer, param_idx2name=idx2name,
                                       **optimizer_params)
        self._optimizer = optimizer
        kernel = optimizer.fused_kernel()
        if kernel is None:
            raise MXNetError("PipelineModule needs an optimizer with a "
                             "fused kernel (got %s)"
                             % type(optimizer).__name__)
        self._make_slots, self._opt_apply = kernel
        self._param_order = sorted(self._params)
        self._slots = {n: self._make_slots(self._params[n])
                       for n in self._param_order}
        self.optimizer_initialized = True

    # ------------------------------------------------------------------
    def _build_step(self):
        import jax
        import jax.numpy as jnp
        from ..parallel.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from ..parallel.pipeline import pipeline_apply

        mesh = self._mesh
        m, mb = self._num_micro, self._mb
        act_tail = self._act_shape[1:]
        stage_names = sorted(self._stage_shapes)
        stage_fn, head_fn, embed_fn = \
            self._stage_fn, self._head_fn, self._embed_fn
        label_names = self._label_names
        opt_apply = self._opt_apply
        order = self._param_order
        macc = self._metric_acc

        stage_specs = {n: P(*(("pipe",) + (None,) * len(s)))
                       for n, s in self._stage_shapes.items()}

        def pipe(sp, a, rng):
            def body(p, xx, key):
                stage_key = jax.random.fold_in(
                    key, jax.lax.axis_index("pipe"))

                def run_stage(pdict, act, mb_id):
                    # distinct stochastic-op keys per (stage, microbatch):
                    # fold the stage index, then the microbatch id the
                    # schedule hands us, so each microbatch draws its own
                    # dropout masks (reference semantics: a fresh mask per
                    # forward call, src/operator/dropout-inl.h)
                    skey = jax.random.fold_in(stage_key, mb_id)
                    env = dict(pdict)
                    env["data"] = act
                    return stage_fn(env, True, skey)[0]

                return pipeline_apply(run_stage, p, xx, "pipe", m,
                                      remat=self._remat)

            return shard_map(
                body, mesh=mesh,
                in_specs=(stage_specs, P(None, "data"), P()),
                out_specs=P(None, "data"))(sp, a, rng)

        def fwd(params, x, labels, rng):
            a = x
            if embed_fn is not None:
                env = {n: params[n] for n in self._embed_shapes}
                # embed runs per microbatch shape (mb, ...): flatten batch
                env["data"] = a.reshape((m * mb,) + a.shape[1:])
                a = embed_fn(env, True, rng)[0]
            a = jnp.reshape(a, (m, mb) + act_tail)
            sp = {n: params[n] for n in stage_names}
            piped = pipe(sp, a, rng)
            h = jnp.reshape(piped, (m * mb,) + act_tail)
            env = {n: params[n] for n in self._head_shapes}
            env["data"] = h
            for nme, shape in self._extra_head_vars.items():
                env[nme] = jnp.zeros(shape, jnp.float32)
            env.update(labels)
            return head_fn(env, True, rng)

        def step(params, slots, mstate, x, labels, lrs, wds, rescale, clip,
                 extra, rng):
            outs, vjp_fn = jax.vjp(
                lambda p: fwd(p, x, labels, rng), params)
            cts = [jnp.ones_like(o) for o in outs]
            (grads,) = vjp_fn(cts)
            new_params = dict(params)
            new_slots = {}
            for i, nme in enumerate(order):
                g = grads[nme].astype(params[nme].dtype)
                w, s = opt_apply(params[nme], g, slots[nme], lrs[i], wds[i],
                                 rescale, clip, extra)
                new_params[nme] = w.astype(params[nme].dtype)
                new_slots[nme] = tuple(
                    sn.astype(so.dtype) for sn, so in zip(s, slots[nme]))
            if macc is not None:
                # metric accumulation inside the pipelined program: reads
                # the head outputs/labels, feeds nothing back into training
                mstate = macc.update(mstate, [labels[n] for n in label_names],
                                     list(outs))
            return new_params, new_slots, mstate, outs

        return jax.jit(step, donate_argnums=(0, 1, 2))

    def _build_fwd_only(self, is_train):
        """Forward-only program (no grads, no update) for forward()/score."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from ..parallel.compat import shard_map

        from ..parallel.pipeline import pipeline_apply

        m, mb = self._num_micro, self._mb
        act_tail = self._act_shape[1:]
        stage_names = sorted(self._stage_shapes)
        stage_fn, head_fn, embed_fn = \
            self._stage_fn, self._head_fn, self._embed_fn

        stage_specs = {n: P(*(("pipe",) + (None,) * len(s)))
                       for n, s in self._stage_shapes.items()}

        def eval_fn(params, x, rng):
            a = x
            if embed_fn is not None:
                env = {n: params[n] for n in self._embed_shapes}
                env["data"] = a
                a = embed_fn(env, is_train, rng)[0]
            a = jnp.reshape(a, (m, mb) + act_tail)
            sp = {n: params[n] for n in stage_names}

            def body(p, xx, key):
                stage_key = jax.random.fold_in(
                    key, jax.lax.axis_index("pipe"))

                def run_stage(pdict, act, mb_id):
                    skey = jax.random.fold_in(stage_key, mb_id)
                    env = dict(pdict)
                    env["data"] = act
                    return stage_fn(env, is_train, skey)[0]

                return pipeline_apply(run_stage, p, xx, "pipe", m)

            piped = shard_map(
                body, mesh=self._mesh,
                in_specs=(stage_specs, P(None, "data"), P()),
                out_specs=P(None, "data"))(sp, a, rng)
            h = jnp.reshape(piped, (m * mb,) + act_tail)
            env = {n: params[n] for n in self._head_shapes}
            env["data"] = h
            for nme, shape in self._extra_head_vars.items():
                env[nme] = jnp.zeros(shape, jnp.float32)
            return head_fn(env, is_train, rng)

        return jax.jit(eval_fn)

    # ------------------------------------------------------------------
    # device-side metrics (same protocol as CompiledTrainStep)
    # ------------------------------------------------------------------
    def _bind_metric(self, eval_metric):
        from .. import config as _config

        self._pending_metric = None
        if not _config.get("MXNET_DEVICE_METRICS"):
            if self._metric_acc is not None:
                self._detach_metric()  # knob off: actually disarm
            return
        if self._metric_acc is not None \
                and self._metric_acc.metric is not eval_metric:
            self._detach_metric()
        self._pending_metric = eval_metric

    def _try_attach_metric(self, data_batch):
        from ..metric import DeviceMetricAccumulator

        metric = self._pending_metric
        self._pending_metric = None
        if not DeviceMetricAccumulator.supported(metric):
            return
        # device pairing must mirror the host update_metric(labels, outs)
        # call exactly: every iterator label must be a head input
        if len(data_batch.label or []) != len(self._label_names) or \
                [d.name for d in self._label_shapes] != self._label_names:
            return
        self._metric_acc = DeviceMetricAccumulator(metric)
        self._metric_acc.install()
        self._metric_traced = False
        self._step = None  # program signature changed: recompile

    def _detach_metric(self):
        if self._metric_acc is not None:
            self._metric_acc.uninstall()
            self._metric_acc = None
        self._metric_traced = False
        self._step = None

    def _dispatch_fence(self):
        if self._outputs:
            return self._outputs[0]
        return None

    # ------------------------------------------------------------------
    def forward_backward(self, data_batch):
        """One fused train step (forward + reverse pipeline + update)."""
        import jax

        from .. import random as _rnd

        if self._pending_metric is not None and self._metric_acc is None:
            self._try_attach_metric(data_batch)
        if self._step is None:
            self._step = self._build_step()
        x = jax.device_put(data_batch.data[0].data, self._x_sharding)
        labels = {}
        for nme, arr in zip([d.name for d in self._label_shapes],
                            data_batch.label or []):
            if nme in self._label_names:
                labels[nme] = jax.device_put(arr.data, self._rep_sharding)
        idx = list(range(len(self._param_order)))
        lrs, wds, rescale, clip = self._optimizer.fused_hyper(idx)
        extra = self._optimizer.fused_extra()
        # keep hypers device-resident across steps (one transfer total with
        # a constant schedule — same policy as train_step.py's fused step)
        cached = self._hyper_cache
        if cached is not None and np.array_equal(cached[0], lrs) \
                and np.array_equal(cached[1], wds) \
                and cached[2] == rescale and cached[3] == clip \
                and np.array_equal(cached[4], extra):
            lrs, wds, rescale, clip, extra = cached[5]
        else:
            import jax.numpy as jnp

            dev = (jnp.asarray(lrs), jnp.asarray(wds), rescale, clip,
                   jnp.asarray(extra))
            self._hyper_cache = (lrs, wds, rescale, clip, extra, dev)
            lrs, wds, rescale, clip, extra = dev
        acc = self._metric_acc
        mstate = acc.state if acc is not None else ()
        rng = _rnd.split_key()
        if acc is not None and not self._metric_traced:
            # trace-only validation (same policy as CompiledTrainStep.run):
            # eval_shape executes nothing, so a metric mirror that can't
            # trace against the head graph demotes to the host path
            # without risking the step's donated buffers
            try:
                jax.eval_shape(self._step, self._params, self._slots,
                               mstate, x, labels, lrs, wds, rescale, clip,
                               extra, rng)
                self._metric_traced = True
            except Exception as exc:
                self.logger.info("device metric accumulation unavailable "
                                 "(%s); metric stays on the host path", exc)
                self._detach_metric()
                self._step = self._build_step()
                acc, mstate = None, ()
        self._params, self._slots, mstate, outs = self._step(
            self._params, self._slots, mstate, x, labels, lrs, wds,
            rescale, clip, extra, rng)
        if acc is not None:
            acc.commit(mstate)
        self._num_steps += 1
        self._outputs = outs
        self._outputs_from_step = True

    def update(self):
        pass  # the optimizer update is fused into the step program

    def backward(self, out_grads=None):
        raise MXNetError("PipelineModule fuses forward/backward/update; "
                         "use forward_backward()")

    def forward(self, data_batch, is_train=None):
        """Forward only — never updates parameters (Module contract;
        training steps go through forward_backward)."""
        import jax

        from .. import random as _rnd

        if is_train is None:
            is_train = self.for_training
        if self._fwd_fns.get(bool(is_train)) is None:
            self._fwd_fns[bool(is_train)] = self._build_fwd_only(
                bool(is_train))
        x = jax.device_put(data_batch.data[0].data, self._x_sharding)
        self._outputs = self._fwd_fns[bool(is_train)](
            self._params, x, _rnd.split_key())
        self._outputs_from_step = False

    def get_outputs(self, merge_multi_context=True):
        return [nd.NDArray(o, self._context[0]) for o in self._outputs]

    def get_input_grads(self, merge_multi_context=True):
        raise MXNetError("inputs_need_grad is not supported by "
                         "PipelineModule")

    def update_metric(self, eval_metric, labels):
        acc = self._metric_acc
        # the device path only covers outputs the STEP program produced;
        # score()/predict() run the forward-only program (no accumulation
        # in it) and must keep updating on the host even when the same
        # metric object is armed for training (fit's validation_metric
        # defaults to the train metric)
        if acc is not None and acc.metric is eval_metric \
                and self._outputs_from_step:
            acc.maybe_drain(self._num_steps)
            return
        from .. import metric as metric_mod

        eval_metric.update(labels, metric_mod.select_outputs(
            eval_metric, self.get_outputs()))

    def install_monitor(self, mon):
        raise MXNetError("per-op monitoring is not available inside the "
                         "pipelined program; use NaiveEngine on a "
                         "non-pipelined Module to inspect values")
