"""Module — the standard symbol-backed module.

Reference: `python/mxnet/module/module.py` (708 LoC; bind:323,
init_optimizer:432 incl. kvstore wiring + rescale_grad conventions,
update:553).  Gradients from the mesh-sharded executor group are already
globally reduced (XLA psum), so `update` is: optimizer step through the
kvstore facade (update_on_kvstore) or the local updater.
"""
from __future__ import annotations

import logging

from .. import context as ctx_mod
from .. import ndarray as nd
from .. import optimizer as opt_mod
from ..base import MXNetError
from ..model import save_checkpoint, load_checkpoint, _create_kvstore
from .base_module import BaseModule
from .executor_group import DataParallelExecutorGroup


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None, compute_dtype=None,
                 mesh_config=None):
        super().__init__(logger=logger)
        # multi-axis parallelism over the bound contexts (parallel.MeshConfig:
        # data/model/pipe/seq/expert); None = pure data parallel
        self._mesh_config = mesh_config
        if compute_dtype is None:
            from .. import config as _config

            compute_dtype = _config.get("MXNET_COMPUTE_DTYPE") or None
        self._compute_dtype = compute_dtype
        # fused-train-step state (see ..train_step.CompiledTrainStep)
        self._fused_step = None
        self._fused_outputs = None
        self._fused_update_done = False   # update() becomes a no-op for it
        self._pending_metric = None       # metric to fold into the step
        self._step_stale = False          # executor arrays newer than step
        self._exec_stale = False          # step newer than executor arrays
        self._opt_owner = "eager"         # who holds live optimizer slots
        self._monitor = None
        # NOTE: _step_stale/_exec_stale are properties delegating to the
        # (possibly shared) fused step when one exists — several bucket
        # modules can view one master-weight store, so staleness must live
        # with the store, not the module
        if context is None:
            context = ctx_mod.cpu()
        if isinstance(context, ctx_mod.Context):
            context = [context]
        self._context = context
        self._work_load_list = work_load_list

        self._symbol = symbol
        data_names = list(data_names) if data_names is not None else []
        label_names = list(label_names) if label_names is not None else []

        arg_names = symbol.list_arguments()
        input_names = data_names + label_names
        self._param_names = [x for x in arg_names if x not in input_names]
        self._fixed_param_names = list(fixed_param_names or [])
        self._aux_names = symbol.list_auxiliary_states()
        self._data_names = data_names
        self._label_names = label_names
        self._state_names = list(state_names or [])
        self._output_names = symbol.list_outputs()

        self._arg_params = None
        self._aux_params = None
        self._params_dirty = False

        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None
        self._preload_opt_states = None
        self._exec_group = None
        self._data_shapes = None
        self._label_shapes = None

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        """Load from checkpoint (reference: module.py:86)."""
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        return mod

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        self._symbol.save("%s-symbol.json" % prefix)
        param_name = "%s-%04d.params" % (prefix, epoch)
        self.save_params(param_name)
        logging.info("Saved checkpoint to \"%s\"", param_name)
        if save_optimizer_states:
            state_name = "%s-%04d.states" % (prefix, epoch)
            self.save_optimizer_states(state_name)
            logging.info("Saved optimizer state to \"%s\"", state_name)

    # ------------------------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return [(n, o.shape) for n, o in
                zip(self._output_names, self._exec_group.get_outputs())] \
            if self._exec_group.exec_._outputs is not None else []

    # ------------------------------------------------------------------
    def get_params(self):
        assert self.binded and self.params_initialized
        if self._params_dirty:
            self._sync_params_from_devices()
        return (self._arg_params, self._aux_params)

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before initializing the parameters"

        param_names = self._param_names
        aux_names = self._aux_names
        if self._arg_params is None:
            self._arg_params = {
                name: nd.zeros(self._exec_group.exec_.arg_dict[name].shape,
                               dtype=self._exec_group.exec_.arg_dict[name].dtype)
                for name in param_names}
        if self._aux_params is None:
            self._aux_params = {
                name: nd.zeros(self._exec_group.exec_.aux_dict[name].shape,
                               dtype=self._exec_group.exec_.aux_dict[name].dtype)
                for name in aux_names}

        from ..initializer import InitDesc

        # Variable attrs make per-param init overrides visible to the
        # initializer (reference: initializer.py:85-107 InitDesc dispatch)
        attrs = self._symbol.attr_dict()

        def _impl(name, arr, cache):
            desc = InitDesc(name, attrs.get(name), initializer)
            if cache is not None:
                if name in cache:
                    cache_arr = cache[name]
                    if cache_arr is not arr:
                        if cache_arr.shape != arr.shape:
                            raise MXNetError(
                                "Parameter %s cannot be initialized from loading. "
                                "Shape mismatch: %s vs %s"
                                % (name, cache_arr.shape, arr.shape))
                        cache_arr.copyto(arr)
                else:
                    if not allow_missing:
                        raise RuntimeError("%s is not presented" % name)
                    if initializer is not None:
                        initializer(desc, arr)
            else:
                if initializer is not None:
                    initializer(desc, arr)

        for name, arr in sorted(self._arg_params.items()):
            _impl(name, arr, arg_params)
        for name, arr in sorted(self._aux_params.items()):
            _impl(name, arr, aux_params)

        self.params_initialized = True
        self._params_dirty = False
        self._exec_group.set_params(self._arg_params, self._aux_params)
        self._step_stale = self._fused_step is not None

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True):
        if not allow_missing:
            self.init_params(initializer=None, arg_params=arg_params,
                             aux_params=aux_params, allow_missing=allow_missing,
                             force_init=force_init)
            return
        if self.params_initialized and not force_init:
            return
        self._exec_group.set_params(arg_params, aux_params)
        self._params_dirty = True
        self._step_stale = self._fused_step is not None
        self.params_initialized = True

    # ------------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if force_rebind:
            self._reset_bind()
        if self.binded:
            self.logger.warning("Already binded, ignoring bind()")
            return

        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True

        if not for_training:
            assert not inputs_need_grad

        self._data_shapes = [s if hasattr(s, "name") else s for s in data_shapes]
        self._label_shapes = list(label_shapes) if label_shapes else None

        shared_group = None
        if shared_module is not None:
            assert shared_module.binded and shared_module.params_initialized
            shared_group = shared_module._exec_group

        self._exec_group = DataParallelExecutorGroup(
            self._symbol, self._context, self._work_load_list,
            self._data_shapes, self._label_shapes, self._param_names,
            for_training, inputs_need_grad, shared_group, logger=self.logger,
            fixed_param_names=self._fixed_param_names, grad_req=grad_req,
            state_names=self._state_names, mesh_config=self._mesh_config)
        self._total_exec_bytes = 0

        if shared_module is not None:
            self.params_initialized = True
            self._arg_params = shared_module._arg_params
            self._aux_params = shared_module._aux_params
        elif self.params_initialized:
            self._exec_group.set_params(self._arg_params, self._aux_params)

    def _reset_bind(self):
        # the fused step holds the live master weights; pull them back into
        # the host param dicts before the executor they came from is dropped
        if self._fused_step is not None and self.params_initialized:
            self._sync_params_from_devices()
        if self._fused_step is not None:
            self._fused_step.detach_metric()
        self._fused_step = None
        self._pending_metric = None
        self._fused_outputs = None
        self._fused_update_done = False
        self._step_stale = False
        self._exec_stale = False
        self.binded = False
        self._exec_group = None
        self._data_shapes = None
        self._label_shapes = None

    def reshape(self, data_shapes, label_shapes=None):
        assert self.binded
        self._data_shapes = list(data_shapes)
        self._label_shapes = list(label_shapes) if label_shapes else None
        self._exec_group.reshape(self._data_shapes, self._label_shapes)

    def reconfigure(self, contexts, mesh_config=None):
        """Re-form the module over a new device set mid-training — the
        elastic shrink/regrow step (mxnet_tpu.elastic).

        Rebinds at the SAME data/label shapes on the new contexts/mesh
        (the global batch is unchanged; each surviving device simply owns
        a larger slice of the 'data' axis) and re-initializes the
        optimizer so a fresh fused step compiles against the new executor
        group.  The caller then restores params/slots from the last fence
        checkpoint, re-sharded onto the new mesh — nothing may be in
        flight when this runs (the elastic controller drains first)."""
        assert self.binded and self.params_initialized \
            and self.optimizer_initialized
        if isinstance(contexts, ctx_mod.Context):
            contexts = [contexts]
        data_shapes, label_shapes = self._data_shapes, self._label_shapes
        optimizer = self._optimizer
        self._context = list(contexts)
        self._mesh_config = mesh_config
        self.bind(data_shapes=data_shapes, label_shapes=label_shapes,
                  for_training=True, force_rebind=True)
        # bind() pushed the host param dicts into the new group; the fused
        # step (fresh zero-moment slots) rebuilds here and the fence
        # restore that follows overwrites both
        self.init_optimizer(kvstore="local", optimizer=optimizer,
                            force_init=True)

    # ------------------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring...")
            return

        (kvstore, update_on_kvstore) = _create_kvstore(
            kvstore, len(self._context), self._arg_params)

        batch_size = self._exec_group.batch_size
        if kvstore and kvstore.type.startswith("dist") and "_sync" in kvstore.type:
            batch_size *= kvstore.num_workers
        rescale_grad = 1.0 / batch_size

        if isinstance(optimizer, str):
            idx2name = {i: n for i, n in enumerate(self._exec_group.param_names)}
            optimizer_params = dict(optimizer_params)
            if "rescale_grad" not in optimizer_params:
                optimizer_params["rescale_grad"] = rescale_grad
            optimizer = opt_mod.create(optimizer, sym=self.symbol,
                                       param_idx2name=idx2name, **optimizer_params)
        else:
            assert isinstance(optimizer, opt_mod.Optimizer)
            if optimizer.rescale_grad != rescale_grad:
                self.logger.warning(
                    "Optimizer created manually outside Module but rescale_grad "
                    "is not normalized to 1.0/batch_size/num_workers (%s vs. %s). "
                    "Is this intended?", optimizer.rescale_grad, rescale_grad)

        self._optimizer = optimizer
        self._kvstore = kvstore
        # when the fused step will own the update, the optimizer must NOT
        # also live in the kvstore — keep a local updater as the eager
        # fallback so state handoffs have somewhere to go
        if update_on_kvstore and self._fused_eligible(optimizer, kvstore):
            update_on_kvstore = False
        self._update_on_kvstore = update_on_kvstore
        self._updater = None

        if kvstore:
            # copy initialized params into the store
            for idx, name in enumerate(self._exec_group.param_names):
                kvstore.init(idx, self._arg_params[name])
            if update_on_kvstore:
                kvstore.set_optimizer(self._optimizer)
        if not update_on_kvstore:
            self._updater = opt_mod.get_updater(optimizer)

        self.optimizer_initialized = True
        self._maybe_build_fused_step()
        self._opt_owner = "fused" if self._fused_step is not None else "eager"

        if self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

    @property
    def _step_stale(self):
        if self._fused_step is not None:
            return self._fused_step.step_stale
        return self.__dict__.get("_step_stale_local", False)

    @_step_stale.setter
    def _step_stale(self, value):
        if getattr(self, "_fused_step", None) is not None:
            self._fused_step.step_stale = value
        self.__dict__["_step_stale_local"] = value

    @property
    def _exec_stale(self):
        if self._fused_step is not None:
            return self._fused_step.exec_stale
        return self.__dict__.get("_exec_stale_local", False)

    @_exec_stale.setter
    def _exec_stale(self, value):
        if getattr(self, "_fused_step", None) is not None:
            self._fused_step.exec_stale = value
        self.__dict__["_exec_stale_local"] = value

    @property
    def _opt_owner(self):
        # like the staleness flags, slot ownership belongs to the (possibly
        # shared) store: a fused->eager handoff by one bucket module must be
        # visible to every other module viewing the same master weights
        if self._fused_step is not None:
            return self._fused_step.opt_owner
        return self.__dict__.get("_opt_owner_local", "eager")

    @_opt_owner.setter
    def _opt_owner(self, value):
        if getattr(self, "_fused_step", None) is not None:
            self._fused_step.opt_owner = value
        self.__dict__["_opt_owner_local"] = value

    def _fused_eligible(self, optimizer, kvstore):
        """Whether the fused (donated, jitted) train step can own the
        update: single-process kvstore, no monitor taps, optimizer with a
        fused kernel, no data grads requested."""
        from .. import config as _config

        if not _config.get("MXNET_FUSED_TRAIN_STEP"):
            return False
        if _config.get("MXNET_ENGINE_TYPE") == "NaiveEngine":
            return False  # debugging mode: eager per-op execution
        if not self.for_training or self.inputs_need_grad:
            return False
        if self._monitor is not None:
            return False  # per-op taps need the eager executor path
        if kvstore is not None and kvstore.type.startswith("dist"):
            return False  # cross-process reduction rides the kvstore path
        if optimizer.fused_kernel() is None:
            self.logger.info(
                "optimizer %s has no fused kernel; using eager update path",
                type(optimizer).__name__)
            return False
        return True

    def _maybe_build_fused_step(self):
        """Compile forward+backward+optimizer into one donated XLA program
        when the configuration allows it."""
        self._flush_fused()  # re-init must not revert trained weights
        if self._fused_step is not None:
            self._fused_step.detach_metric()
        self._fused_step = None
        if not self._fused_eligible(self._optimizer, self._kvstore):
            return
        from ..train_step import CompiledTrainStep

        try:
            self._fused_step = CompiledTrainStep(
                self._exec_group, self._optimizer,
                compute_dtype=self._compute_dtype)
        except MXNetError as exc:
            self.logger.info("fused train step unavailable (%s); using "
                             "eager update path", exc)

    def borrow_optimizer(self, shared_module):
        """Share optimizer state with another module (bucketing).

        When the shared module owns a fused step, this module adopts the
        SAME master-weight store — its own executor graph gets a
        shape-specialized program inside that store on first run, so every
        bucket trains through the fused path against one set of weights.
        """
        assert shared_module.optimizer_initialized
        self._optimizer = shared_module._optimizer
        self._kvstore = shared_module._kvstore
        self._update_on_kvstore = shared_module._update_on_kvstore
        self._updater = shared_module._updater
        self._fused_step = shared_module._fused_step
        if self._fused_step is None:
            self._opt_owner = "eager"
        # (with a shared step, _opt_owner reads the store's flag directly)
        self.optimizer_initialized = True

    # ------------------------------------------------------------------
    def forward_backward(self, data_batch):
        """One training forward+backward.  With a fused step compiled, this
        runs the entire donated program (including the optimizer update —
        the following ``update()`` call is then a no-op)."""
        if self._fused_step is not None:
            self._run_fused(data_batch)
        else:
            self.forward(data_batch, is_train=True)
            self.backward()

    def _run_fused(self, data_batch):
        from .. import ndarray as _nd

        if self._pending_metric is not None:
            # arm device-side metric accumulation once; a metric the step
            # can't host stays on the classic update_metric path
            self._fused_step.attach_metric(self._pending_metric)
            self._pending_metric = None
        if self._step_stale:
            self._fused_step.load_from_executor()
            self._step_stale = False
        if self._opt_owner == "eager":
            # momentum/Adam moments accumulated on the eager path carry over
            if self._updater is not None and self._updater.states:
                self._fused_step.import_updater_states(
                    self._updater.states, self._exec_group.param_names)
            self._opt_owner = "fused"
        outs = self._fused_step.run(data_batch, group=self._exec_group)
        ctx = self._context[0]
        self._fused_outputs = [_nd.NDArray(o, ctx) for o in outs]
        self._fused_update_done = True
        self._exec_stale = True
        self._params_dirty = True

    def _flush_fused(self):
        """Bring the executor's NDArray buffers up to date with the fused
        step's master state (eval / checkpoint / classic-path boundary)."""
        if self._fused_step is not None and self._exec_stale:
            self._fused_step.flush_to_executor()
            self._exec_stale = False

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        self._flush_fused()
        self._fused_outputs = None
        self._exec_group.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec_group.backward(out_grads=out_grads)

    def update(self):
        """Optimizer step (reference: module.py:553).  No-op when the
        preceding forward_backward already ran the fused program."""
        assert self.binded and self.params_initialized and self.optimizer_initialized
        if self._fused_update_done:
            self._fused_update_done = False
            return
        self._params_dirty = True
        if self._fused_step is not None:
            self._handoff_fused_to_eager()
            self._step_stale = True
        group = self._exec_group
        if self._update_on_kvstore:
            for idx, (name, w, g) in enumerate(zip(group.param_names,
                                                   group.param_arrays,
                                                   group.grad_arrays)):
                if g is None:
                    continue
                self._kvstore.push(idx, g)
                self._kvstore.pull(idx, out=w)
        else:
            if self._kvstore:
                for idx, (w, g) in enumerate(zip(group.param_arrays,
                                                 group.grad_arrays)):
                    if g is None:
                        continue
                    self._kvstore.push(idx, g)
                    self._kvstore.pull(idx, out=g)
            # one fused whole-model update call (TPU: dispatch latency would
            # dominate a per-parameter loop)
            idxs, ws, gs = [], [], []
            for idx, (w, g) in enumerate(zip(group.param_arrays, group.grad_arrays)):
                if g is None:
                    continue
                idxs.append(idx)
                ws.append(w)
                gs.append(g)
            self._updater.update_multi(idxs, gs, ws)

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        if self._fused_outputs is not None:
            return list(self._fused_outputs)
        return self._exec_group.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized and self.inputs_need_grad
        return self._exec_group.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        if self._fused_outputs is not None:
            step = self._fused_step
            acc = step._metric_acc if step is not None else None
            if acc is not None and acc.metric is eval_metric:
                # already accumulated INSIDE the step program — no host
                # read; the accumulator applies the periodic-drain policy
                acc.maybe_drain(step.num_steps)
                return
            from .. import metric as metric_mod

            eval_metric.update(labels, metric_mod.select_outputs(
                eval_metric, self._fused_outputs))
        else:
            self._exec_group.update_metric(eval_metric, labels)

    def _bind_metric(self, eval_metric):
        from .. import config as _config

        self._pending_metric = None
        if self._fused_step is None:
            return
        if not _config.get("MXNET_DEVICE_METRICS"):
            # knob turned off between fits: a previously armed accumulator
            # must actually come off the program, not linger
            self._fused_step.detach_metric()
            return
        acc = self._fused_step._metric_acc
        if acc is not None and acc.metric is not eval_metric:
            # don't keep accumulating into the previous fit's metric
            self._fused_step.detach_metric()
        self._pending_metric = eval_metric

    def _bind_eval_metric(self, eval_metric):
        """Arm device-side metric accumulation for score(): the eval pass
        runs one jitted forward+accumulate program per batch and never
        materializes outputs on the host (ROADMAP PR-3 open item)."""
        from .. import config as _config

        if not _config.get("MXNET_DEVICE_METRICS"):
            return None
        if _config.get("MXNET_ENGINE_TYPE") == "NaiveEngine":
            return None
        if self._monitor is not None:
            return None  # per-op taps need the eager executor path
        if not self.binded or not self.params_initialized:
            return None
        from ..metric import DeviceMetricAccumulator

        if not DeviceMetricAccumulator.supported(eval_metric):
            return None
        # fit() defaults validation_metric to the TRAIN metric instance,
        # whose drain/reset hooks the fused step's accumulator owns;
        # installing eval hooks over them (and uninstalling at pass end)
        # would orphan the train-side device sums — such shared metrics
        # score through the host path, as before
        if any(getattr(m, "_device_sync", None) is not None
               for m in DeviceMetricAccumulator._flatten(eval_metric)):
            return None
        # the program reads the executor's parameter buffers — bring them
        # up to date with the fused step's master state first (forward()
        # would have done the same)
        self._flush_fused()
        # one compiled eval step per (executor, metric) pair: repeated
        # score() calls — fit's per-epoch validation — reuse it
        cached = getattr(self, "_eval_step_cache", None)
        if cached is not None and cached[0] is self._exec_group.exec_ \
                and cached[1] is eval_metric:
            return cached[2].rearm()
        from ..train_step import CompiledEvalStep

        try:
            step = CompiledEvalStep(self._exec_group, eval_metric)
        except MXNetError as exc:
            self.logger.info("device-side eval metrics unavailable (%s); "
                             "using the host path", exc)
            return None
        self._eval_step_cache = (self._exec_group.exec_, eval_metric, step)
        return step

    def program_artifacts(self):
        """The module's compiled programs as analysis artifacts.

        Returns ``{name: ProgramArtifact}`` for every program this module
        currently holds compiled: the fused train step (after its first
        run) and the cached compiled eval step (after a device-metric
        ``score``).  The uniform probe surface ``tools/mxlint.py`` and
        custom audits consume — see docs/static_analysis.md.
        """
        arts = {}
        if self._fused_step is not None:
            art = self._fused_step.artifact(group=self._exec_group)
            if art is not None:
                arts[art.name] = art
        cached = getattr(self, "_eval_step_cache", None)
        if cached is not None:
            art = cached[2].artifact()
            if art is not None:
                arts[art.name] = art
        return arts

    def _wrap_train_data(self, train_data):
        from .. import config as _config
        from ..io import DevicePrefetchIter

        if self._fused_step is None \
                or not _config.get("MXNET_DEVICE_PREFETCH") \
                or isinstance(train_data, DevicePrefetchIter):
            return train_data
        return DevicePrefetchIter(train_data, module=self)

    def _dispatch_fence(self):
        if self._fused_outputs is None or not self._fused_outputs:
            return None
        return self._fused_outputs[0].data

    def _sync_params_from_devices(self):
        self._flush_fused()
        self._exec_group.get_params(self._arg_params, self._aux_params)
        self._params_dirty = False

    def _handoff_fused_to_eager(self):
        """Move live state (params + optimizer slots) from the fused step to
        the eager path so momentum/moments survive the switch."""
        if self._fused_step is None or self._opt_owner != "fused":
            return
        self._flush_fused()
        self._fused_step.detach_metric()  # drains pending device sums
        self._pending_metric = None
        if self._updater is not None:
            self._fused_step.export_updater_states(
                self._updater, self._exec_group.param_names,
                self._context[0])
        self._opt_owner = "eager"

    def save_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._fused_step is not None and self._opt_owner == "fused":
            with open(fname, "wb") as fout:
                fout.write(self._fused_step.get_states())
        elif self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname)
        else:
            with open(fname, "wb") as fout:
                fout.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._fused_step is not None:
            with open(fname, "rb") as fin:
                self._fused_step.set_states(fin.read())
            self._opt_owner = "fused"
        elif self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
        else:
            with open(fname, "rb") as fin:
                self._updater.set_states(fin.read())

    def install_monitor(self, mon):
        """Per-op output taps require the interpreted executor path, so a
        monitored module drops back to eager forward/backward/update."""
        assert self.binded
        self._monitor = mon
        if self._fused_step is not None:
            self._handoff_fused_to_eager()
            self._fused_step = None
        self._exec_group.install_monitor(mon)
