"""BaseModule — the high-level train/eval/predict interface.

API parity with the reference's ``python/mxnet/module/base_module.py``
(fit/score/predict/forward_backward and the abstract surface below), with
the training loop rebuilt around this framework's compiled-step execution
model: ``fit`` is a thin driver over ``_fit_epoch``, and evaluation /
prediction share one padded-batch iterator helper instead of three copies
of the reset/limit/pad logic.
"""
from __future__ import annotations

import logging
import time
from collections import deque, namedtuple

from .. import metric as metric_mod
from .. import ndarray as nd

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def _callbacks(cb):
    """Normalize a callback argument to an iterable."""
    if cb is None:
        return ()
    return cb if isinstance(cb, (list, tuple)) else (cb,)


def _fire(cbs, *args):
    for cb in _callbacks(cbs):
        cb(*args)


def _block_on(fence):
    """Block until a dispatched step's result is materialized on device."""
    import jax

    jax.block_until_ready(fence)


class BaseModule:
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.inputs_need_grad = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None
        self._total_exec_bytes = 0
        # fault-tolerance sidecar (mxnet_tpu.elastic.ElasticController),
        # armed by fit() for the duration of a training run
        self._elastic = None

    # ------------------------------------------------------------------
    # High-level interface
    # ------------------------------------------------------------------
    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def _eval_batches(self, eval_data, num_batch, reset):
        """Yield (nbatch, batch) honoring the batch limit; resets first."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch >= num_batch:
                return
            yield nbatch, batch

    @staticmethod
    def _unpadded(batch, outputs):
        """Strip the iterator's tail padding from a batch's outputs.

        Each output is sliced by its own leading dim, so a scalar/aggregated
        loss output alongside per-sample outputs is not mis-sliced.
        """
        return [out[:out.shape[0] - batch.pad] if out.ndim > 0 else out
                for out in outputs]

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0):
        """Run an evaluation pass, returning the metric's name/value list.

        Drivers with a compiled forward may arm device-side metric
        accumulation (``_bind_eval_metric``): the whole pass then performs
        no per-batch device→host transfer — the classic path materializes
        label + pred on the host for every batch.  A metric/graph pair the
        device path rejects falls back to the host path mid-loop with
        everything already accumulated preserved.
        """
        eval_metric = metric_mod.create(eval_metric)
        eval_metric.reset()
        eval_step = self._bind_eval_metric(eval_metric)
        nbatch = -1
        try:
            for nbatch, batch in self._eval_batches(eval_data, num_batch,
                                                    reset):
                if eval_step is not None:
                    try:
                        eval_step.run(batch)
                    except Exception as exc:
                        # demote to the host path; device sums drain into
                        # the metric so nothing accumulated is lost
                        self.logger.info(
                            "device-side eval metrics unavailable (%s); "
                            "using the host path", exc)
                        eval_step.finish()
                        eval_step = None
                if eval_step is None:
                    self.forward(batch, is_train=False)
                    self.update_metric(eval_metric, batch.label)
                _fire(batch_end_callback,
                      BatchEndParam(epoch, nbatch, eval_metric, locals()))
        finally:
            if eval_step is not None:
                eval_step.finish()
        _fire(score_end_callback,
              BatchEndParam(epoch, nbatch + 1, eval_metric, locals()))
        return eval_metric.get_name_value()

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        """Generator over (outputs, nbatch, batch) with padding stripped."""
        for nbatch, batch in self._eval_batches(eval_data, num_batch, reset):
            self.forward(batch, is_train=False)
            yield (self._unpadded(batch, self.get_outputs()), nbatch, batch)

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False):
        """Collect forward outputs over a dataset.  With ``merge_batches``
        the per-batch output lists are concatenated along axis 0."""
        collected = [list(outs) for outs, _, _
                     in self.iter_predict(eval_data, num_batch, reset)]
        if not collected or not merge_batches:
            return collected
        widths = {len(outs) for outs in collected}
        if len(widths) != 1:
            raise ValueError("Cannot merge batches: mismatched number of outputs")
        merged = [nd.concatenate([outs[i] for outs in collected])
                  for i in range(widths.pop())]
        if len(merged) == 1 and not always_output_list:
            return merged[0]
        return merged

    # ------------------------------------------------------------------
    # Training
    # ------------------------------------------------------------------
    def prepare_fit(self, train_data, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False, force_rebind=False,
                    force_init=False, kvstore="local", optimizer="sgd",
                    optimizer_params=(("learning_rate", 0.01),), monitor=None):
        """Bind + init params + init optimizer for training on
        ``train_data``'s shapes.  Split out of fit() so custom loops can
        reuse the setup."""
        from ..initializer import Uniform

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer or Uniform(0.01),
                         arg_params=arg_params, aux_params=aux_params,
                         allow_missing=allow_missing, force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)

    # ------------------------------------------------------------------
    # async-loop hooks (overridden by drivers with compiled steps)
    # ------------------------------------------------------------------
    def _bind_metric(self, eval_metric):
        """Give the driver a chance to fold ``eval_metric``'s accumulation
        into its compiled step (device-side metrics).  Default: host path."""

    def _bind_eval_metric(self, eval_metric):
        """Return a ``CompiledEvalStep``-like object (``run(batch)`` /
        ``finish()``) accumulating ``eval_metric`` on device during
        ``score``, or None for the classic host path.  Default: host."""
        return None

    def _wrap_train_data(self, train_data):
        """Optionally wrap the training iterator (device prefetch).  The
        wrapper must preserve reset(); fit() closes it when it adds one."""
        return train_data

    def _dispatch_fence(self):
        """A device array that completes when the most recently dispatched
        training step has finished, or None when the driver executes
        synchronously.  fit() bounds the number of outstanding steps by
        blocking on the step-K-behind fence."""
        return None

    def _fit_epoch(self, epoch, train_data, eval_metric, batch_end_callback,
                   monitor):
        """One pass over train_data; returns the wall-clock cost.

        The loop rides JAX's async dispatch: with a compiled step and
        device-side metric accumulation the body performs no host sync, so
        up to ``MXNET_MAX_STEPS_IN_FLIGHT`` steps stay outstanding and the
        host prepares batch n+K while the device runs step n.  Device
        memory is bounded by blocking on the step-K-behind fence rather
        than the current result (the dependency-engine analog: the host
        throttles on an OLD variable's WaitToRead, never the newest).
        Input-pipeline stalls and host waits are recorded in
        ``profiler.step_stats`` for the bench contract.
        """
        from contextlib import ExitStack

        from .. import config as _config
        from .. import profiler as _prof

        start = time.time()
        eval_metric.reset()
        limit = max(1, int(_config.get("MXNET_MAX_STEPS_IN_FLIGHT")))
        fences = deque()
        nbatch = 0
        if self._elastic is not None:
            # resuming into this epoch: metric sums back to the fence
            # values, iterator fast-forwarded past the already-done batches
            nbatch = self._elastic.on_epoch_start(self, epoch, train_data,
                                                  eval_metric)
        it = iter(train_data)
        # MXNET_TRANSFER_GUARD arms jax's device->host transfer guard for
        # the whole epoch body: with device-side metrics + prefetch + the
        # fence deque, the hot loop performs no d2h at all, and 'disallow'
        # turns that invariant into a runtime error on the TPU rig (the
        # analysis host-sync pass is the static half).  Thread-local, so
        # the prefetch worker's h2d device_puts are unaffected.
        guard = str(_config.get("MXNET_TRANSFER_GUARD") or "off").lower()
        stack = ExitStack()
        if guard not in ("", "off"):
            import jax

            stack.enter_context(jax.transfer_guard_device_to_host(guard))
        # one timeline span per epoch (always-on, bounded ring): the
        # host_wait/input_wait/ckpt_* loop spans nest under it
        from .. import obs as _obs

        stack.enter_context(_obs.span("fit_epoch", cat="loop",
                                      args={"epoch": int(epoch)}))
        with stack:
            while True:
                t0 = time.perf_counter()
                try:
                    batch = next(it)
                except StopIteration:
                    break
                _prof.record_input_wait(time.perf_counter() - t0)
                if monitor is not None:
                    monitor.tic()
                self.forward_backward(batch)
                self.update()
                self.update_metric(eval_metric, batch.label)
                fence = self._dispatch_fence()
                if fence is not None:
                    fences.append(fence)
                    # at most `limit` dispatched-but-unfinished steps: with
                    # limit=1 this waits on the step just issued
                    # (synchronous)
                    if len(fences) >= limit:
                        t0 = time.perf_counter()
                        _block_on(fences.popleft())
                        _prof.record_host_wait(time.perf_counter() - t0)
                if monitor is not None:
                    monitor.toc_print()
                _prof.record_step()
                _fire(batch_end_callback,
                      BatchEndParam(epoch, nbatch, eval_metric, locals()))
                if self._elastic is not None:
                    # fault injection, the periodic fence checkpoint, and
                    # the liveness poll (which drains `fences` and raises
                    # ReconfigureSignal when the mesh must re-form).  After
                    # the callback, so user callbacks observe every
                    # completed batch exactly once even across a resume.
                    self._elastic.on_step(self, epoch, nbatch, fences)
                nbatch += 1
        if fences:
            # steps chain through donated params, so the newest fence
            # transitively covers every outstanding step
            t0 = time.perf_counter()
            _block_on(fences[-1])
            _prof.record_host_wait(time.perf_counter() - t0)
            fences.clear()
        return time.time() - start

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, elastic=None):
        """Train for ``num_epoch`` epochs: compiled train steps per batch,
        optional validation pass and checkpoints per epoch.

        ``elastic`` is an optional
        :class:`~mxnet_tpu.elastic.ElasticController` (auto-created from
        ``MXNET_CKPT_DIR``/``MXNET_CKPT_PERIOD`` when unset): async fenced
        checkpoints at step boundaries, auto-resume from the last
        committed fence, and — with a failure monitor — mid-fit mesh
        shrink/regrow on heartbeat transitions (docs/elasticity.md).
        """
        from .. import elastic as elastic_mod

        assert num_epoch is not None, "please specify number of epochs"
        self.prepare_fit(train_data, initializer=initializer,
                         arg_params=arg_params, aux_params=aux_params,
                         allow_missing=allow_missing,
                         force_rebind=force_rebind, force_init=force_init,
                         kvstore=kvstore, optimizer=optimizer,
                         optimizer_params=optimizer_params, monitor=monitor)
        eval_metric = metric_mod.create(eval_metric)
        validation_metric = validation_metric or eval_metric
        # async loop setup: device-side metric accumulation in the compiled
        # step, and device prefetch of upcoming batches (both no-ops for
        # drivers/configs without a fused step)
        self._bind_metric(eval_metric)
        fit_data = self._wrap_train_data(train_data)
        if elastic is None:
            elastic = elastic_mod.from_env()
            if elastic is not None and \
                    getattr(self, "_exec_group", None) is None:
                # env-armed checkpointing on a driver without executor-
                # group state to fence (Bucketing/Sequential/Python
                # modules): train WITHOUT checkpoints rather than abort —
                # the env knobs are ambient, not a per-call opt-in.  An
                # explicitly passed controller still fails loudly.
                self.logger.warning(
                    "MXNET_CKPT_DIR is set but %s has no executor-group "
                    "state to fence; training without elastic "
                    "checkpoints", type(self).__name__)
                elastic = None
        self._elastic = elastic
        if elastic is not None:
            # auto-resume: a committed fence in the checkpoint directory
            # restores params/slots/RNG and advances the starting epoch
            begin_epoch = elastic.attach(self, eval_metric, begin_epoch)

        try:
            epoch = begin_epoch
            first_epoch = True
            while epoch < num_epoch:
                if not first_epoch:
                    # reset at epoch START: after the last epoch there is
                    # no reset, so a prefetching wrapper's worker is not
                    # restarted just to have its read-ahead thrown away
                    fit_data.reset()
                first_epoch = False
                try:
                    cost = self._fit_epoch(epoch, fit_data, eval_metric,
                                           batch_end_callback, monitor)
                except elastic_mod.ReconfigureSignal as sig:
                    # a heartbeat transition: in-flight steps are already
                    # drained; re-form the mesh on the survivors, restore
                    # the last fence, and continue from its epoch
                    epoch = elastic.handle_reconfigure(self, sig,
                                                       eval_metric)
                    continue
                # reading the metric drains any pending device accumulation
                for name, val in eval_metric.get_name_value():
                    self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
                self.logger.info("Epoch[%d] Time cost=%.3f", epoch, cost)

                # materialize params host-side once per epoch: checkpoints
                # and user callbacks observe a consistent snapshot
                arg_snap, aux_snap = self.get_params()
                self.set_params(arg_snap, aux_snap)
                _fire(epoch_end_callback, epoch, self.symbol, arg_snap,
                      aux_snap)

                if eval_data:
                    for name, val in self.score(
                            eval_data, validation_metric,
                            score_end_callback=eval_end_callback,
                            batch_end_callback=eval_batch_end_callback,
                            epoch=epoch):
                        self.logger.info("Epoch[%d] Validation-%s=%f",
                                         epoch, name, val)
                epoch += 1
        finally:
            if elastic is not None:
                elastic.finish()
            self._elastic = None
            if fit_data is not train_data and hasattr(fit_data, "close"):
                fit_data.close()
            # fit() leaves the caller's iterator fresh (the pre-async loop
            # reset after every epoch; a second fit() must not silently
            # iterate zero batches)
            train_data.reset()

    # ------------------------------------------------------------------
    # Parameter persistence
    # ------------------------------------------------------------------
    def save_params(self, fname):
        arg_params, aux_params = self.get_params()
        blob = {"arg:%s" % k: v for k, v in arg_params.items()}
        blob.update({"aux:%s" % k: v for k, v in aux_params.items()})
        nd.save(fname, blob)

    def load_params(self, fname):
        arg_params, aux_params = {}, {}
        for key, value in nd.load(fname).items():
            kind, _, name = key.partition(":")
            if kind == "arg":
                arg_params[name] = value
            elif kind == "aux":
                aux_params[name] = value
            else:
                raise ValueError("Invalid param file " + fname)
        self.set_params(arg_params, aux_params)

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)

    # ------------------------------------------------------------------
    # Abstract surface (implemented by Module / BucketingModule / ...)
    # ------------------------------------------------------------------
    @property
    def symbol(self):
        return self._symbol

    @property
    def data_names(self):
        raise NotImplementedError()

    @property
    def output_names(self):
        raise NotImplementedError()

    @property
    def data_shapes(self):
        raise NotImplementedError()

    @property
    def label_shapes(self):
        raise NotImplementedError()

    @property
    def output_shapes(self):
        raise NotImplementedError()

    def get_params(self):
        raise NotImplementedError()

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False):
        raise NotImplementedError()

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError()

    def backward(self, out_grads=None):
        raise NotImplementedError()

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError()

    def update(self):
        raise NotImplementedError()

    def get_input_grads(self, merge_multi_context=True):
        raise NotImplementedError()

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError()

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        raise NotImplementedError()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        raise NotImplementedError()

    def install_monitor(self, mon):
        raise NotImplementedError()
