"""PythonModule — modules whose computation is user-defined Python.

Capability parity with the reference's ``module/python_module.py``: a
BaseModule subclass for computation expressed directly in numpy/jax
(no Symbol graph), typically parameter-free glue in a SequentialModule
chain — e.g. a custom loss attached after a feature extractor.

Design here: where the reference hand-wires numpy forward/backward pairs,
``PythonLossModule`` also accepts a jax-traceable ``loss_function`` and
derives the gradient automatically (``jax.grad``), so custom losses get
correct backward for free; an explicit ``grad_func`` still overrides.
"""
from __future__ import annotations

import logging

import numpy as np

from ..base import MXNetError
from .. import ndarray as nd
from ..io import DataDesc
from .base_module import BaseModule

__all__ = ["PythonModule", "PythonLossModule"]


class PythonModule(BaseModule):
    """Base for python-computation modules (reference: python_module.py:11).

    Subclasses implement ``forward`` / ``backward`` / ``get_outputs`` /
    ``get_input_grads``; parameters are assumed empty (the common case —
    python modules act as glue/loss heads)."""

    def __init__(self, data_names, label_names, output_names,
                 logger=logging):
        super().__init__(logger=logger)
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._output_names = list(output_names)
        self._data_shapes = None
        self._label_shapes = None
        self._output_shapes = None

    # -- properties ---------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._output_shapes

    # -- parameters: none ---------------------------------------------
    def get_params(self):
        return {}, {}

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False):
        self.params_initialized = True

    def update(self):
        pass

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        self.optimizer_initialized = True

    def update_metric(self, eval_metric, labels):
        if not self._label_names:
            return
        outs = self.get_outputs()
        if outs and labels and tuple(outs[0].shape[:1]) != \
                tuple(labels[0].shape[:1]):
            # scalar-loss heads have no per-sample predictions to score
            return
        eval_metric.update(labels, outs)

    # -- binding -------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already binded, ignoring bind()")
            return
        assert grad_req == "write", \
            "PythonModule only supports grad_req='write'"
        self._data_shapes = [d if isinstance(d, DataDesc)
                             else DataDesc(d[0], d[1]) for d in data_shapes]
        self._label_shapes = [d if isinstance(d, DataDesc)
                              else DataDesc(d[0], d[1])
                              for d in (label_shapes or [])]
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True
        self._output_shapes = self._compute_output_shapes()

    def _compute_output_shapes(self):
        raise NotImplementedError()


class PythonLossModule(PythonModule):
    """A loss head in Python (reference: python_module.py:219).

    ``loss_function(pred, label) -> scalar`` (jax-traceable) gives both the
    forward loss value and, via ``jax.grad``, the input gradient; or pass
    ``grad_func(pred, label) -> d loss/d pred`` explicitly (the reference's
    style).  Default (neither given): identity forward whose backward is
    the incoming head gradient — a passthrough tap.
    """

    def __init__(self, name="pyloss", data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 grad_func=None, loss_function=None):
        super().__init__(data_names, label_names,
                         [name + "_output"], logger=logger)
        self._name = name
        self._grad_func = grad_func
        self._loss_function = loss_function
        self._pred = None
        self._label = None
        self._pred_grad = None
        self._value_and_grad = None   # jitted, built on first use
        self._cached_pair = None      # (loss, grad) for the current batch

    def _compute_output_shapes(self):
        if self._loss_function is not None:
            return [DataDesc(self._output_names[0], (1,))]
        return [DataDesc(self._output_names[0],
                         tuple(self._data_shapes[0].shape))]

    def forward(self, data_batch, is_train=None):
        assert self.binded
        self._pred = data_batch.data[0]
        self._label = data_batch.label[0] if data_batch.label else None
        self._pred_grad = None
        self._cached_pair = None

    def _loss_and_grad(self):
        """(loss, d loss/d pred) for the current batch — ONE jitted
        value_and_grad call, compiled once and cached per batch (forward
        value and gradient share the trace)."""
        if self._cached_pair is None:
            import jax

            if self._value_and_grad is None:
                self._value_and_grad = jax.jit(
                    jax.value_and_grad(self._loss_function))
            self._cached_pair = self._value_and_grad(
                self._pred.data,
                self._label.data if self._label is not None else None)
        return self._cached_pair

    def get_outputs(self, merge_multi_context=True):
        if self._loss_function is not None:
            import jax.numpy as jnp

            val, _ = self._loss_and_grad()
            return [nd.NDArray(jnp.reshape(val, (1,)), self._pred.context)]
        return [self._pred]

    def backward(self, out_grads=None):
        assert self.binded and self.for_training
        if self._grad_func is not None:
            g = self._grad_func(self._pred, self._label)
            self._pred_grad = g if isinstance(g, nd.NDArray) \
                else nd.array(np.asarray(g), ctx=self._pred.context)
        elif self._loss_function is not None:
            _, g = self._loss_and_grad()
            self._pred_grad = nd.NDArray(g, self._pred.context)
        else:
            if out_grads is None:
                raise MXNetError(
                    "PythonLossModule passthrough needs out_grads (no "
                    "loss_function/grad_func given)")
            self._pred_grad = out_grads[0] if isinstance(out_grads, list) \
                else out_grads

    def get_input_grads(self, merge_multi_context=True):
        assert self._pred_grad is not None, "call backward() first"
        return [self._pred_grad]

    def install_monitor(self, mon):
        pass
