"""Base utilities: errors, name management, attribute scopes.

TPU-native re-design of the reference's base layer
(`/root/reference/python/mxnet/base.py`, `python/mxnet/name.py`,
`python/mxnet/attribute.py`).  There is no ctypes FFI here: the "C ABI" of
the reference collapses into direct Python dispatch onto JAX; a real C ABI
for non-Python frontends lives in src/ (native runtime).
"""
from __future__ import annotations

import threading

__all__ = ["MXNetError", "NameManager", "AttrScope", "string_types", "numeric_types"]

string_types = (str,)
numeric_types = (float, int)


class MXNetError(Exception):
    """Error raised by the framework (reference: python/mxnet/base.py:38)."""


class _ScopeStack(threading.local):
    def __init__(self):
        self.stack = []


class NameManager:
    """Automatic symbol naming (reference: python/mxnet/name.py:6-60).

    Assigns ``{op}{count}`` names to anonymous symbols, e.g. ``convolution0``.
    """

    _state = _ScopeStack()

    def __init__(self):
        self._counter = {}

    def get(self, name, hint):
        if name is not None:
            return name
        if hint not in self._counter:
            self._counter[hint] = 0
        name = "%s%d" % (hint, self._counter[hint])
        self._counter[hint] += 1
        return name

    def __enter__(self):
        NameManager._state.stack.append(self)
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        NameManager._state.stack.pop()

    @classmethod
    def current(cls):
        if not cls._state.stack:
            cls._state.stack.append(NameManager())
        return cls._state.stack[-1]


class Prefix(NameManager):
    """Prefixing name manager (reference: python/mxnet/name.py:63-79)."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        name = super().get(name, hint)
        return self._prefix + name


class AttrScope:
    """Attribute scoping for symbols (reference: python/mxnet/attribute.py).

    ``with mx.AttrScope(ctx_group='dev1'):`` attaches attributes to every
    symbol created inside the scope — this is how model parallelism
    (`group2ctx`) is expressed.
    """

    _state = _ScopeStack()

    def __init__(self, **kwargs):
        for value in kwargs.values():
            if not isinstance(value, string_types):
                raise ValueError("Attributes need to be strings")
        self._attr = kwargs

    def get(self, attr):
        """Merge user-supplied attrs with scope attrs (user wins)."""
        if self._attr:
            ret = self._attr.copy()
            if attr:
                ret.update(attr)
            return ret
        return attr if attr else {}

    def __enter__(self):
        # inherit outer scope attributes
        if AttrScope._state.stack:
            merged = AttrScope._state.stack[-1]._attr.copy()
            merged.update(self._attr)
            self._attr = merged
        AttrScope._state.stack.append(self)
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        AttrScope._state.stack.pop()

    @classmethod
    def current(cls):
        if not cls._state.stack:
            cls._state.stack.append(AttrScope())
        return cls._state.stack[-1]
