"""Runtime device-kernel registration — the reference's RTC analog.

The reference lets users hand the framework raw device-kernel source at
runtime (``python/mxnet/rtc.py``: CUDA strings compiled via ``MXRtc*``,
src/c_api/c_api.cc) and call it on NDArrays.  The TPU-native equivalent of
"user-authored device kernel, compiled at runtime" is a **Pallas kernel**:
the user writes the kernel in Python against ``jax.experimental.pallas``,
and Mosaic compiles it for the TPU at first trace — same runtime-compile
contract, memory-safe, and differentiable when the user supplies a
backward.

``register_pallas_op`` wires such a kernel into the op registry, so it is
callable as ``mx.nd.<name>`` / ``mx.sym.<name>`` and composes with jit,
vjp, Module training, and the rest of the framework exactly like built-in
ops — the extension-point story the Custom op (host Python) cannot cover
because its callbacks never run on the device.

Worked example (see tests/test_rtc.py for the full differentiable one)::

    import jax, jax.numpy as jnp
    from jax.experimental import pallas as pl

    def scale_add_kernel(x_ref, y_ref, o_ref, *, alpha):
        o_ref[...] = x_ref[...] * alpha + y_ref[...]

    def forward(x, y, alpha=2.0):
        return pl.pallas_call(
            functools.partial(scale_add_kernel, alpha=alpha),
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype))(x, y)

    def backward(inputs, outputs, cotangents, alpha=2.0):
        (g,) = cotangents
        return [g * alpha, g]

    mx.rtc.register_pallas_op("scale_add", forward, backward=backward,
                              num_inputs=2,
                              attr_params={"alpha": 2.0})
    out = mx.nd.scale_add(a, b, alpha=3.0)
"""
from __future__ import annotations

import numpy as np

from .attrs import Param, ParamSchema
from .base import MXNetError
from .registry import OpDef, register_op

__all__ = ["register_pallas_op"]


def register_pallas_op(name, forward, backward=None, num_inputs=1,
                       num_outputs=1, infer_shape=None, attr_params=None,
                       doc=""):
    """Register a user device kernel as a first-class operator.

    Args:
      name: op name (becomes ``mx.nd.<name>`` / ``mx.sym.<name>``).
      forward: ``forward(*inputs, **attrs) -> output(s)`` — jnp arrays in,
        array or list out; typically wraps ``pl.pallas_call``.  Traced
        under jit: Mosaic compiles the kernel at first use (the RTC
        "compile at runtime" contract).
      backward: optional ``backward(inputs, outputs, cotangents, **attrs)
        -> [input cotangents]``.  When given, the op is differentiable
        (wrapped in ``jax.custom_vjp``); without it, differentiating the
        op raises at trace time (the reference's Rtc kernels are likewise
        forward-only).
      num_inputs / num_outputs: arity (ints).
      infer_shape: optional ``(attrs, in_shapes, aux_shapes) ->
        (in, out, aux)`` hook; defaults to abstract evaluation of
        ``forward`` (fine for most kernels).
      attr_params: {name: default} scalar attributes forwarded to both
        ``forward`` and ``backward`` as keyword arguments.
      doc: docstring for the generated wrappers.

    The op name must not collide with an existing operator.
    """
    from .registry import get_op
    from . import ndarray as nd_mod
    from . import symbol as sym_mod

    try:
        get_op(name)
    except (KeyError, MXNetError):
        pass
    else:
        raise MXNetError("op %r already registered" % name)
    # the wrappers install into mx.nd / mx.sym: refuse to shadow ANY
    # existing attribute there (e.g. nd.array, sym.Variable)
    if hasattr(nd_mod, name) or hasattr(sym_mod, name):
        raise MXNetError(
            "name %r would shadow an existing mx.nd/mx.sym attribute" % name)

    attr_params = dict(attr_params or {})
    schema = ParamSchema(*[Param(k, type(v), default=v)
                           for k, v in attr_params.items()])

    def _attrs(attrs):
        return {k: attrs.get(k, d) for k, d in attr_params.items()}

    def _as_list(v):
        return list(v) if isinstance(v, (list, tuple)) else [v]

    def fcompute(attrs, inputs, aux, octx):
        import jax

        kw = _attrs(attrs)
        if backward is None:
            return _as_list(forward(*inputs, **kw)), []

        @jax.custom_vjp
        def run(*ins):
            return tuple(_as_list(forward(*ins, **kw)))

        def run_fwd(*ins):
            outs = tuple(_as_list(forward(*ins, **kw)))
            return outs, (ins, outs)

        def run_bwd(res, cts):
            ins, outs = res
            grads = backward(list(ins), list(outs), list(cts), **kw)
            if len(grads) != len(ins):
                raise MXNetError(
                    "%s.backward returned %d cotangents for %d inputs"
                    % (name, len(grads), len(ins)))
            return tuple(grads)

        run.defvjp(run_fwd, run_bwd)
        return list(run(*inputs)), []

    register_op(OpDef(
        name, fcompute, schema=schema,
        num_inputs=num_inputs, num_outputs=num_outputs,
        infer_shape=infer_shape, needs_train=False,
        hint=name.lower(), user_defined=True,
        doc=doc or ("User-registered Pallas kernel op (rtc analog; "
                    "reference python/mxnet/rtc.py).")))
    # expose wrappers on the generated namespaces (ops registered after
    # import must install their functions explicitly)
    setattr(nd_mod, name, nd_mod._make_op_func(get_op(name)))
    setattr(sym_mod, name, sym_mod._make_sym_func(name))
    return get_op(name)
