"""KV-cached autoregressive decoding — prefill/decode split + batched serving.

The ``Predictor`` runs a whole forward per call, so generating token T
re-executes the full prefix: O(T^2) work per sequence.  This module is the
TPU-era serving path (Pope et al., "Efficiently Scaling Transformer
Inference"): :class:`DecodePredictor` splits an ``attention_lm``-style
symbol into TWO jitted programs —

* **prefill** — one full causal forward over the prompt that additionally
  captures every ``dot_product_attention`` node's K/V into a preallocated
  ring-buffer cache (``ops.attention.cache_append`` layout), and samples
  the first output token;
* **decode step** — one token per call: embed the last sampled token,
  append its K/V at the next ring slot (``jax.lax.dynamic_update_slice``),
  attend the single query position against the cache with a length-masked
  softmax (``ops.attention.sdpa_decode``), sample the next token
  (``ops.sample.sample_tokens``).  The program carries ``(params, state,
  rng)`` with the state (caches + per-sequence lengths + last token)
  DONATED (``MXNET_DECODE_DONATE``), so the token loop neither re-uploads
  parameters, re-traces, nor allocates: O(1) work per token in the prefix
  length.

Under a mesh, parameters shard by the Megatron column/row plan
(``parallel.tp_rules.plan_tensor_parallel``) and the caches' E (head) dim
shards on 'model' (``parallel.tp_rules.kv_cache_pspec``): each model shard
holds and scores only its own head group's cache slice — the inference-side
counterpart of the training-side ring×TP composition.

:class:`DecodeServer` is the batched serving loop: ``MXNET_DECODE_SLOTS``
in-flight sequence slots at a FIXED batch shape (Orca-style continuous
batching) — new requests prefill into a free slot between decode steps,
sequences retire on EOS/max-len, and the freed slot refills from the
request queue, all without retracing anything.

Decode is bandwidth-bound on the cache, so this module attacks both
factors of ``bytes/token = passes/token x cache bytes``:

* **Speculative decoding** (Leviathan et al. 2023): a proposer drafts k
  tokens — a small draft model through a second ``DecodePredictor``
  (:class:`DraftProposer`) or the model-free n-gram self-speculation
  lookup (:class:`NGramProposer`) — and ONE batched verify pass
  (``ops.attention.sdpa_verify``, fixed shape in k) scores all k+1
  positions against the caches; ``ops.sample.speculative_accept``
  commits the accepted prefix plus one resampled token, preserving the
  target distribution exactly.  Rejection rolls back ``lens`` only (the
  length mask hides the dead cache entries; the next append overwrites
  them), and speculation gates off near the ring-wrap boundary (host-side
  length bookkeeping, no extra device sync) so there is exactly ONE
  draft program and ONE verify program — never a retrace.
* **Quantized KV caches** (``MXNET_KV_DTYPE``: int8 / fp8 with
  per-(token, head) scales, ``ops.attention.QuantKV``): ``cache_append``
  quantizes on the way in, ``sdpa_decode``/``sdpa_verify`` dequantize per
  head on the way out, and the cache bytes every step streams drop 2-4x.
  Scale buffers shard like the caches (``tp_rules.kv_cache_pspec`` — an
  H-split is the same head-group split).

The symbol contract (checked at trace time, documented in
docs/inference.md): decoder-only graphs built from position-independent ops
plus ``dot_product_attention`` for sequence mixing, with at most a learned
positional table added via a ``broadcast_*`` op against a ``(1, S, E)``
variable — ``models.attention_lm`` and the benchmark LMs qualify.
"""
from __future__ import annotations

from collections import deque
from typing import NamedTuple

import numpy as np

from .base import MXNetError
from . import context as ctx_mod
from .registry import OpContext

__all__ = ["DecodePredictor", "DecodeServer", "DecodeState",
           "NGramProposer", "DraftProposer"]

# MXNET_KV_DTYPE spellings -> canonical jnp dtype names (resolved lazily so
# the module imports without jax)
_KV_DTYPES = {
    "int8": "int8", "s8": "int8",
    "float8_e4m3fn": "float8_e4m3fn", "f8e4m3": "float8_e4m3fn",
    "f8e4m3fn": "float8_e4m3fn",
    "float8_e5m2": "float8_e5m2", "f8e5m2": "float8_e5m2",
}

# broadcast ops through which a (1, S, E) position table may meet the
# (B, t, E) activation stream; the decode walk gathers the table rows for
# the CURRENT positions before applying the op
_POSITION_BROADCAST_OPS = {
    "broadcast_add", "broadcast_plus", "broadcast_sub", "broadcast_minus",
    "broadcast_mul",
}


class DecodeState(NamedTuple):
    """The donated per-step serving state (a jax pytree)."""

    caches: tuple       # ((k, v), ...) per attention node: (B, C, E)
                        # arrays, or ops.attention.QuantKV (data + scales)
                        # under a quantized MXNET_KV_DTYPE
    lens: object        # (B,) int32 — tokens appended to each cache so far
    tok: object         # (B, 1) int32 — last sampled token, not yet appended


class DecodePredictor:
    """Incremental-decode executor for a trained attention LM.

    Parameters
    ----------
    symbol : Symbol or str
        The network — a Symbol, a JSON string, or a ``*-symbol.json`` path
        (same forms as :class:`~mxnet_tpu.predictor.Predictor`).
    params : dict, str, or bytes
        Trained parameters (``arg:``/``aux:`` prefixes optional).
    cache_len : int
        Ring-buffer KV-cache length C per attention node.  Generation past
        C tokens wraps: the cache keeps the latest C keys/values
        (sliding-window attention).
    ctx : Context, optional
        Single-device placement; defaults to cpu.  Ignored when ``mesh``
        is given.
    mesh : jax.sharding.Mesh, optional
        Shard parameters by the Megatron plan and KV caches on the
        'model' (head) / 'data' (batch) axes.
    temperature, top_k
        Sampling knobs baked into the step program (0 = greedy).
    data_name : str
        The token-input variable; other free inputs (labels) are fed zeros.
    kv_dtype : str, optional
        KV-cache storage dtype: 'int8', 'float8_e4m3fn' or 'float8_e5m2'
        (per-(token, head) scales, quantize-on-append / dequantize-in-
        kernel).  ``None`` (default) reads ``MXNET_KV_DTYPE``; empty
        string = full-precision caches.
    """

    def __init__(self, symbol, params, cache_len, ctx=None, mesh=None,
                 temperature=0.0, top_k=0, data_name="data", kv_dtype=None):
        import jax
        import jax.numpy as jnp

        from . import symbol as sym_mod
        from .predictor import _as_param_dicts

        if isinstance(symbol, str):
            symbol = sym_mod.load_json(symbol) \
                if symbol.lstrip().startswith("{") else sym_mod.load(symbol)
        self._symbol = symbol
        self._cache_len = int(cache_len)
        if self._cache_len <= 0:
            raise MXNetError("cache_len must be positive")
        self._ctx = ctx if ctx is not None else ctx_mod.cpu()
        self._mesh = mesh
        self._temperature = float(temperature)
        self._top_k = int(top_k)
        self._data_name = data_name

        from . import config as _config

        if kv_dtype is None:
            kv_dtype = _config.get("MXNET_KV_DTYPE")
        kv_dtype = (kv_dtype or "").strip().lower()
        if kv_dtype:
            canonical = _KV_DTYPES.get(kv_dtype)
            if canonical is None:
                raise MXNetError(
                    "unsupported MXNET_KV_DTYPE %r (supported: %s)"
                    % (kv_dtype, sorted(set(_KV_DTYPES.values()))))
            self._kv_dtype = jnp.dtype(canonical)
        else:
            self._kv_dtype = None

        arg_params, aux_params = _as_param_dicts(params)
        free = [n for n in symbol.list_arguments() if n not in arg_params]
        if data_name not in free:
            raise MXNetError("%r is not a free input of the symbol (free "
                             "inputs: %s)" % (data_name, free))
        self._attn_nodes = [n for n in symbol._topo()
                            if not n.is_variable
                            and n.op.name == "dot_product_attention"]
        if not self._attn_nodes:
            raise MXNetError("symbol has no dot_product_attention node; "
                             "nothing to cache — use Predictor")

        self._cache_sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from .parallel.tp_rules import (kv_cache_pspec,
                                            plan_tensor_parallel)

            sizes = dict(mesh.shape)
            model_par = sizes.get("model", 1)
            rep = NamedSharding(mesh, P())
            plan = plan_tensor_parallel(symbol) if model_par > 1 else {}

            def place(name, arr):
                spec = plan.get(name)
                if spec is not None and len(spec) == len(arr.shape) and all(
                        ax is None or arr.shape[d] % sizes.get(ax, 1) == 0
                        for d, ax in enumerate(spec)):
                    return jax.device_put(arr, NamedSharding(mesh, P(*spec)))
                return jax.device_put(arr, rep)

            self._env = {n: place(n, a.data)
                         for n, a in arg_params.items()}
            self._env.update({n: jax.device_put(a.data, rep)
                              for n, a in aux_params.items()})
            self._cache_sharding = NamedSharding(
                mesh, kv_cache_pspec(mesh.shape))
            self._token_sharding = NamedSharding(
                mesh, P("data" if sizes.get("data", 1) > 1 else None, None))
        else:
            dev = self._ctx.jax_device
            self._env = {n: jax.device_put(a.data, dev)
                         for n, a in arg_params.items()}
            self._env.update({n: jax.device_put(a.data, dev)
                              for n, a in aux_params.items()})
            self._token_sharding = dev

        from . import config as _config

        donate = (1,) if _config.get("MXNET_DECODE_DONATE") else ()
        self._donate = bool(donate)
        # retrace instrumentation (analysis.RetracePass): the impl bodies
        # run only while jax traces them, so these counters check the
        # serving loop's "zero retraces" claim — decode and verify must
        # each trace ONCE, prefill once per admitted (B, P) shape.
        # Probes (lowering for artifact/FLOP text) set _probing and don't
        # count.
        self.trace_counts = {"prefill": 0, "decode": 0, "verify": 0}
        self._probing = False
        self._decode_fn = jax.jit(self._decode_impl, donate_argnums=donate)
        self._verify_fn = jax.jit(self._verify_impl, donate_argnums=donate)
        self._verify_shapes = set()   # distinct (B, k, has_q) driven
        self._prefill_fns = {}   # (B, P) -> jitted prefill program
        # jnp dummies reused every call (sample_tokens at temperature 0
        # never reads the key, but the jit signature keeps it)
        self._zero_key = jax.random.PRNGKey(0)

    @property
    def cache_len(self):
        return self._cache_len

    # ------------------------------------------------------------------
    # the shared graph walk (traced inside both programs)
    # ------------------------------------------------------------------
    def _run(self, env, tokens, caches, pos0):
        """Execute the symbol on (B, t) tokens.

        ``caches is None`` = prefill mode: full causal attention, fresh
        ring buffers captured from each attention node's K/V.  Otherwise
        decode mode: append K/V at ``pos0`` (per-sequence), length-masked
        attention against the cache.  Returns ``(probs (B, t, V),
        caches)``.
        """
        import jax
        import jax.numpy as jnp

        from .ops import attention as _attn

        b, t = tokens.shape[0], tokens.shape[1]
        new_caches = []
        ci = 0
        values = {}
        base_key = jax.random.PRNGKey(0)
        for seq, node in enumerate(self._symbol._topo()):
            if node.is_variable:
                if node.name == self._data_name:
                    val = tokens
                elif node.name in env:
                    val = env[node.name]
                else:
                    # unfed free input (loss labels): zeros, forward-unused
                    val = jnp.zeros((b, t), jnp.float32)
                values[(id(node), 0)] = val
                continue
            attrs = node.parsed_attrs()
            n_args = node.op.n_inputs(attrs)
            ins = [values[(id(s), i)] for s, i in node.inputs[:n_args]]
            aux_ins = [values[(id(s), i)] for s, i in node.inputs[n_args:]]
            opname = node.op.name
            if opname == "dot_product_attention":
                q, k, v = ins
                heads = attrs.get("num_heads", 1)
                scale = attrs.get("scale", 0.0) or None
                if caches is None:
                    outs = [_attn.sdpa(q, k, v, num_heads=heads,
                                       causal=attrs.get("causal", False),
                                       scale=scale)]
                    new_caches.append((self._fill_cache(k, heads),
                                       self._fill_cache(v, heads)))
                else:
                    kc, vc = caches[ci]
                    ci += 1
                    kc = _attn.cache_append(kc, k, pos0, num_heads=heads)
                    vc = _attn.cache_append(vc, v, pos0, num_heads=heads)
                    pos = jnp.asarray(pos0, jnp.int32).reshape(-1)
                    sdpa_cached = _attn.sdpa_decode if t == 1 \
                        else _attn.sdpa_verify
                    outs = [sdpa_cached(q, kc, vc, pos + t,
                                        num_heads=heads, scale=scale)]
                    new_caches.append((kc, vc))
            else:
                if opname in _POSITION_BROADCAST_OPS and len(ins) == 2 \
                        and getattr(ins[0], "ndim", 0) == 3 \
                        and getattr(ins[1], "ndim", 0) == 3 \
                        and ins[0].shape[1] != ins[1].shape[1] \
                        and t in (ins[0].shape[1], ins[1].shape[1]):
                    # learned positional table vs the (B, t, E) stream:
                    # gather the rows for the CURRENT positions
                    big_i = 0 if ins[0].shape[1] != t else 1
                    big = ins[big_i]
                    if big.shape[0] != 1:
                        raise MXNetError(
                            "decode: node %r mixes time-lengths %s without "
                            "a broadcastable (1, S, E) side" %
                            (node.name, (ins[0].shape, ins[1].shape)))
                    s_len = big.shape[1]
                    idx = (jnp.asarray(pos0, jnp.int32).reshape(-1, 1)
                           + jnp.arange(t, dtype=jnp.int32)[None, :])
                    idx = jnp.clip(idx, 0, s_len - 1)
                    ins = list(ins)
                    ins[big_i] = jnp.take(big[0], idx, axis=0)
                octx = OpContext(
                    is_train=False,
                    rng=jax.random.fold_in(base_key, seq),
                    mesh_active=self._mesh is not None, mesh=self._mesh)
                outs, _ = node.op.fcompute(attrs, ins, aux_ins, octx)
            for i, o in enumerate(outs):
                values[(id(node), i)] = o
        head_node, head_idx = self._symbol._outputs[0]
        out = values[(id(head_node), head_idx)]
        if out.ndim == 2 and out.shape[0] == b * t:
            out = out.reshape(b, t, -1)
        elif out.ndim != 3:
            raise MXNetError("decode: head output shape %s is not (B*t, V) "
                             "or (B, t, V)" % (out.shape,))
        return out, tuple(new_caches)

    def _fill_cache(self, x, num_heads=1):
        """(B, t, E) prefill K/V -> a (B, C, E) ring buffer holding the t
        tokens at their ``pos % C`` slots (prefill enforces t <= C).
        Under a quantized ``kv_dtype`` the buffer is an
        ``ops.attention.QuantKV`` — data quantized per (token, head), pad
        slots at a floor scale; the fp32 scale plane shards like the data
        (``kv_cache_pspec`` — its trailing H dim is the same head-group
        split as E)."""
        import jax
        import jax.numpy as jnp

        from .ops import attention as _attn

        b, t, e = x.shape
        buf = jnp.zeros((b, self._cache_len, e), x.dtype)
        buf = jax.lax.dynamic_update_slice(buf, x, (0, 0, 0))
        if self._kv_dtype is not None:
            q = _attn.quantize_kv(buf, self._kv_dtype, num_heads)
            if self._cache_sharding is not None:
                q = _attn.QuantKV(
                    jax.lax.with_sharding_constraint(q.data,
                                                     self._cache_sharding),
                    jax.lax.with_sharding_constraint(
                        q.scale, self._scale_sharding(num_heads)))
            return q
        if self._cache_sharding is not None:
            buf = jax.lax.with_sharding_constraint(buf, self._cache_sharding)
        return buf

    @property
    def _greedy(self):
        from .ops.sample import is_greedy_policy

        return is_greedy_policy(self._temperature, self._top_k)

    def _scale_sharding(self, num_heads):
        """Sharding for a (B, C, H) scale plane: the cache spec's head
        axis when H divides it, else replicated heads.  The data plane's
        E-split can be finer than a head split (E % axis == 0 with
        heads % axis != 0 — legal, GSPMD handles the einsum), and the
        tiny scale plane must not turn that config into a trace error."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = self._cache_sharding.spec
        head_ax = spec[2]
        if head_ax is not None and \
                num_heads % dict(self._mesh.shape)[head_ax] != 0:
            return NamedSharding(self._mesh, P(spec[0], None, None))
        return self._cache_sharding

    def _sample(self, key, probs):
        import jax.numpy as jnp

        from .ops.sample import sample_tokens

        if self._greedy:
            # argmax(p) == argmax(log p): skip the log on the hot path
            return jnp.argmax(probs, axis=-1).astype(jnp.int32)[:, None]
        logits = jnp.log(probs.astype(jnp.float32) + 1e-30)
        return sample_tokens(key, logits, self._temperature,
                             self._top_k)[:, None]

    def _policy_probs(self, probs):
        """The EXACT sampling distribution :meth:`_sample` draws from, as
        explicit probability vectors — what speculative acceptance must
        compare against.  Softmax of the SAME ``policy_logits`` the
        sampler's categorical draws over (one implementation, so the two
        cannot drift)."""
        import jax
        import jax.numpy as jnp

        from .ops.sample import policy_logits

        logits = jnp.log(probs.astype(jnp.float32) + 1e-30)
        return jax.nn.softmax(
            policy_logits(logits, self._temperature, self._top_k), axis=-1)

    # ------------------------------------------------------------------
    # the two programs
    # ------------------------------------------------------------------
    def _prefill_impl(self, env, tokens, lens, key):
        import jax.numpy as jnp

        if not self._probing:
            self.trace_counts["prefill"] += 1
        probs3, caches = self._run(env, tokens, None, 0)
        # output at the last REAL prompt position, per sequence
        last = jnp.clip(lens - 1, 0, tokens.shape[1] - 1)
        probs = jnp.take_along_axis(
            probs3, last[:, None, None], axis=1)[:, 0]
        tok = self._sample(key, probs)
        return DecodeState(caches, lens, tok), probs

    def _decode_impl(self, env, state, key):
        if not self._probing:
            self.trace_counts["decode"] += 1
        probs3, caches = self._run(env, state.tok, state.caches, state.lens)
        probs = probs3[:, 0]
        tok = self._sample(key, probs)
        return DecodeState(caches, state.lens + 1, tok), probs

    def _verify_impl(self, env, state, draft_toks, draft_probs, key):
        """ONE batched speculative verify pass: score the last committed
        token + k drafts, accept a prefix, resample at the first
        mismatch.  The cache gets all k+1 K/V appended at fixed width;
        rejection rolls back ``lens`` only — slots past it are masked and
        the next append overwrites them in place."""
        import jax.numpy as jnp

        from .ops.sample import speculative_accept

        if not self._probing:
            self.trace_counts["verify"] += 1
        toks_in = jnp.concatenate(
            [state.tok.astype(jnp.int32), draft_toks.astype(jnp.int32)],
            axis=1)                                        # (B, k+1)
        probs3, caches = self._run(env, toks_in, state.caches, state.lens)
        pi = probs3 if self._greedy else self._policy_probs(probs3)
        counts, out = speculative_accept(key, pi, draft_toks, draft_probs,
                                         greedy=self._greedy)
        tok = jnp.take_along_axis(out, (counts - 1)[:, None], axis=1)
        return (DecodeState(caches, state.lens + counts, tok), out, counts)

    # ------------------------------------------------------------------
    # public surface
    # ------------------------------------------------------------------
    def prefill(self, tokens, prompt_len=None, key=None):
        """Process a (B, P) prompt batch once; returns ``(state, probs)``.

        ``prompt_len`` (int or (B,)) marks the real length per row of a
        padded batch — cache slots past it stay masked until decode
        overwrites them.  ``probs`` is the model's (B, V) output at each
        row's last real position; ``state.tok`` the sampled first token.
        Jitted per (B, P) shape; repeated calls at one shape reuse the
        compiled program (the serving loop's fixed-shape prefill).
        """
        import jax
        import jax.numpy as jnp

        tokens = self._place_tokens(tokens)
        b, p = tokens.shape
        if p > self._cache_len:
            # a wider window would have to wrap PADDED rows over real
            # tokens for rows shorter than the window — refuse instead of
            # silently attending pad K/V; bind a larger cache_len (decode
            # itself may still wrap past it)
            raise MXNetError("prompt width %d exceeds cache_len %d"
                             % (p, self._cache_len))
        if prompt_len is None:
            prompt_len = p
        lens = jnp.broadcast_to(
            jnp.asarray(prompt_len, jnp.int32).reshape(-1), (b,))
        fn = self._prefill_fns.get((b, p))
        if fn is None:
            fn = jax.jit(self._prefill_impl)
            self._prefill_fns[(b, p)] = fn
        return fn(self._env, tokens, lens,
                  key if key is not None else self._zero_key)

    def step(self, state, key=None):
        """One decode step: append ``state.tok``'s K/V, attend, sample.

        Returns ``(state', probs)`` with ``probs`` the (B, V) distribution
        the new ``state'.tok`` was drawn from.  The input state is donated
        (``MXNET_DECODE_DONATE``) — do not reuse it after the call.
        """
        return self._decode_fn(self._env, state,
                               key if key is not None else self._zero_key)

    def verify_step(self, state, draft_toks, draft_probs=None, key=None):
        """One speculative macro-step: verify k drafted tokens in ONE
        target forward, commit the accepted prefix plus a resampled
        token.

        ``draft_toks`` is (B, k) int32; ``draft_probs`` (B, k, V) are the
        proposal distributions they were drawn from (``None`` for a
        deterministic proposer — n-gram lookup or a greedy draft).
        Returns ``(state', out_toks, counts)``: ``out_toks`` (B, k+1) are
        the emitted tokens, valid through ``counts`` (B,) in [1, k+1];
        ``state'.tok`` is the last emitted token, ``state'.lens`` advanced
        by ``counts`` (rejection rollback — rejected cache entries stay
        masked until overwritten).  The caller must keep the verify
        window inside the ring: ``lens + k + 1 <= cache_len`` for every
        live row (the serving loop's host-side gate).  Fixed shape in k —
        one trace per (B, k, has-draft-probs) signature, donated like
        :meth:`step`.
        """
        import jax.numpy as jnp

        draft_toks = jnp.asarray(draft_toks, jnp.int32)
        self._verify_shapes.add((draft_toks.shape[0], draft_toks.shape[1],
                                 draft_probs is not None))
        return self._verify_fn(self._env, state, draft_toks, draft_probs,
                               key if key is not None else self._zero_key)

    def generate_speculative(self, tokens, prompt_len=None,
                             max_new_tokens=16, seed=0, eos_id=None,
                             k=None, draft=None, proposer=None):
        """Speculative :meth:`generate`: a (B, N) int32 array of sampled
        tokens, but each loop iteration drafts ``k`` tokens and commits
        1..k+1 of them through one verify pass.  With ``eos_id``, a row
        retires AT its EOS — the speculation window's tail is discarded
        (the serving loop's rule) and the row pads with its last token,
        where plain :meth:`generate` keeps decoding garbage past EOS —
        slice per row in both cases.

        ``draft`` is an optional small draft model (a second
        ``DecodePredictor`` over the same vocabulary — wrapped in a
        :class:`DraftProposer`); without one, ``proposer`` defaults to the
        model-free :class:`NGramProposer`.  Greedy sampling
        (temperature=0) emits EXACTLY the target-only greedy sequence;
        stochastic sampling preserves the target distribution (the
        acceptance-rejection identity) though not the per-seed sample
        path.  Near the ring-wrap boundary the loop falls back to plain
        single-token steps — both programs already traced, so the
        fallback never retraces.
        """
        import jax

        from . import config as _config

        if k is None:
            k = int(_config.get("MXNET_SPEC_K")) or 4
        k = int(k)
        if k <= 0:
            raise MXNetError("speculative k must be positive (got %d)" % k)
        key = jax.random.PRNGKey(seed)
        key, sub = jax.random.split(key)
        tokens = np.asarray(tokens)
        b = tokens.shape[0]
        if prompt_len is None:
            prompt_len = tokens.shape[1]
        lens_h = np.broadcast_to(
            np.asarray(prompt_len, np.int64).reshape(-1), (b,)).copy()
        state, _ = self.prefill(tokens, prompt_len, sub)

        if proposer is None:
            proposer = DraftProposer(draft, k) if draft is not None \
                else NGramProposer(k)
        else:
            # the proposer's draft width IS the verify shape
            k = int(getattr(proposer, "k", k))
        hist = [list(tokens[i, :lens_h[i]].astype(np.int64))
                for i in range(b)]
        first = np.asarray(state.tok)[:, 0]
        rows = [[int(t)] for t in first]
        for i in range(b):
            hist[i].append(int(first[i]))
        if getattr(proposer, "needs_prefill", False):
            key, sub = jax.random.split(key)
            proposer.start(tokens, prompt_len, sub)

        done = np.array([eos_id is not None and rows[i][-1] == eos_id
                         for i in range(b)])
        # the verify window must not wrap the target ring; a draft model
        # appends k entries to its OWN ring too (proposer.cache_len)
        limit = self._cache_len
        if getattr(proposer, "cache_len", None):
            limit = min(limit, proposer.cache_len + 1)
        while True:
            live = [i for i in range(b) if len(rows[i]) < max_new_tokens
                    and not done[i]]
            if not live:
                break
            key, sub = jax.random.split(key)
            if max(lens_h[i] for i in live) + k + 1 <= limit:
                draft_toks, draft_probs = proposer.propose(
                    hist, state, lens_h, sub)
                key, sub = jax.random.split(key)
                state, out, counts = self.verify_step(
                    state, draft_toks, draft_probs, sub)
                out_h = np.asarray(out)
                counts_h = np.asarray(counts)
            else:
                state, _ = self.step(state, sub)
                out_h = np.asarray(state.tok)
                counts_h = np.ones(b, np.int64)
            lens_h += counts_h
            for i in range(b):
                emitted = [int(t) for t in out_h[i, :counts_h[i]]]
                # history tracks everything COMMITTED to the cache —
                # including any window tail past an EOS
                hist[i].extend(emitted)
                if i in live:
                    if eos_id is not None and eos_id in emitted:
                        # discard the speculation-window tail after EOS
                        # (same rule as DecodeServer's deliver)
                        emitted = emitted[:emitted.index(eos_id) + 1]
                        done[i] = True
                    rows[i].extend(emitted)
        n = min(max_new_tokens, max(len(r) for r in rows))
        out = np.zeros((b, n), np.int32)
        for i in range(b):
            row = (rows[i] + [rows[i][-1]] * n)[:n]
            out[i] = row
        return out

    def generate(self, tokens, prompt_len=None, max_new_tokens=16,
                 seed=0, eos_id=None):
        """Prefill + ``max_new_tokens`` decode steps; returns a (B, N)
        int32 numpy array of sampled tokens (rows keep decoding past
        their EOS — slice per row; the serving loop retires properly)."""
        import jax

        key = jax.random.PRNGKey(seed)
        key, sub = jax.random.split(key)
        state, _ = self.prefill(tokens, prompt_len, sub)
        out = [np.asarray(state.tok)]
        done = (out[0][:, 0] == eos_id) if eos_id is not None else None
        for _ in range(max_new_tokens - 1):
            if done is not None and done.all():
                break
            key, sub = jax.random.split(key)
            state, _ = self.step(state, sub)
            out.append(np.asarray(state.tok))
            if done is not None:
                done |= out[-1][:, 0] == eos_id
        return np.concatenate(out, axis=1)

    def _place_tokens(self, tokens):
        import jax

        from .ndarray import NDArray

        if isinstance(tokens, NDArray):
            tokens = tokens.data
        elif not isinstance(tokens, jax.Array):
            tokens = np.asarray(tokens, np.float32)
        return jax.device_put(tokens, self._token_sharding)

    def decode_step_text(self, state, key=None):
        """Lowered (pre-optimization) StableHLO of the decode-step program
        at this state's shapes — feed to ``parallel.hlo_stats.dot_flops``
        for the O(1)-in-prefix FLOP assertion (bench_decode.py)."""
        self._probing = True
        try:
            return self._decode_fn.lower(
                self._env, state,
                key if key is not None else self._zero_key).as_text()
        finally:
            self._probing = False

    def _prefill_args(self, b, p):
        import jax
        import jax.numpy as jnp

        env = {n: jax.ShapeDtypeStruct(v.shape, v.dtype)
               for n, v in self._env.items()}
        tokens = jax.ShapeDtypeStruct((b, p), jnp.float32)
        lens = jax.ShapeDtypeStruct((b,), jnp.int32)
        key = jax.ShapeDtypeStruct(self._zero_key.shape,
                                   self._zero_key.dtype)
        return env, tokens, lens, key

    def prefill_text(self, b, p):
        """Lowered StableHLO of the (b, p) prefill program — the
        recompute-the-prefix cost baseline for the FLOP assertion."""
        import jax

        fn = self._prefill_fns.get((b, p)) or jax.jit(self._prefill_impl)
        self._probing = True
        try:
            return fn.lower(*self._prefill_args(b, p)).as_text()
        finally:
            self._probing = False

    def prefill_artifact(self, b, p, name="prefill"):
        """:class:`~mxnet_tpu.analysis.artifact.ProgramArtifact` of the
        (b, p) prefill program.  Prefill donates nothing (its caches are
        born inside the program); expected traces = one per distinct
        admitted (B, P) shape."""
        import jax

        from .analysis.artifact import artifact_from_jit

        fn = self._prefill_fns.get((b, p)) or jax.jit(self._prefill_impl)
        count = self.trace_counts["prefill"]
        expected = max(len(self._prefill_fns), 1)
        self._probing = True
        try:
            return artifact_from_jit(
                fn, self._prefill_args(b, p), name=name, donated_leaves=0,
                mesh_shape=dict(self._mesh.shape)
                if self._mesh is not None else None,
                trace_count=count, expected_traces=expected,
                cache_len=self._cache_len)
        finally:
            self._probing = False

    def cache_bytes(self, state):
        """Static byte size of the ring caches behind ``state`` — data
        AND scale planes — sized through the analysis width table
        (``analysis.hlo_parse.shape_bytes``, f8/sub-byte aware), so the
        number mxlint budgets and the bench's tokens/s/GB headline share
        one accounting."""
        import jax.tree_util as jtu

        from .analysis.hlo_parse import shape_bytes, shape_str

        return sum(shape_bytes(shape_str(leaf.shape, leaf.dtype))
                   for leaf in jtu.tree_leaves(state.caches))

    def _cache_meta(self, state):
        """Cache metadata for artifacts: the static byte budget plus the
        DATA dtypes actually stored (the cache-bytes pass flags an f32
        data plane inside a quantized config from these)."""
        from .ops.attention import QuantKV

        dtypes = set()
        for kc, vc in state.caches:
            for c in (kc, vc):
                dtypes.add(str((c.data if isinstance(c, QuantKV)
                                else c).dtype))
        return {"cache_bytes": self.cache_bytes(state),
                "kv_dtype": str(self._kv_dtype)
                if self._kv_dtype is not None else None,
                "cache_data_dtypes": sorted(dtypes)}

    def decode_artifact(self, state, key=None, name="decode_step"):
        """:class:`~mxnet_tpu.analysis.artifact.ProgramArtifact` of the
        donated decode-step program at this state's shapes — the "zero
        retraces / zero allocation per token" serving claims as checkable
        metadata (donated leaves = every cache/len/token buffer; cache
        byte + dtype meta for the cache-bytes pass)."""
        import jax.tree_util as jtu

        from .analysis.artifact import artifact_from_jit, aval_of as _aval

        env = {n: _aval(v) for n, v in self._env.items()}
        astate = jtu.tree_map(_aval, state)
        akey = _aval(key if key is not None else self._zero_key)
        donated = len(jtu.tree_leaves(astate)) if self._donate else 0
        count = self.trace_counts["decode"]
        self._probing = True
        try:
            return artifact_from_jit(
                self._decode_fn, (env, astate, akey), name=name,
                donated_leaves=donated,
                mesh_shape=dict(self._mesh.shape)
                if self._mesh is not None else None,
                trace_count=count, expected_traces=1,
                cache_len=self._cache_len, **self._cache_meta(state))
        finally:
            self._probing = False

    def verify_artifact(self, state, k, draft_probs=None, key=None,
                        name="verify_step"):
        """:class:`~mxnet_tpu.analysis.artifact.ProgramArtifact` of the
        donated speculative-verify program at this state's shapes and
        draft width ``k`` — same donation/retrace/cache-byte contract as
        the decode step (expected traces = one per driven (B, k, has-q)
        signature).  ``draft_probs`` (array or aval) selects the
        with-proposal-distribution variant; ``None`` the deterministic-
        proposer one."""
        import jax.numpy as jnp
        import jax.tree_util as jtu

        from .analysis.artifact import artifact_from_jit, aval_of as _aval

        import jax

        env = {n: _aval(v) for n, v in self._env.items()}
        astate = jtu.tree_map(_aval, state)
        b = state.lens.shape[0]
        atoks = jax.ShapeDtypeStruct((b, int(k)), jnp.int32)
        aq = _aval(draft_probs) if draft_probs is not None else None
        akey = _aval(key if key is not None else self._zero_key)
        donated = len(jtu.tree_leaves(astate)) if self._donate else 0
        count = self.trace_counts["verify"]
        expected = max(len(self._verify_shapes), 1)
        self._probing = True
        try:
            return artifact_from_jit(
                self._verify_fn, (env, astate, atoks, aq, akey), name=name,
                donated_leaves=donated,
                mesh_shape=dict(self._mesh.shape)
                if self._mesh is not None else None,
                trace_count=count, expected_traces=expected,
                cache_len=self._cache_len, spec_k=int(k),
                **self._cache_meta(state))
        finally:
            self._probing = False


def _build_insert_fn():
    """Jitted splice of a batch-1 :class:`DecodeState` into slot ``slot``
    of a batch state (traced slot index — admission never retraces).
    Generic over the cache pytree, so quantized caches (data + scale
    leaves) and draft-model states ride the same machinery."""
    import jax

    from . import config as _config

    donate = (0,) if _config.get("MXNET_DECODE_DONATE") else ()

    def insert(state, one, slot):
        import jax.numpy as jnp
        import jax.tree_util as jtu

        slot = jnp.asarray(slot, jnp.int32)

        def put(full, single):
            idx = (slot,) + (jnp.int32(0),) * (full.ndim - 1)
            return jax.lax.dynamic_update_slice(full, single, idx)

        return jtu.tree_map(put, state, one)

    return jax.jit(insert, donate_argnums=donate)


def _empty_batch_state(one, slots):
    """An all-zero batch state with ``slots`` rows shaped like the
    batch-1 state ``one``."""
    import jax.numpy as jnp
    import jax.tree_util as jtu

    return jtu.tree_map(
        lambda x: jnp.zeros((slots,) + tuple(x.shape[1:]), x.dtype), one)


class NGramProposer:
    """Model-free draft proposer: n-gram lookup over each sequence's own
    history (prompt-lookup / self-speculation).

    Matches the last ``ngram`` committed tokens (``MXNET_SPEC_NGRAM``)
    against earlier history and proposes the k tokens that followed the
    most recent earlier occurrence, backing off to shorter suffixes and
    finally to repeating the last token — always exactly k proposals, so
    the verify shape stays fixed.  Deterministic, so its proposal
    distribution is a delta and :func:`ops.sample.speculative_accept`
    needs no q vectors (``draft_probs=None``).  Pure host-side numpy: the
    proposer costs no device program at all, which is what makes
    self-speculation profitable even at high rejection rates.
    """

    cache_len = None      # no draft ring to keep inside
    needs_prefill = False

    def __init__(self, k, ngram=None):
        from . import config as _config

        self.k = int(k)
        if self.k <= 0:
            raise MXNetError("NGramProposer k must be positive")
        self.ngram = int(ngram) if ngram is not None \
            else int(_config.get("MXNET_SPEC_NGRAM"))
        self.ngram = max(1, self.ngram)

    def propose(self, histories, state=None, lens=None, key=None):
        out = np.zeros((len(histories), self.k), np.int32)
        for r, h in enumerate(histories):
            out[r] = self._row(np.asarray(h, np.int64).reshape(-1))
        return out, None

    def _row(self, h):
        k = self.k
        if h.size == 0:
            return np.zeros(k, np.int32)
        for n in range(min(self.ngram, h.size - 1), 0, -1):
            # vectorized suffix match over every window start with a
            # continuation (body drops the last element, so i + n < |h|
            # holds for free and the suffix's own occurrence is excluded)
            body = h[:-1]
            if body.size < n:
                continue
            win = np.lib.stride_tricks.sliding_window_view(body, n)
            hits = np.flatnonzero((win == h[-n:]).all(axis=1))
            if hits.size:
                i = int(hits[-1])            # most recent earlier match
                cont = h[i + n:i + n + k]
                pad = np.full(k - cont.size, cont[-1], np.int64)
                return np.concatenate([cont, pad]).astype(np.int32)
        return np.full(k, h[-1], np.int32)


class DraftProposer:
    """Draft-model proposer: k autoregressive steps of a SMALL
    :class:`DecodePredictor` over the same vocabulary.

    The draft keeps its own ring caches in lockstep with the target's
    committed prefix: each macro-step it resumes from the target's
    (lens, tok) — rejection rollback is free, rejected draft cache
    entries sit past ``lens`` where the length mask hides them until the
    next append overwrites them.  Committed tokens the draft never
    stepped through (the k-th draft of a fully-accepted window; tokens
    decoded by plain near-wrap fallback steps) are healed by a
    teacher-forced CATCH-UP at the top of :meth:`propose`: per-row
    ``filled`` counters (host-side, fed by the caller's committed-token
    histories — no extra device sync) replay the missing inputs through
    the same decode-step program, so the draft cache never holds a
    permanent hole and acceptance does not decay over long serves.  A
    greedy draft proposes deterministically (``draft_probs=None``, delta
    proposals); a stochastic draft returns its exact per-step sampling
    distributions so the acceptance ratio p/q and the residual are
    well-defined.  One decode-step program on the draft, traced once —
    the "draft" program mxlint audits.
    """

    needs_prefill = True

    def __init__(self, predictor, k):
        self._pred = predictor
        self.k = int(k)
        if self.k <= 0:
            raise MXNetError("DraftProposer k must be positive")
        self.cache_len = predictor.cache_len
        self._state = None
        self._insert = None
        self._filled = None     # (B,) host int64: cache valid through

    @property
    def predictor(self):
        return self._pred

    def start(self, tokens, prompt_len, key=None):
        """Prefill the draft on the same (B, P) prompt batch (the
        fixed-batch :meth:`DecodePredictor.generate_speculative` path)."""
        self._state, _ = self._pred.prefill(tokens, prompt_len, key)
        b = self._state.lens.shape[0]
        self._filled = np.broadcast_to(
            np.asarray(prompt_len, np.int64).reshape(-1), (b,)).copy()

    def admit(self, tokens, prompt_len, slot, slots, key=None):
        """Prefill ONE request and splice it into draft slot ``slot`` —
        the serving-loop path (mirrors the server's own admission)."""
        one, _ = self._pred.prefill(tokens, prompt_len, key)
        if self._state is None:
            self._state = _empty_batch_state(one, slots)
            self._filled = np.zeros(slots, np.int64)
        if self._insert is None:
            self._insert = _build_insert_fn()
        self._state = self._insert(self._state, one, np.int32(slot))
        self._filled[slot] = int(prompt_len)

    def _hist_tok(self, histories, pos):
        """(B, 1) int32 of each row's committed token at ``pos`` (host;
        clamped — rows past their history just replay their last
        token, which only touches already-dead cache slots)."""
        out = np.zeros((len(histories), 1), np.int32)
        for r, h in enumerate(histories):
            out[r, 0] = int(h[min(int(pos[r]), len(h) - 1)])
        return out

    def propose(self, histories, state, lens, key=None):
        """Teacher-forced catch-up to the target's committed prefix,
        then k draft steps; returns ``(draft_toks (B, k), draft_probs
        (B, k, V) | None)``.  ``lens`` is the caller's HOST-side
        committed-length vector (the serving loops already track it)."""
        import jax
        import jax.numpy as jnp

        if self._state is None:
            raise MXNetError("DraftProposer.propose before start()/admit()")
        if key is None:
            key = jax.random.PRNGKey(0)
        lens_h = np.broadcast_to(
            np.asarray(lens, np.int64).reshape(-1),
            (self._state.lens.shape[0],)).copy()

        # --- catch-up: replay committed tokens the draft never saw
        # (position `filled` onward) through the same step program.
        # Rows already caught up harmlessly re-append their pending
        # token at `lens` — the very slot the proposal steps below
        # overwrite first.  Usual gap is 0 or 1 (the k-th draft of a
        # fully-accepted window); fallback eras pay theirs here too.
        cur = np.minimum(self._filled, lens_h)
        st = self._state
        for _ in range(int((lens_h - cur).max()) if cur.size else 0):
            st = DecodeState(st.caches, jnp.asarray(cur, jnp.int32),
                             jnp.asarray(self._hist_tok(histories, cur)))
            key, sub = jax.random.split(key)
            st, _ = self._pred.step(st, sub)
            cur = np.minimum(cur + 1, lens_h)

        # --- k proposal steps from the target's committed (lens, tok).
        # Fresh copies: the draft step DONATES its state, and lens/tok
        # here are the target's live buffers.
        st = DecodeState(st.caches, state.lens + 0, state.tok + 0)
        toks, qs = [], []
        for _ in range(self.k):
            key, sub = jax.random.split(key)
            st, probs = self._pred.step(st, sub)
            # st.tok is donated into the NEXT draft step — keep a copy
            toks.append(st.tok + 0)
            if not self._pred._greedy:
                qs.append(self._pred._policy_probs(probs))
        self._state = st
        # appended inputs were [tok, d_1..d_{k-1}]: valid through the
        # accepted prefix, which the caller's next `lens` reveals
        self._filled = lens_h + self.k
        return (jnp.concatenate(toks, axis=1),
                jnp.stack(qs, axis=1) if qs else None)


class DecodeServer:
    """Continuous batching over a :class:`DecodePredictor`.

    ``slots`` in-flight sequences decode as ONE fixed-shape batch; between
    steps, finished sequences (EOS or per-request max-len) retire and free
    slots refill from the request queue via a single-sequence prefill
    spliced into the batch state with ``jax.lax.dynamic_update_slice``
    (slot index traced, so admission never retraces).  Single-threaded by
    design: the serving loop IS the schedule (Orca iteration-level
    scheduling), callers queue requests with :meth:`submit` and drain with
    :meth:`run`.
    """

    def __init__(self, predictor, max_prefill, slots=None, eos_id=None,
                 max_new_tokens=None, seed=0, spec_k=None, proposer=None,
                 draft=None):
        from . import config as _config

        self._pred = predictor
        self._max_prefill = int(max_prefill)
        if self._max_prefill > predictor.cache_len:
            raise MXNetError("max_prefill %d exceeds the predictor's "
                             "cache_len %d" % (self._max_prefill,
                                               predictor.cache_len))
        self._slots = int(slots or _config.get("MXNET_DECODE_SLOTS"))
        self._eos_id = eos_id
        self._max_new = int(max_new_tokens) if max_new_tokens is not None \
            else int(_config.get("MXNET_DECODE_MAX_NEW"))
        self._seed = seed
        self._queue = deque()
        self._next_id = 0
        self._insert_fn = None
        # --- speculative decoding (MXNET_SPEC_K / explicit args) ---
        if spec_k is None:
            spec_k = int(_config.get("MXNET_SPEC_K"))
        if proposer is not None:
            spec_k = int(getattr(proposer, "k", spec_k))
        elif draft is not None:
            spec_k = int(spec_k) or 4
            proposer = DraftProposer(draft, spec_k)
        elif spec_k:
            proposer = NGramProposer(spec_k)
        self._spec_k = int(spec_k or 0)
        self._proposer = proposer
        if proposer is not None and getattr(proposer, "cache_len", None):
            if self._max_prefill > proposer.cache_len:
                raise MXNetError(
                    "max_prefill %d exceeds the draft's cache_len %d"
                    % (self._max_prefill, proposer.cache_len))
        self.steps = 0          # device steps executed (bench accounting)
        self.spec_steps = 0     # of which speculative verify steps
        self.tokens_out = 0     # tokens delivered to finished requests
        self.proposed = 0       # drafted tokens offered to verify
        self.accepted = 0       # drafted tokens accepted

    @property
    def accept_rate(self):
        """Fraction of drafted tokens the target accepted (the k-tuning
        signal: tokens/step = 1 + accept_rate * k on average)."""
        return self.accepted / max(self.proposed, 1)

    def submit(self, tokens, max_new_tokens=None):
        """Queue a prompt (1-D int sequence); returns the request id."""
        tokens = np.asarray(tokens).reshape(-1)
        if tokens.size > self._max_prefill:
            raise MXNetError("prompt length %d exceeds max_prefill %d"
                             % (tokens.size, self._max_prefill))
        rid = self._next_id
        self._next_id += 1
        cap = int(max_new_tokens) if max_new_tokens is not None \
            else self._max_new
        self._queue.append((rid, tokens, cap))
        return rid

    def run(self):
        """Drain the queue; returns ``{request_id: np.int32 array}`` of
        generated tokens (EOS included when hit).

        With speculation armed (``spec_k``/``MXNET_SPEC_K``/``proposer``/
        ``draft``), each iteration drafts k tokens per slot and commits
        1..k+1 through ONE verify pass; a sequence that emits EOS or hits
        its cap MID-WINDOW retires immediately — the window's later
        tokens are discarded from the result (their cache entries are
        dead weight the next admission overwrites) and the freed slot
        refills before the next step.  Near the ring-wrap boundary the
        loop falls back to plain single-token steps (both programs
        already traced — still zero retraces).
        """
        import jax

        key = jax.random.PRNGKey(self._seed)
        state = None
        active = {}     # slot -> [rid, tokens list, max_new]
        results = {}
        histories = {}  # slot -> committed token list (proposer food)
        slot_lens = np.zeros(self._slots, np.int64)
        proposer = self._proposer
        k = self._spec_k
        limit = self._pred.cache_len
        if proposer is not None and getattr(proposer, "cache_len", None):
            limit = min(limit, proposer.cache_len + 1)
        if self._insert_fn is None:
            self._insert_fn = _build_insert_fn()

        def retire():
            for slot in list(active):
                rid, toks, max_new = active[slot]
                if (self._eos_id is not None and toks
                        and toks[-1] == self._eos_id) \
                        or len(toks) >= max_new:
                    results[rid] = np.asarray(toks, np.int32)
                    self.tokens_out += len(toks)
                    del active[slot]

        def deliver(rec, emitted):
            """Append a window of emitted tokens to a request, honoring
            its cap and retiring at an EOS inside the window."""
            _, toks, max_new = rec
            for t in emitted:
                if len(toks) >= max_new:
                    break
                toks.append(int(t))
                if self._eos_id is not None and t == self._eos_id:
                    break

        while self._queue or active:
            # admit: prefill one request per free slot, splice into batch
            while self._queue and len(active) < self._slots:
                rid, prompt, max_new = self._queue.popleft()
                padded = np.zeros((1, self._max_prefill), np.float32)
                padded[0, :prompt.size] = prompt
                key, sub = jax.random.split(key)
                one, _ = self._pred.prefill(padded, prompt.size, sub)
                slot = next(s for s in range(self._slots)
                            if s not in active)
                if state is None:
                    state = _empty_batch_state(one, self._slots)
                first = int(np.asarray(one.tok)[0, 0])
                state = self._insert_fn(state, one, np.int32(slot))
                if proposer is not None \
                        and getattr(proposer, "needs_prefill", False):
                    key, sub = jax.random.split(key)
                    proposer.admit(padded, prompt.size, slot, self._slots,
                                   sub)
                active[slot] = [rid, [first], max_new]
                histories[slot] = list(prompt.astype(np.int64)) + [first]
                slot_lens[slot] = prompt.size
            retire()
            if not active:
                continue
            key, sub = jax.random.split(key)
            can_spec = proposer is not None and k > 0 and \
                max(slot_lens[s] for s in active) + k + 1 <= limit
            if can_spec:
                hists = [histories.get(s) or [0] for s in range(self._slots)]
                draft_toks, draft_probs = proposer.propose(
                    hists, state, slot_lens, sub)
                key, sub = jax.random.split(key)
                state, out, counts = self._pred.verify_step(
                    state, draft_toks, draft_probs, sub)
                out_h = np.asarray(out)
                counts_h = np.asarray(counts).astype(np.int64)
                self.steps += 1
                self.spec_steps += 1
                for slot, rec in active.items():
                    emitted = out_h[slot, :counts_h[slot]]
                    self.proposed += k
                    self.accepted += int(counts_h[slot]) - 1
                    deliver(rec, emitted)
                    histories[slot].extend(int(t) for t in emitted)
                slot_lens += counts_h
            else:
                state, _ = self._pred.step(state, sub)
                self.steps += 1
                toks = np.asarray(state.tok)[:, 0]
                for slot, rec in active.items():
                    deliver(rec, toks[slot:slot + 1])
                    histories[slot].append(int(toks[slot]))
                slot_lens += 1
            retire()
        return results
