"""KV-cached autoregressive decoding — prefill/decode split + batched serving.

The ``Predictor`` runs a whole forward per call, so generating token T
re-executes the full prefix: O(T^2) work per sequence.  This module is the
TPU-era serving path (Pope et al., "Efficiently Scaling Transformer
Inference"): :class:`DecodePredictor` splits an ``attention_lm``-style
symbol into TWO jitted programs —

* **prefill** — one full causal forward over the prompt that additionally
  captures every ``dot_product_attention`` node's K/V into a preallocated
  ring-buffer cache (``ops.attention.cache_append`` layout), and samples
  the first output token;
* **decode step** — one token per call: embed the last sampled token,
  append its K/V at the next ring slot (``jax.lax.dynamic_update_slice``),
  attend the single query position against the cache with a length-masked
  softmax (``ops.attention.sdpa_decode``), sample the next token
  (``ops.sample.sample_tokens``).  The program carries ``(params, state,
  rng)`` with the state (caches + per-sequence lengths + last token)
  DONATED (``MXNET_DECODE_DONATE``), so the token loop neither re-uploads
  parameters, re-traces, nor allocates: O(1) work per token in the prefix
  length.

Under a mesh, parameters shard by the Megatron column/row plan
(``parallel.tp_rules.plan_tensor_parallel``) and the caches' E (head) dim
shards on 'model' (``parallel.tp_rules.kv_cache_pspec``): each model shard
holds and scores only its own head group's cache slice — the inference-side
counterpart of the training-side ring×TP composition.

:class:`DecodeServer` is the batched serving loop: ``MXNET_DECODE_SLOTS``
in-flight sequence slots at a FIXED batch shape (Orca-style continuous
batching) — new requests prefill into a free slot between decode steps,
sequences retire on EOS/max-len, and the freed slot refills from the
request queue, all without retracing anything.

The symbol contract (checked at trace time, documented in
docs/inference.md): decoder-only graphs built from position-independent ops
plus ``dot_product_attention`` for sequence mixing, with at most a learned
positional table added via a ``broadcast_*`` op against a ``(1, S, E)``
variable — ``models.attention_lm`` and the benchmark LMs qualify.
"""
from __future__ import annotations

from collections import deque
from typing import NamedTuple

import numpy as np

from .base import MXNetError
from . import context as ctx_mod
from .registry import OpContext

__all__ = ["DecodePredictor", "DecodeServer", "DecodeState"]

# broadcast ops through which a (1, S, E) position table may meet the
# (B, t, E) activation stream; the decode walk gathers the table rows for
# the CURRENT positions before applying the op
_POSITION_BROADCAST_OPS = {
    "broadcast_add", "broadcast_plus", "broadcast_sub", "broadcast_minus",
    "broadcast_mul",
}


class DecodeState(NamedTuple):
    """The donated per-step serving state (a jax pytree)."""

    caches: tuple       # ((k, v), ...) per attention node, each (B, C, E)
    lens: object        # (B,) int32 — tokens appended to each cache so far
    tok: object         # (B, 1) int32 — last sampled token, not yet appended


class DecodePredictor:
    """Incremental-decode executor for a trained attention LM.

    Parameters
    ----------
    symbol : Symbol or str
        The network — a Symbol, a JSON string, or a ``*-symbol.json`` path
        (same forms as :class:`~mxnet_tpu.predictor.Predictor`).
    params : dict, str, or bytes
        Trained parameters (``arg:``/``aux:`` prefixes optional).
    cache_len : int
        Ring-buffer KV-cache length C per attention node.  Generation past
        C tokens wraps: the cache keeps the latest C keys/values
        (sliding-window attention).
    ctx : Context, optional
        Single-device placement; defaults to cpu.  Ignored when ``mesh``
        is given.
    mesh : jax.sharding.Mesh, optional
        Shard parameters by the Megatron plan and KV caches on the
        'model' (head) / 'data' (batch) axes.
    temperature, top_k
        Sampling knobs baked into the step program (0 = greedy).
    data_name : str
        The token-input variable; other free inputs (labels) are fed zeros.
    """

    def __init__(self, symbol, params, cache_len, ctx=None, mesh=None,
                 temperature=0.0, top_k=0, data_name="data"):
        import jax
        import jax.numpy as jnp

        from . import symbol as sym_mod
        from .predictor import _as_param_dicts

        if isinstance(symbol, str):
            symbol = sym_mod.load_json(symbol) \
                if symbol.lstrip().startswith("{") else sym_mod.load(symbol)
        self._symbol = symbol
        self._cache_len = int(cache_len)
        if self._cache_len <= 0:
            raise MXNetError("cache_len must be positive")
        self._ctx = ctx if ctx is not None else ctx_mod.cpu()
        self._mesh = mesh
        self._temperature = float(temperature)
        self._top_k = int(top_k)
        self._data_name = data_name

        arg_params, aux_params = _as_param_dicts(params)
        free = [n for n in symbol.list_arguments() if n not in arg_params]
        if data_name not in free:
            raise MXNetError("%r is not a free input of the symbol (free "
                             "inputs: %s)" % (data_name, free))
        self._attn_nodes = [n for n in symbol._topo()
                            if not n.is_variable
                            and n.op.name == "dot_product_attention"]
        if not self._attn_nodes:
            raise MXNetError("symbol has no dot_product_attention node; "
                             "nothing to cache — use Predictor")

        self._cache_sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from .parallel.tp_rules import (kv_cache_pspec,
                                            plan_tensor_parallel)

            sizes = dict(mesh.shape)
            model_par = sizes.get("model", 1)
            rep = NamedSharding(mesh, P())
            plan = plan_tensor_parallel(symbol) if model_par > 1 else {}

            def place(name, arr):
                spec = plan.get(name)
                if spec is not None and len(spec) == len(arr.shape) and all(
                        ax is None or arr.shape[d] % sizes.get(ax, 1) == 0
                        for d, ax in enumerate(spec)):
                    return jax.device_put(arr, NamedSharding(mesh, P(*spec)))
                return jax.device_put(arr, rep)

            self._env = {n: place(n, a.data)
                         for n, a in arg_params.items()}
            self._env.update({n: jax.device_put(a.data, rep)
                              for n, a in aux_params.items()})
            self._cache_sharding = NamedSharding(
                mesh, kv_cache_pspec(mesh.shape))
            self._token_sharding = NamedSharding(
                mesh, P("data" if sizes.get("data", 1) > 1 else None, None))
        else:
            dev = self._ctx.jax_device
            self._env = {n: jax.device_put(a.data, dev)
                         for n, a in arg_params.items()}
            self._env.update({n: jax.device_put(a.data, dev)
                              for n, a in aux_params.items()})
            self._token_sharding = dev

        from . import config as _config

        donate = (1,) if _config.get("MXNET_DECODE_DONATE") else ()
        self._donate = bool(donate)
        # retrace instrumentation (analysis.RetracePass): the impl bodies
        # run only while jax traces them, so these counters check the
        # serving loop's "zero retraces" claim — decode must trace ONCE,
        # prefill once per admitted (B, P) shape.  Probes (lowering for
        # artifact/FLOP text) set _probing and don't count.
        self.trace_counts = {"prefill": 0, "decode": 0}
        self._probing = False
        self._decode_fn = jax.jit(self._decode_impl, donate_argnums=donate)
        self._prefill_fns = {}   # (B, P) -> jitted prefill program
        # jnp dummies reused every call (sample_tokens at temperature 0
        # never reads the key, but the jit signature keeps it)
        self._zero_key = jax.random.PRNGKey(0)

    @property
    def cache_len(self):
        return self._cache_len

    # ------------------------------------------------------------------
    # the shared graph walk (traced inside both programs)
    # ------------------------------------------------------------------
    def _run(self, env, tokens, caches, pos0):
        """Execute the symbol on (B, t) tokens.

        ``caches is None`` = prefill mode: full causal attention, fresh
        ring buffers captured from each attention node's K/V.  Otherwise
        decode mode: append K/V at ``pos0`` (per-sequence), length-masked
        attention against the cache.  Returns ``(probs (B, t, V),
        caches)``.
        """
        import jax
        import jax.numpy as jnp

        from .ops import attention as _attn

        b, t = tokens.shape[0], tokens.shape[1]
        new_caches = []
        ci = 0
        values = {}
        base_key = jax.random.PRNGKey(0)
        for seq, node in enumerate(self._symbol._topo()):
            if node.is_variable:
                if node.name == self._data_name:
                    val = tokens
                elif node.name in env:
                    val = env[node.name]
                else:
                    # unfed free input (loss labels): zeros, forward-unused
                    val = jnp.zeros((b, t), jnp.float32)
                values[(id(node), 0)] = val
                continue
            attrs = node.parsed_attrs()
            n_args = node.op.n_inputs(attrs)
            ins = [values[(id(s), i)] for s, i in node.inputs[:n_args]]
            aux_ins = [values[(id(s), i)] for s, i in node.inputs[n_args:]]
            opname = node.op.name
            if opname == "dot_product_attention":
                q, k, v = ins
                heads = attrs.get("num_heads", 1)
                scale = attrs.get("scale", 0.0) or None
                if caches is None:
                    outs = [_attn.sdpa(q, k, v, num_heads=heads,
                                       causal=attrs.get("causal", False),
                                       scale=scale)]
                    new_caches.append((self._fill_cache(k),
                                       self._fill_cache(v)))
                else:
                    kc, vc = caches[ci]
                    ci += 1
                    kc = _attn.cache_append(kc, k, pos0)
                    vc = _attn.cache_append(vc, v, pos0)
                    pos = jnp.asarray(pos0, jnp.int32).reshape(-1)
                    outs = [_attn.sdpa_decode(q, kc, vc, pos + t,
                                              num_heads=heads, scale=scale)]
                    new_caches.append((kc, vc))
            else:
                if opname in _POSITION_BROADCAST_OPS and len(ins) == 2 \
                        and getattr(ins[0], "ndim", 0) == 3 \
                        and getattr(ins[1], "ndim", 0) == 3 \
                        and ins[0].shape[1] != ins[1].shape[1] \
                        and t in (ins[0].shape[1], ins[1].shape[1]):
                    # learned positional table vs the (B, t, E) stream:
                    # gather the rows for the CURRENT positions
                    big_i = 0 if ins[0].shape[1] != t else 1
                    big = ins[big_i]
                    if big.shape[0] != 1:
                        raise MXNetError(
                            "decode: node %r mixes time-lengths %s without "
                            "a broadcastable (1, S, E) side" %
                            (node.name, (ins[0].shape, ins[1].shape)))
                    s_len = big.shape[1]
                    idx = (jnp.asarray(pos0, jnp.int32).reshape(-1, 1)
                           + jnp.arange(t, dtype=jnp.int32)[None, :])
                    idx = jnp.clip(idx, 0, s_len - 1)
                    ins = list(ins)
                    ins[big_i] = jnp.take(big[0], idx, axis=0)
                octx = OpContext(
                    is_train=False,
                    rng=jax.random.fold_in(base_key, seq),
                    mesh_active=self._mesh is not None, mesh=self._mesh)
                outs, _ = node.op.fcompute(attrs, ins, aux_ins, octx)
            for i, o in enumerate(outs):
                values[(id(node), i)] = o
        head_node, head_idx = self._symbol._outputs[0]
        out = values[(id(head_node), head_idx)]
        if out.ndim == 2 and out.shape[0] == b * t:
            out = out.reshape(b, t, -1)
        elif out.ndim != 3:
            raise MXNetError("decode: head output shape %s is not (B*t, V) "
                             "or (B, t, V)" % (out.shape,))
        return out, tuple(new_caches)

    def _fill_cache(self, x):
        """(B, t, E) prefill K/V -> a (B, C, E) ring buffer holding the t
        tokens at their ``pos % C`` slots (prefill enforces t <= C)."""
        import jax
        import jax.numpy as jnp

        b, t, e = x.shape
        buf = jnp.zeros((b, self._cache_len, e), x.dtype)
        buf = jax.lax.dynamic_update_slice(buf, x, (0, 0, 0))
        if self._cache_sharding is not None:
            buf = jax.lax.with_sharding_constraint(buf, self._cache_sharding)
        return buf

    def _sample(self, key, probs):
        import jax.numpy as jnp

        from .ops.sample import sample_tokens

        logits = jnp.log(probs.astype(jnp.float32) + 1e-30)
        return sample_tokens(key, logits, self._temperature,
                             self._top_k)[:, None]

    # ------------------------------------------------------------------
    # the two programs
    # ------------------------------------------------------------------
    def _prefill_impl(self, env, tokens, lens, key):
        import jax.numpy as jnp

        if not self._probing:
            self.trace_counts["prefill"] += 1
        probs3, caches = self._run(env, tokens, None, 0)
        # output at the last REAL prompt position, per sequence
        last = jnp.clip(lens - 1, 0, tokens.shape[1] - 1)
        probs = jnp.take_along_axis(
            probs3, last[:, None, None], axis=1)[:, 0]
        tok = self._sample(key, probs)
        return DecodeState(caches, lens, tok), probs

    def _decode_impl(self, env, state, key):
        if not self._probing:
            self.trace_counts["decode"] += 1
        probs3, caches = self._run(env, state.tok, state.caches, state.lens)
        probs = probs3[:, 0]
        tok = self._sample(key, probs)
        return DecodeState(caches, state.lens + 1, tok), probs

    # ------------------------------------------------------------------
    # public surface
    # ------------------------------------------------------------------
    def prefill(self, tokens, prompt_len=None, key=None):
        """Process a (B, P) prompt batch once; returns ``(state, probs)``.

        ``prompt_len`` (int or (B,)) marks the real length per row of a
        padded batch — cache slots past it stay masked until decode
        overwrites them.  ``probs`` is the model's (B, V) output at each
        row's last real position; ``state.tok`` the sampled first token.
        Jitted per (B, P) shape; repeated calls at one shape reuse the
        compiled program (the serving loop's fixed-shape prefill).
        """
        import jax
        import jax.numpy as jnp

        tokens = self._place_tokens(tokens)
        b, p = tokens.shape
        if p > self._cache_len:
            # a wider window would have to wrap PADDED rows over real
            # tokens for rows shorter than the window — refuse instead of
            # silently attending pad K/V; bind a larger cache_len (decode
            # itself may still wrap past it)
            raise MXNetError("prompt width %d exceeds cache_len %d"
                             % (p, self._cache_len))
        if prompt_len is None:
            prompt_len = p
        lens = jnp.broadcast_to(
            jnp.asarray(prompt_len, jnp.int32).reshape(-1), (b,))
        fn = self._prefill_fns.get((b, p))
        if fn is None:
            fn = jax.jit(self._prefill_impl)
            self._prefill_fns[(b, p)] = fn
        return fn(self._env, tokens, lens,
                  key if key is not None else self._zero_key)

    def step(self, state, key=None):
        """One decode step: append ``state.tok``'s K/V, attend, sample.

        Returns ``(state', probs)`` with ``probs`` the (B, V) distribution
        the new ``state'.tok`` was drawn from.  The input state is donated
        (``MXNET_DECODE_DONATE``) — do not reuse it after the call.
        """
        return self._decode_fn(self._env, state,
                               key if key is not None else self._zero_key)

    def generate(self, tokens, prompt_len=None, max_new_tokens=16,
                 seed=0, eos_id=None):
        """Prefill + ``max_new_tokens`` decode steps; returns a (B, N)
        int32 numpy array of sampled tokens (rows keep decoding past
        their EOS — slice per row; the serving loop retires properly)."""
        import jax

        key = jax.random.PRNGKey(seed)
        key, sub = jax.random.split(key)
        state, _ = self.prefill(tokens, prompt_len, sub)
        out = [np.asarray(state.tok)]
        done = (out[0][:, 0] == eos_id) if eos_id is not None else None
        for _ in range(max_new_tokens - 1):
            if done is not None and done.all():
                break
            key, sub = jax.random.split(key)
            state, _ = self.step(state, sub)
            out.append(np.asarray(state.tok))
            if done is not None:
                done |= out[-1][:, 0] == eos_id
        return np.concatenate(out, axis=1)

    def _place_tokens(self, tokens):
        import jax

        from .ndarray import NDArray

        if isinstance(tokens, NDArray):
            tokens = tokens.data
        elif not isinstance(tokens, jax.Array):
            tokens = np.asarray(tokens, np.float32)
        return jax.device_put(tokens, self._token_sharding)

    def decode_step_text(self, state, key=None):
        """Lowered (pre-optimization) StableHLO of the decode-step program
        at this state's shapes — feed to ``parallel.hlo_stats.dot_flops``
        for the O(1)-in-prefix FLOP assertion (bench_decode.py)."""
        self._probing = True
        try:
            return self._decode_fn.lower(
                self._env, state,
                key if key is not None else self._zero_key).as_text()
        finally:
            self._probing = False

    def _prefill_args(self, b, p):
        import jax
        import jax.numpy as jnp

        env = {n: jax.ShapeDtypeStruct(v.shape, v.dtype)
               for n, v in self._env.items()}
        tokens = jax.ShapeDtypeStruct((b, p), jnp.float32)
        lens = jax.ShapeDtypeStruct((b,), jnp.int32)
        key = jax.ShapeDtypeStruct(self._zero_key.shape,
                                   self._zero_key.dtype)
        return env, tokens, lens, key

    def prefill_text(self, b, p):
        """Lowered StableHLO of the (b, p) prefill program — the
        recompute-the-prefix cost baseline for the FLOP assertion."""
        import jax

        fn = self._prefill_fns.get((b, p)) or jax.jit(self._prefill_impl)
        self._probing = True
        try:
            return fn.lower(*self._prefill_args(b, p)).as_text()
        finally:
            self._probing = False

    def prefill_artifact(self, b, p, name="prefill"):
        """:class:`~mxnet_tpu.analysis.artifact.ProgramArtifact` of the
        (b, p) prefill program.  Prefill donates nothing (its caches are
        born inside the program); expected traces = one per distinct
        admitted (B, P) shape."""
        import jax

        from .analysis.artifact import artifact_from_jit

        fn = self._prefill_fns.get((b, p)) or jax.jit(self._prefill_impl)
        count = self.trace_counts["prefill"]
        expected = max(len(self._prefill_fns), 1)
        self._probing = True
        try:
            return artifact_from_jit(
                fn, self._prefill_args(b, p), name=name, donated_leaves=0,
                mesh_shape=dict(self._mesh.shape)
                if self._mesh is not None else None,
                trace_count=count, expected_traces=expected,
                cache_len=self._cache_len)
        finally:
            self._probing = False

    def decode_artifact(self, state, key=None, name="decode_step"):
        """:class:`~mxnet_tpu.analysis.artifact.ProgramArtifact` of the
        donated decode-step program at this state's shapes — the "zero
        retraces / zero allocation per token" serving claims as checkable
        metadata (donated leaves = every cache/len/token buffer)."""
        import jax.tree_util as jtu

        from .analysis.artifact import artifact_from_jit, aval_of as _aval

        env = {n: _aval(v) for n, v in self._env.items()}
        astate = jtu.tree_map(_aval, state)
        akey = _aval(key if key is not None else self._zero_key)
        donated = len(jtu.tree_leaves(astate)) if self._donate else 0
        count = self.trace_counts["decode"]
        self._probing = True
        try:
            return artifact_from_jit(
                self._decode_fn, (env, astate, akey), name=name,
                donated_leaves=donated,
                mesh_shape=dict(self._mesh.shape)
                if self._mesh is not None else None,
                trace_count=count, expected_traces=1,
                cache_len=self._cache_len)
        finally:
            self._probing = False


class DecodeServer:
    """Continuous batching over a :class:`DecodePredictor`.

    ``slots`` in-flight sequences decode as ONE fixed-shape batch; between
    steps, finished sequences (EOS or per-request max-len) retire and free
    slots refill from the request queue via a single-sequence prefill
    spliced into the batch state with ``jax.lax.dynamic_update_slice``
    (slot index traced, so admission never retraces).  Single-threaded by
    design: the serving loop IS the schedule (Orca iteration-level
    scheduling), callers queue requests with :meth:`submit` and drain with
    :meth:`run`.
    """

    def __init__(self, predictor, max_prefill, slots=None, eos_id=None,
                 max_new_tokens=None, seed=0):
        from . import config as _config

        self._pred = predictor
        self._max_prefill = int(max_prefill)
        if self._max_prefill > predictor.cache_len:
            raise MXNetError("max_prefill %d exceeds the predictor's "
                             "cache_len %d" % (self._max_prefill,
                                               predictor.cache_len))
        self._slots = int(slots or _config.get("MXNET_DECODE_SLOTS"))
        self._eos_id = eos_id
        self._max_new = int(max_new_tokens) if max_new_tokens is not None \
            else int(_config.get("MXNET_DECODE_MAX_NEW"))
        self._seed = seed
        self._queue = deque()
        self._next_id = 0
        self._insert_fn = None
        self.steps = 0          # decode steps executed (bench accounting)
        self.tokens_out = 0     # tokens delivered to finished requests

    def submit(self, tokens, max_new_tokens=None):
        """Queue a prompt (1-D int sequence); returns the request id."""
        tokens = np.asarray(tokens).reshape(-1)
        if tokens.size > self._max_prefill:
            raise MXNetError("prompt length %d exceeds max_prefill %d"
                             % (tokens.size, self._max_prefill))
        rid = self._next_id
        self._next_id += 1
        cap = int(max_new_tokens) if max_new_tokens is not None \
            else self._max_new
        self._queue.append((rid, tokens, cap))
        return rid

    # ------------------------------------------------------------------
    def _build_insert(self):
        import jax

        from . import config as _config

        donate = (0,) if _config.get("MXNET_DECODE_DONATE") else ()

        def insert(state, one, slot):
            import jax.numpy as jnp

            slot = jnp.asarray(slot, jnp.int32)
            zero = jnp.zeros((), jnp.int32)
            caches = tuple(
                (jax.lax.dynamic_update_slice(kc, nk, (slot, zero, zero)),
                 jax.lax.dynamic_update_slice(vc, nv, (slot, zero, zero)))
                for (kc, vc), (nk, nv) in zip(state.caches, one.caches))
            lens = jax.lax.dynamic_update_slice(state.lens, one.lens,
                                                (slot,))
            tok = jax.lax.dynamic_update_slice(state.tok, one.tok,
                                               (slot, zero))
            return DecodeState(caches, lens, tok)

        return jax.jit(insert, donate_argnums=donate)

    def _empty_batch_state(self, one):
        import jax.numpy as jnp
        import jax.tree_util as jtu

        b = self._slots
        return jtu.tree_map(
            lambda x: jnp.zeros((b,) + tuple(x.shape[1:]), x.dtype), one)

    def run(self):
        """Drain the queue; returns ``{request_id: np.int32 array}`` of
        generated tokens (EOS included when hit)."""
        import jax

        key = jax.random.PRNGKey(self._seed)
        state = None
        active = {}     # slot -> [rid, tokens list, max_new]
        results = {}
        if self._insert_fn is None:
            self._insert_fn = self._build_insert()

        def retire():
            for slot in list(active):
                rid, toks, max_new = active[slot]
                if (self._eos_id is not None and toks
                        and toks[-1] == self._eos_id) \
                        or len(toks) >= max_new:
                    results[rid] = np.asarray(toks, np.int32)
                    self.tokens_out += len(toks)
                    del active[slot]

        while self._queue or active:
            # admit: prefill one request per free slot, splice into batch
            while self._queue and len(active) < self._slots:
                rid, prompt, max_new = self._queue.popleft()
                padded = np.zeros((1, self._max_prefill), np.float32)
                padded[0, :prompt.size] = prompt
                key, sub = jax.random.split(key)
                one, _ = self._pred.prefill(padded, prompt.size, sub)
                if state is None:
                    state = self._empty_batch_state(one)
                slot = next(s for s in range(self._slots)
                            if s not in active)
                first = int(np.asarray(one.tok)[0, 0])
                state = self._insert_fn(state, one, np.int32(slot))
                active[slot] = [rid, [first], max_new]
            retire()
            if not active:
                continue
            key, sub = jax.random.split(key)
            state, _ = self._pred.step(state, sub)
            self.steps += 1
            toks = np.asarray(state.tok)[:, 0]
            for slot, rec in active.items():
                rec[1].append(int(toks[slot]))
            retire()
        return results
