"""KV-cached autoregressive decoding — prefill/decode split + batched serving.

The ``Predictor`` runs a whole forward per call, so generating token T
re-executes the full prefix: O(T^2) work per sequence.  This module is the
TPU-era serving path (Pope et al., "Efficiently Scaling Transformer
Inference"): :class:`DecodePredictor` splits an ``attention_lm``-style
symbol into TWO jitted programs —

* **prefill** — one full causal forward over the prompt that additionally
  captures every ``dot_product_attention`` node's K/V into a preallocated
  ring-buffer cache (``ops.attention.cache_append`` layout), and samples
  the first output token;
* **decode step** — one token per call: embed the last sampled token,
  append its K/V at the next ring slot (``jax.lax.dynamic_update_slice``),
  attend the single query position against the cache with a length-masked
  softmax (``ops.attention.sdpa_decode``), sample the next token
  (``ops.sample.sample_tokens``).  The program carries ``(params, state,
  rng)`` with the state (caches + per-sequence lengths + last token)
  DONATED (``MXNET_DECODE_DONATE``), so the token loop neither re-uploads
  parameters, re-traces, nor allocates: O(1) work per token in the prefix
  length.

Under a mesh, parameters shard by the Megatron column/row plan
(``parallel.tp_rules.plan_tensor_parallel``) and the caches' E (head) dim
shards on 'model' (``parallel.tp_rules.kv_cache_pspec``): each model shard
holds and scores only its own head group's cache slice — the inference-side
counterpart of the training-side ring×TP composition.

:class:`DecodeServer` is the batched serving loop: ``MXNET_DECODE_SLOTS``
in-flight sequence slots at a FIXED batch shape (Orca-style continuous
batching) — new requests prefill into a free slot between decode steps,
sequences retire on EOS/max-len, and the freed slot refills from the
request queue, all without retracing anything.

Decode is bandwidth-bound on the cache, so this module attacks both
factors of ``bytes/token = passes/token x cache bytes``:

* **Speculative decoding** (Leviathan et al. 2023): a proposer drafts k
  tokens — a small draft model through a second ``DecodePredictor``
  (:class:`DraftProposer`) or the model-free n-gram self-speculation
  lookup (:class:`NGramProposer`) — and ONE batched verify pass
  (``ops.attention.sdpa_verify``, fixed shape in k) scores all k+1
  positions against the caches; ``ops.sample.speculative_accept``
  commits the accepted prefix plus one resampled token, preserving the
  target distribution exactly.  Rejection rolls back ``lens`` only (the
  length mask hides the dead cache entries; the next append overwrites
  them), and speculation gates off near the ring-wrap boundary (host-side
  length bookkeeping, no extra device sync) so there is exactly ONE
  draft program and ONE verify program — never a retrace.
* **Quantized KV caches** (``MXNET_KV_DTYPE``: int8 / fp8 with
  per-(token, head) scales, ``ops.attention.QuantKV``): ``cache_append``
  quantizes on the way in, ``sdpa_decode``/``sdpa_verify`` dequantize per
  head on the way out, and the cache bytes every step streams drop 2-4x.
  Scale buffers shard like the caches (``tp_rules.kv_cache_pspec`` — an
  H-split is the same head-group split).

**Paged KV caches** (``MXNET_KV_PAGED`` / ``DecodePredictor(paged=True)``)
replace the dense per-slot ring buffers with fixed-size pages in ONE shared
device pool per attention node (PagedAttention, Kwon et al. SOSP 2023):
per-slot page tables are traced *data* (``ops.attention.paged_gather`` /
``paged_append`` index through them), so HBM scales with live tokens
instead of slots x max-context and admissions / copy-on-write forks /
retirements reuse the same compiled programs — the zero-retrace invariant
extends to the memory manager.  The host half (refcounted allocator with
admission reservations, the token-hash-chain prefix cache that lets
matching prompts share their leading pages and prefill only the tail, the
fork-before-divergent-write rule) lives in ``mxnet_tpu.serve``.  Prompts
admit in fixed-size chunks (``MXNET_PREFILL_CHUNK``) interleaved with
decode steps, so a long prompt never stalls the serving batch.

The symbol contract (checked at trace time, documented in
docs/inference.md): decoder-only graphs built from position-independent ops
plus ``dot_product_attention`` for sequence mixing, with at most a learned
positional table added via a ``broadcast_*`` op against a ``(1, S, E)``
variable — ``models.attention_lm`` and the benchmark LMs qualify.
"""
from __future__ import annotations

import time
from collections import deque
from typing import NamedTuple

import numpy as np

from .base import MXNetError
from . import context as ctx_mod
from . import obs as _obs
from .registry import OpContext

__all__ = ["DecodePredictor", "DecodeServer", "DecodeState",
           "NGramProposer", "DraftProposer"]

# MXNET_KV_DTYPE spellings -> canonical jnp dtype names (resolved lazily so
# the module imports without jax)
_KV_DTYPES = {
    "int8": "int8", "s8": "int8",
    "float8_e4m3fn": "float8_e4m3fn", "f8e4m3": "float8_e4m3fn",
    "f8e4m3fn": "float8_e4m3fn",
    "float8_e5m2": "float8_e5m2", "f8e5m2": "float8_e5m2",
}

def _pad_window(tokens, width):
    """``tokens`` left-aligned in a zero-padded (1, width) float32 window —
    the ONE place admission padding and prefill-chunk windows are derived
    (the dense admission path used to rebuild this per admit)."""
    toks = np.asarray(tokens).reshape(-1)
    out = np.zeros((1, int(width)), np.float32)
    out[0, :toks.size] = toks
    return out


# broadcast ops through which a (1, S, E) position table may meet the
# (B, t, E) activation stream; the decode walk gathers the table rows for
# the CURRENT positions before applying the op
_POSITION_BROADCAST_OPS = {
    "broadcast_add", "broadcast_plus", "broadcast_sub", "broadcast_minus",
    "broadcast_mul",
}


class DecodeState(NamedTuple):
    """The donated per-step serving state (a jax pytree)."""

    caches: tuple       # ((k, v), ...) per attention node: (B, C, E)
                        # arrays, or ops.attention.QuantKV (data + scales)
                        # under a quantized MXNET_KV_DTYPE
    lens: object        # (B,) int32 — tokens appended to each cache so far
    tok: object         # (B, 1) int32 — last sampled token, not yet appended


class DecodePredictor:
    """Incremental-decode executor for a trained attention LM.

    Parameters
    ----------
    symbol : Symbol or str
        The network — a Symbol, a JSON string, or a ``*-symbol.json`` path
        (same forms as :class:`~mxnet_tpu.predictor.Predictor`).
    params : dict, str, or bytes
        Trained parameters (``arg:``/``aux:`` prefixes optional).
    cache_len : int
        Ring-buffer KV-cache length C per attention node.  Generation past
        C tokens wraps: the cache keeps the latest C keys/values
        (sliding-window attention).
    ctx : Context, optional
        Single-device placement; defaults to cpu.  Ignored when ``mesh``
        is given.
    mesh : jax.sharding.Mesh, optional
        Shard parameters by the Megatron plan and KV caches on the
        'model' (head) / 'data' (batch) axes.
    temperature, top_k
        Sampling knobs baked into the step program (0 = greedy).
    data_name : str
        The token-input variable; other free inputs (labels) are fed zeros.
    kv_dtype : str, optional
        KV-cache storage dtype: 'int8', 'float8_e4m3fn' or 'float8_e5m2'
        (per-(token, head) scales, quantize-on-append / dequantize-in-
        kernel).  ``None`` (default) reads ``MXNET_KV_DTYPE``; empty
        string = full-precision caches.
    paged : bool, optional
        Store the caches as fixed-size pages in one shared pool per
        attention node with per-slot page tables (traced data — see the
        module docstring).  ``None`` (default) reads ``MXNET_KV_PAGED``.
    page_tokens, pool_pages, prefill_chunk : int, optional
        Paged-mode knobs; default to ``MXNET_KV_PAGE_TOKENS`` /
        ``MXNET_KV_POOL_PAGES`` / ``MXNET_PREFILL_CHUNK``.
        ``cache_len`` must divide by ``page_tokens`` (the table ring-mods
        over ``cache_len // page_tokens`` entries, so paged results stay
        bit-parity with a dense ring of the same capacity).
    prefix_cache : bool
        Arm copy-on-write prefix sharing in paged mode (default on).
    """

    def __init__(self, symbol, params, cache_len, ctx=None, mesh=None,
                 temperature=0.0, top_k=0, data_name="data", kv_dtype=None,
                 paged=None, page_tokens=None, pool_pages=None,
                 prefill_chunk=None, prefix_cache=True):
        import jax
        import jax.numpy as jnp

        from . import symbol as sym_mod
        from .predictor import _as_param_dicts

        if isinstance(symbol, str):
            symbol = sym_mod.load_json(symbol) \
                if symbol.lstrip().startswith("{") else sym_mod.load(symbol)
        self._symbol = symbol
        self._cache_len = int(cache_len)
        if self._cache_len <= 0:
            raise MXNetError("cache_len must be positive")
        self._ctx = ctx if ctx is not None else ctx_mod.cpu()
        self._mesh = mesh
        self._temperature = float(temperature)
        self._top_k = int(top_k)
        self._data_name = data_name

        from . import config as _config

        if kv_dtype is None:
            kv_dtype = _config.get("MXNET_KV_DTYPE")
        kv_dtype = (kv_dtype or "").strip().lower()
        if kv_dtype:
            canonical = _KV_DTYPES.get(kv_dtype)
            if canonical is None:
                raise MXNetError(
                    "unsupported MXNET_KV_DTYPE %r (supported: %s)"
                    % (kv_dtype, sorted(set(_KV_DTYPES.values()))))
            self._kv_dtype = jnp.dtype(canonical)
        else:
            self._kv_dtype = None

        # an explicit paged= argument outranks the ambient env var (a
        # deliberately dense predictor under MXNET_KV_PAGED=1 — e.g. a
        # draft model — must not read as a dropped-plumbing regression)
        self._paged_from_env = paged is None
        if paged is None:
            paged = _config.get("MXNET_KV_PAGED")
        self._paged = bool(paged)
        self._prefix_cache_on = bool(prefix_cache)
        self._page_tokens = int(page_tokens) if page_tokens \
            else int(_config.get("MXNET_KV_PAGE_TOKENS"))
        self._pool_pages = int(pool_pages) if pool_pages \
            else int(_config.get("MXNET_KV_POOL_PAGES"))
        self._prefill_chunk = int(prefill_chunk) if prefill_chunk \
            else int(_config.get("MXNET_PREFILL_CHUNK"))
        if self._paged:
            if self._page_tokens <= 0:
                raise MXNetError("page_tokens must be positive")
            if self._cache_len % self._page_tokens:
                raise MXNetError(
                    "cache_len %d is not a multiple of page_tokens %d — "
                    "paged capacity must tile into whole pages"
                    % (self._cache_len, self._page_tokens))

        arg_params, aux_params = _as_param_dicts(params)
        free = [n for n in symbol.list_arguments() if n not in arg_params]
        if data_name not in free:
            raise MXNetError("%r is not a free input of the symbol (free "
                             "inputs: %s)" % (data_name, free))
        self._attn_nodes = [n for n in symbol._topo()
                            if not n.is_variable
                            and n.op.name == "dot_product_attention"]
        if not self._attn_nodes:
            raise MXNetError("symbol has no dot_product_attention node; "
                             "nothing to cache — use Predictor")
        # per-attention-node head dims, recorded at trace time by _run
        # (num_heads / num_kv_heads / q_dim / kv_dim) — the grouped-layout
        # source of truth for cache meta and CacheBytesPass
        self._attn_dims = []
        # grouped-query config (any node with num_kv_heads < num_heads):
        # the kv-head count gating the cache/pool trailing-dim shard
        grouped = []
        for n in self._attn_nodes:
            a = n.parsed_attrs()
            kvh = a.get("num_kv_heads", 0) or a.get("num_heads", 1)
            if kvh != a.get("num_heads", 1):
                grouped.append(int(kvh))
        self._grouped_kv_heads = min(grouped) if grouped else None

        self._cache_sharding = None
        self._partition_rules = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from .parallel.tp_rules import (kv_cache_pspec,
                                            plan_tensor_parallel)
            from .programs.partition import build_shardings, \
                rules_from_plan

            sizes = dict(mesh.shape)
            model_par = sizes.get("model", 1)
            rep = NamedSharding(mesh, P())
            # the Megatron graph-walk plan, funneled through the ONE
            # regex partition-rule matcher (programs.partition) — the
            # same degrade-to-replicated guard, now shared with every
            # registered program's pspec plumbing
            plan = plan_tensor_parallel(symbol) if model_par > 1 else {}
            self._partition_rules = rules_from_plan(plan)
            arg_data = {n: a.data for n, a in arg_params.items()}
            coverage = {}
            self._replicated_degrades = []
            shardings = build_shardings(mesh, self._partition_rules,
                                        arg_data, coverage=coverage)
            self._sharding_coverage = {
                "mesh": {str(k): int(v) for k, v in mesh.shape.items()},
                "leaves": coverage}
            self._env = {n: jax.device_put(v, shardings[n])
                         for n, v in arg_data.items()}
            self._env.update({n: jax.device_put(a.data, rep)
                              for n, a in aux_params.items()})
            self._cache_sharding = NamedSharding(
                mesh, kv_cache_pspec(
                    mesh.shape, num_kv_heads=self._grouped_kv_heads,
                    degrades=self._replicated_degrades))
            self._token_sharding = NamedSharding(
                mesh, P("data" if sizes.get("data", 1) > 1 else None, None))
        else:
            dev = self._ctx.jax_device
            self._env = {n: jax.device_put(a.data, dev)
                         for n, a in arg_params.items()}
            self._env.update({n: jax.device_put(a.data, dev)
                              for n, a in aux_params.items()})
            self._token_sharding = dev
            self._sharding_coverage = None
            self._replicated_degrades = []

        from . import config as _config

        donate = (1,) if _config.get("MXNET_DECODE_DONATE") else ()
        self._donate = bool(donate)
        # retrace instrumentation (analysis.RetracePass): the impl bodies
        # run only while jax traces them, so these counters check the
        # serving loop's "zero retraces" claim — decode and verify must
        # each trace ONCE, prefill once per admitted (B, P) shape.
        # Probes (lowering for artifact/FLOP text) set _probing and don't
        # count.
        self.trace_counts = {"prefill": 0, "decode": 0, "verify": 0,
                             "chunk": 0, "fork": 0, "commit": 0,
                             "extract": 0, "install": 0}
        self._probing = False
        if self._paged:
            from .programs.aot import AotDispatch

            # paged programs take (page tables, active mask) as DATA; the
            # chunk program is the whole prefill story (one fixed width).
            # Each is an AotDispatch facade: a plain jax.jit pass-through
            # until prepare_programs() arms an AOT-deserialized (or
            # freshly compiled) executable — the fleet cold-start path
            half = (1,) if self._donate else ()
            self._decode_fn = AotDispatch(
                "paged_decode_step", jax.jit(self._paged_decode_impl,
                                             donate_argnums=donate))
            self._verify_fn = AotDispatch(
                "paged_verify_step", jax.jit(self._paged_verify_impl,
                                             donate_argnums=donate))
            self._chunk_fn = AotDispatch(
                "prefill_chunk", jax.jit(self._chunk_impl,
                                         donate_argnums=half))
            self._fork_fn = AotDispatch(
                "page_fork", jax.jit(
                    self._fork_impl,
                    donate_argnums=(0,) if self._donate else ()))
            self._commit_fn = AotDispatch(
                "slot_commit", jax.jit(
                    self._commit_impl,
                    donate_argnums=(0, 1) if self._donate else ()))
            # page migration/swap: gather a slot's table row out of the
            # pools / scatter saved page contents back in.  Row ids are
            # DATA — one trace each serves every migration, swap-out and
            # readmit (serve.fleet / serve.swap)
            self._extract_fn = AotDispatch(
                "page_extract", jax.jit(self._extract_impl))
            self._install_fn = AotDispatch(
                "page_install", jax.jit(
                    self._install_impl,
                    donate_argnums=(0,) if self._donate else ()))
            self._manager = None          # serve.PagedKVManager, per batch
            self._pools_template = None   # per-node cache avals (probed)
            self._paged_lens = None       # host mirror for standalone use
            self._chunk_widths = set()    # distinct chunk widths driven
            self._aot_report = None       # last prepare_programs() result
            self._program_specs = {}      # kind -> ProgramSpec (owned
            # here; the global registry only holds weakrefs to these)
        else:
            self._decode_fn = jax.jit(self._decode_impl,
                                      donate_argnums=donate)
            self._verify_fn = jax.jit(self._verify_impl,
                                      donate_argnums=donate)
        self._verify_shapes = set()   # distinct (B, k, has_q) driven
        self._prefill_fns = {}   # (B, P) -> jitted prefill program
        # roofline telemetry: program name -> (jitted fn, arg avals),
        # snapped once on the first dispatch so obs.programs can price
        # the program lazily (trace+lower at TABLE time, off hot paths)
        self._static_args = {}
        # jnp dummies reused every call (sample_tokens at temperature 0
        # never reads the key, but the jit signature keeps it)
        self._zero_key = jax.random.PRNGKey(0)

    @property
    def cache_len(self):
        return self._cache_len

    # ------------------------------------------------------------------
    # roofline telemetry (mxnet_tpu.obs) — host-side only: the compiled
    # programs are byte-identical with telemetry on or off
    # ------------------------------------------------------------------
    def _roofline_register(self, name, fn, args):
        """Snap ``args``' avals once and register a lazy static-cost
        prober for program ``name`` (first dispatch only; later calls
        are one dict hit)."""
        if name in self._static_args or not _obs.enabled():
            return
        import weakref

        import jax.tree_util as jtu

        from .analysis.artifact import aval_of

        self._static_args[name] = (fn, jtu.tree_map(aval_of, args))
        # weakly bound: a collected predictor must not stay pinned (env
        # params + snapped programs) by the process-global accounting
        ref = weakref.ref(self)
        _obs.programs.register_static(
            name, lambda n=name, r=ref: (
                r()._roofline_static(n) if r() is not None else None))

    def _roofline_static(self, name):
        """Price one snapped program (trace+lower only; probe-flagged so
        the trace counters stay honest).  A program dispatching an
        AOT-loaded executable carries its source in the row."""
        from .programs.spec import probe_cost

        fn, args = self._static_args[name]
        cost = probe_cost(self, fn, args)
        src = getattr(fn, "source", None)
        if cost is not None and src and src != "jit":
            cost = dict(cost, aot=src)
        return cost

    # ------------------------------------------------------------------
    # the shared graph walk (traced inside both programs)
    # ------------------------------------------------------------------
    def _run(self, env, tokens, caches, pos0, tables=None, active=None,
             valid=None):
        """Execute the symbol on (B, t) tokens.

        ``caches is None`` = prefill mode: full causal attention, fresh
        ring buffers captured from each attention node's K/V.  Otherwise
        decode mode: append K/V at ``pos0`` (per-sequence), length-masked
        attention against the cache.  With ``tables`` given the caches
        are shared page pools: appends scatter through the per-slot page
        tables (``active``/``valid`` masks redirect non-writes to the
        scratch page) and attention runs over the gathered dense-ring
        view — same numerics, paged storage.  Returns ``(probs (B, t, V),
        caches)``.
        """
        import jax
        import jax.numpy as jnp

        from .ops import attention as _attn

        b, t = tokens.shape[0], tokens.shape[1]
        new_caches = []
        ci = 0
        values = {}
        base_key = jax.random.PRNGKey(0)
        for seq, node in enumerate(self._symbol._topo()):
            if node.is_variable:
                if node.name == self._data_name:
                    val = tokens
                elif node.name in env:
                    val = env[node.name]
                else:
                    # unfed free input (loss labels): zeros, forward-unused
                    val = jnp.zeros((b, t), jnp.float32)
                values[(id(node), 0)] = val
                continue
            attrs = node.parsed_attrs()
            n_args = node.op.n_inputs(attrs)
            ins = [values[(id(s), i)] for s, i in node.inputs[:n_args]]
            aux_ins = [values[(id(s), i)] for s, i in node.inputs[n_args:]]
            opname = node.op.name
            if opname == "dot_product_attention":
                q, k, v = ins
                heads = attrs.get("num_heads", 1)
                # grouped-query attention: the K/V stream (and so the
                # cache/pool) is physically kv_heads wide — every append/
                # quantize below works in kv-head units, attends map
                # q-head h to kv group h // G
                kv_heads = attrs.get("num_kv_heads", 0) or heads
                ai = ci
                ci += 1
                dims = dict(num_heads=int(heads),
                            num_kv_heads=int(kv_heads),
                            q_dim=int(q.shape[-1]),
                            kv_dim=int(k.shape[-1]))
                if ai < len(self._attn_dims):
                    self._attn_dims[ai] = dims
                else:
                    self._attn_dims.append(dims)
                scale = attrs.get("scale", 0.0) or None
                if caches is None:
                    outs = [_attn.sdpa(q, k, v, num_heads=heads,
                                       causal=attrs.get("causal", False),
                                       scale=scale,
                                       num_kv_heads=kv_heads)]
                    new_caches.append((self._fill_cache(k, kv_heads),
                                       self._fill_cache(v, kv_heads)))
                else:
                    kc, vc = caches[ai]
                    pos = jnp.asarray(pos0, jnp.int32).reshape(-1)
                    mesh_on = self._mesh is not None
                    if tables is not None:
                        kc = _attn.paged_append(kc, tables, k, pos0,
                                                num_heads=kv_heads,
                                                active=active, valid=valid)
                        vc = _attn.paged_append(vc, tables, v, pos0,
                                                num_heads=kv_heads,
                                                active=active, valid=valid)
                        outs = [_attn.paged_attend(q, kc, vc, tables,
                                                   pos + t, num_heads=heads,
                                                   scale=scale,
                                                   mesh_active=mesh_on,
                                                   num_kv_heads=kv_heads)]
                    else:
                        kc = _attn.cache_append(kc, k, pos0,
                                                num_heads=kv_heads)
                        vc = _attn.cache_append(vc, v, pos0,
                                                num_heads=kv_heads)
                        outs = [_attn.cache_attend(q, kc, vc, pos + t,
                                                   num_heads=heads,
                                                   scale=scale,
                                                   mesh_active=mesh_on,
                                                   num_kv_heads=kv_heads)]
                    # PATH_TAKEN, recorded at trace time: which decode-
                    # attention path this predictor's programs actually
                    # lowered — refines artifact meta so a shape-gated
                    # fallback ("einsum-gated") never false-trips the
                    # mxlint pallas-fallback error
                    self._decode_path = _attn.DECODE_PATH["last"]
                    new_caches.append((kc, vc))
            else:
                if opname in _POSITION_BROADCAST_OPS and len(ins) == 2 \
                        and getattr(ins[0], "ndim", 0) == 3 \
                        and getattr(ins[1], "ndim", 0) == 3 \
                        and ins[0].shape[1] != ins[1].shape[1] \
                        and t in (ins[0].shape[1], ins[1].shape[1]):
                    # learned positional table vs the (B, t, E) stream:
                    # gather the rows for the CURRENT positions
                    big_i = 0 if ins[0].shape[1] != t else 1
                    big = ins[big_i]
                    if big.shape[0] != 1:
                        raise MXNetError(
                            "decode: node %r mixes time-lengths %s without "
                            "a broadcastable (1, S, E) side" %
                            (node.name, (ins[0].shape, ins[1].shape)))
                    s_len = big.shape[1]
                    idx = (jnp.asarray(pos0, jnp.int32).reshape(-1, 1)
                           + jnp.arange(t, dtype=jnp.int32)[None, :])
                    idx = jnp.clip(idx, 0, s_len - 1)
                    ins = list(ins)
                    ins[big_i] = jnp.take(big[0], idx, axis=0)
                octx = OpContext(
                    is_train=False,
                    rng=jax.random.fold_in(base_key, seq),
                    mesh_active=self._mesh is not None, mesh=self._mesh)
                outs, _ = node.op.fcompute(attrs, ins, aux_ins, octx)
            for i, o in enumerate(outs):
                values[(id(node), i)] = o
        head_node, head_idx = self._symbol._outputs[0]
        out = values[(id(head_node), head_idx)]
        if out.ndim == 2 and out.shape[0] == b * t:
            out = out.reshape(b, t, -1)
        elif out.ndim != 3:
            raise MXNetError("decode: head output shape %s is not (B*t, V) "
                             "or (B, t, V)" % (out.shape,))
        return out, tuple(new_caches)

    def _fill_cache(self, x, num_heads=1):
        """(B, t, E) prefill K/V -> a (B, C, E) ring buffer holding the t
        tokens at their ``pos % C`` slots (prefill enforces t <= C).
        Under a quantized ``kv_dtype`` the buffer is an
        ``ops.attention.QuantKV`` — data quantized per (token, head), pad
        slots at a floor scale; the fp32 scale plane shards like the data
        (``kv_cache_pspec`` — its trailing H dim is the same head-group
        split as E)."""
        import jax
        import jax.numpy as jnp

        from .ops import attention as _attn

        b, t, e = x.shape
        buf = jnp.zeros((b, self._cache_len, e), x.dtype)
        buf = jax.lax.dynamic_update_slice(buf, x, (0, 0, 0))
        if self._kv_dtype is not None:
            q = _attn.quantize_kv(buf, self._kv_dtype, num_heads)
            # _probing also covers the paged shape probe: an eval_shape at
            # B=1 must not trip a batch-axis divisibility check
            if self._cache_sharding is not None and not self._probing:
                q = _attn.QuantKV(
                    jax.lax.with_sharding_constraint(q.data,
                                                     self._cache_sharding),
                    jax.lax.with_sharding_constraint(
                        q.scale, self._scale_sharding(num_heads)))
            return q
        if self._cache_sharding is not None and not self._probing:
            buf = jax.lax.with_sharding_constraint(buf, self._cache_sharding)
        return buf

    @property
    def _greedy(self):
        from .ops.sample import is_greedy_policy

        return is_greedy_policy(self._temperature, self._top_k)

    def _scale_sharding(self, num_heads):
        """Sharding for a (B, C, H) scale plane: the cache spec's head
        axis when H divides it, else replicated heads.  The data plane's
        E-split can be finer than a head split (E % axis == 0 with
        heads % axis != 0 — legal, GSPMD handles the einsum), and the
        tiny scale plane must not turn that config into a trace error."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = self._cache_sharding.spec
        head_ax = spec[2]
        if head_ax is not None and \
                num_heads % dict(self._mesh.shape)[head_ax] != 0:
            return NamedSharding(self._mesh, P(spec[0], None, None))
        return self._cache_sharding

    def _sample(self, key, probs):
        import jax.numpy as jnp

        from .ops.sample import sample_tokens

        if self._greedy:
            # argmax(p) == argmax(log p): skip the log on the hot path
            return jnp.argmax(probs, axis=-1).astype(jnp.int32)[:, None]
        logits = jnp.log(probs.astype(jnp.float32) + 1e-30)
        return sample_tokens(key, logits, self._temperature,
                             self._top_k)[:, None]

    def _policy_probs(self, probs):
        """The EXACT sampling distribution :meth:`_sample` draws from, as
        explicit probability vectors — what speculative acceptance must
        compare against.  Softmax of the SAME ``policy_logits`` the
        sampler's categorical draws over (one implementation, so the two
        cannot drift)."""
        import jax
        import jax.numpy as jnp

        from .ops.sample import policy_logits

        logits = jnp.log(probs.astype(jnp.float32) + 1e-30)
        return jax.nn.softmax(
            policy_logits(logits, self._temperature, self._top_k), axis=-1)

    # ------------------------------------------------------------------
    # the two programs
    # ------------------------------------------------------------------
    def _prefill_impl(self, env, tokens, lens, key):
        import jax.numpy as jnp

        if not self._probing:
            self.trace_counts["prefill"] += 1
        probs3, caches = self._run(env, tokens, None, 0)
        # output at the last REAL prompt position, per sequence
        last = jnp.clip(lens - 1, 0, tokens.shape[1] - 1)
        probs = jnp.take_along_axis(
            probs3, last[:, None, None], axis=1)[:, 0]
        tok = self._sample(key, probs)
        return DecodeState(caches, lens, tok), probs

    def _decode_impl(self, env, state, key):
        if not self._probing:
            self.trace_counts["decode"] += 1
        probs3, caches = self._run(env, state.tok, state.caches, state.lens)
        probs = probs3[:, 0]
        tok = self._sample(key, probs)
        return DecodeState(caches, state.lens + 1, tok), probs

    def _verify_impl(self, env, state, draft_toks, draft_probs, key):
        """ONE batched speculative verify pass: score the last committed
        token + k drafts, accept a prefix, resample at the first
        mismatch.  The cache gets all k+1 K/V appended at fixed width;
        rejection rolls back ``lens`` only — slots past it are masked and
        the next append overwrites them in place."""
        import jax.numpy as jnp

        from .ops.sample import speculative_accept

        if not self._probing:
            self.trace_counts["verify"] += 1
        toks_in = jnp.concatenate(
            [state.tok.astype(jnp.int32), draft_toks.astype(jnp.int32)],
            axis=1)                                        # (B, k+1)
        probs3, caches = self._run(env, toks_in, state.caches, state.lens)
        pi = probs3 if self._greedy else self._policy_probs(probs3)
        counts, out = speculative_accept(key, pi, draft_toks, draft_probs,
                                         greedy=self._greedy)
        tok = jnp.take_along_axis(out, (counts - 1)[:, None], axis=1)
        return (DecodeState(caches, state.lens + counts, tok), out, counts)

    # ------------------------------------------------------------------
    # paged mode — the same programs over shared page pools; page tables
    # and active masks ride in as DATA (mxnet_tpu.serve decides, these
    # execute)
    # ------------------------------------------------------------------
    def _paged_decode_impl(self, env, state, tables, active, key):
        """One paged decode step at fixed batch shape.  ``active`` (B,)
        0/1 gates rows that are empty or mid-chunked-prefill: their
        appends redirect to the scratch page and their lens/tok are
        preserved, so one traced program carries every batch occupancy."""
        import jax.numpy as jnp

        if not self._probing:
            self.trace_counts["decode"] += 1
        probs3, caches = self._run(env, state.tok, state.caches, state.lens,
                                   tables=tables, active=active)
        probs = probs3[:, 0]
        tok = self._sample(key, probs)
        act = jnp.asarray(active).reshape(-1, 1).astype(bool)
        tok = jnp.where(act, tok, state.tok)
        lens = state.lens + jnp.asarray(active, jnp.int32).reshape(-1)
        return DecodeState(caches, lens, tok), probs

    def _paged_verify_impl(self, env, state, tables, active, draft_toks,
                           draft_probs, key):
        """Speculative verify over page tables — same acceptance rule as
        the dense :meth:`_verify_impl`, appends scattered through the
        tables, inactive rows commit zero tokens."""
        import jax.numpy as jnp

        from .ops.sample import speculative_accept

        if not self._probing:
            self.trace_counts["verify"] += 1
        toks_in = jnp.concatenate(
            [state.tok.astype(jnp.int32), draft_toks.astype(jnp.int32)],
            axis=1)
        probs3, caches = self._run(env, toks_in, state.caches, state.lens,
                                   tables=tables, active=active)
        pi = probs3 if self._greedy else self._policy_probs(probs3)
        counts, out = speculative_accept(key, pi, draft_toks, draft_probs,
                                         greedy=self._greedy)
        act = jnp.asarray(active).reshape(-1).astype(bool)
        counts = jnp.where(act, counts, 0)
        k = draft_toks.shape[1]
        tok = jnp.take_along_axis(
            out, jnp.clip(counts - 1, 0, k)[:, None], axis=1)
        tok = jnp.where(act[:, None], tok, state.tok)
        return (DecodeState(caches, state.lens + counts, tok), out, counts)

    def _chunk_impl(self, env, caches, table1, toks, pos0, nvalid, key):
        """One fixed-width prefill chunk for a single slot: append the
        chunk's K/V at positions [pos0, pos0 + nvalid) of the slot's page
        table (pad positions past ``nvalid`` are never written), attend
        causally against everything cached so far, and sample at the
        chunk's last real position.  The final chunk's sample IS the
        request's first token; earlier chunks' samples are discarded.
        One trace per chunk width — chunked prefill never retraces."""
        import jax.numpy as jnp

        if not self._probing:
            self.trace_counts["chunk"] += 1
        ones = jnp.ones((toks.shape[0],), jnp.int32)
        probs3, caches = self._run(env, toks, caches, pos0, tables=table1,
                                   active=ones, valid=nvalid)
        last = jnp.clip(jnp.asarray(nvalid, jnp.int32) - 1, 0,
                        toks.shape[1] - 1)
        probs = jnp.take_along_axis(
            probs3, last[:, None, None], axis=1)[:, 0]
        tok = self._sample(key, probs)
        return caches, probs, tok

    def _fork_impl(self, caches, src, dst):
        """Copy-on-write fork: duplicate page ``src`` into ``dst`` across
        every pool (page ids are one global space).  Traced once — the
        ids are data."""
        import jax.tree_util as jtu

        if not self._probing:
            self.trace_counts["fork"] += 1
        return jtu.tree_map(lambda pool: pool.at[dst].set(pool[src]),
                            caches)

    def _commit_impl(self, lens, tok, slot, new_len, new_tok):
        """Activate a freshly prefilled slot: splice its prompt length and
        first token into the batch state (traced slot index)."""
        import jax

        if not self._probing:
            self.trace_counts["commit"] += 1
        import jax.numpy as jnp

        lens = jax.lax.dynamic_update_slice(lens, new_len, (slot,))
        tok = jax.lax.dynamic_update_slice(tok, new_tok,
                                           (slot, jnp.int32(0)))
        return lens, tok

    def _extract_impl(self, caches, row):
        """Gather one slot's (M,) table row out of every pool — per node
        an (M, page_tokens, E) block of page contents, data AND scale
        planes (QuantKV rides the tree).  The page ids are data, so ONE
        trace serves every migration and swap-out; unmapped entries
        gather the scratch page, whose content is never read."""
        import jax.tree_util as jtu

        if not self._probing:
            self.trace_counts["extract"] += 1
        return jtu.tree_map(lambda pool: pool[row], caches)

    def _install_impl(self, caches, row, data):
        """Scatter extracted page contents back into the pools at a
        (freshly allocated) table row — the receiving half of page
        migration and swap-in.  Unmapped row entries are 0: their
        writes land in the scratch page (harmless by design), so one
        fixed-(M,) program carries any live page count.  Donated like
        the step programs — the pools update in place."""
        import jax.tree_util as jtu

        if not self._probing:
            self.trace_counts["install"] += 1
        return jtu.tree_map(lambda pool, d: pool.at[row].set(d),
                            caches, data)

    def extract_pages(self, caches, row):
        """Host-side (numpy) copy of one slot's pages: the shippable
        payload of the page-migration protocol — quantized data plus
        per-(token, head) scales, in table-row order."""
        import jax.numpy as jnp
        import jax.tree_util as jtu

        with _obs.program_span("page_extract"):
            out = self._extract_fn(caches,
                                   jnp.asarray(row, jnp.int32).reshape(-1))
            return jtu.tree_map(lambda x: np.asarray(x), out)

    def install_pages(self, caches, row, data):
        """Write a shipped page payload into this predictor's pools at
        ``row`` (0 = unmapped, redirected to the scratch page).  Returns
        the updated pools; the input pools are donated."""
        import jax.numpy as jnp
        import jax.tree_util as jtu

        with _obs.program_span("page_install"):
            return self._install_fn(
                caches, jnp.asarray(row, jnp.int32).reshape(-1),
                jtu.tree_map(jnp.asarray, data))

    def _probe_cache_shapes(self):
        """Per-attention-node cache avals — (1, C, E) K/V (or QuantKV)
        from an abstract prefill at (1, 1), the shape source for building
        page pools without running a dense prefill."""
        import jax
        import jax.numpy as jnp

        from .programs.spec import probing

        env = {n: jax.ShapeDtypeStruct(v.shape, v.dtype)
               for n, v in self._env.items()}
        toks = jax.ShapeDtypeStruct((1, 1), jnp.float32)
        with probing(self):
            return jax.eval_shape(
                lambda e, t: self._run(e, t, None, 0)[1], env, toks)

    def _place_pool(self, buf, is_scale=False):
        """Mesh placement for a (P, page_tokens, E|H) pool: heads shard
        on 'model' (``tp_rules.kv_pool_pspec``), page dim replicated; a
        scale plane whose H does not divide the model axis replicates
        (same degrade rule as the dense :meth:`_scale_sharding`)."""
        import jax

        from .ops.attention import apply_kv_layout

        if self._mesh is None:
            # single-device pools take the probe-chosen device layout
            # (MXNET_KV_LAYOUT, benchmarks/layout_probe.py --kv); mesh-
            # sharded pools keep GSPMD's layout choice below
            return apply_kv_layout(buf, self._ctx.jax_device)
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .parallel.tp_rules import kv_pool_pspec

        spec = kv_pool_pspec(self._mesh.shape,
                             num_kv_heads=self._grouped_kv_heads,
                             degrades=self._replicated_degrades)
        if spec[2] is not None and \
                buf.shape[2] % dict(self._mesh.shape)[spec[2]] != 0:
            self._replicated_degrades.append({
                "site": "pool-scale" if is_scale else "pool",
                "reason": "trailing dim %d %% %s=%d != 0"
                % (buf.shape[2], spec[2],
                   dict(self._mesh.shape)[spec[2]])})
            spec = P(None, None, None)
        return jax.device_put(buf, NamedSharding(self._mesh, spec))

    def paged_batch_state(self, slots):
        """Fresh paged serving state over ``slots`` slots: a new
        :class:`~mxnet_tpu.serve.PagedKVManager` (allocator + prefix
        cache + page tables) and zeroed pools.  Pool shapes depend only
        on (pool_pages, page_tokens, E), so repeated batches at one
        sizing reuse every compiled program."""
        import jax.numpy as jnp

        from .ops.attention import QuantKV
        from .serve import PagedKVManager

        self._manager = PagedKVManager(
            slots, self._cache_len, self._page_tokens,
            pool_pages=self._pool_pages,
            prefix_cache=self._prefix_cache_on)
        if self._pools_template is None:
            self._pools_template = self._probe_cache_shapes()
        pp = self._manager.pool_pages
        pt = self._page_tokens

        def pool_of(aval, is_scale=False):
            return self._place_pool(
                jnp.zeros((pp, pt, aval.shape[2]), aval.dtype),
                is_scale=is_scale)

        pools = []
        for kc, vc in self._pools_template:
            pair = []
            for aval in (kc, vc):
                if isinstance(aval, QuantKV):
                    pair.append(QuantKV(pool_of(aval.data),
                                        pool_of(aval.scale, is_scale=True)))
                else:
                    pair.append(pool_of(aval))
            pools.append(tuple(pair))
        self._paged_lens = np.zeros(slots, np.int64)
        return DecodeState(tuple(pools), jnp.zeros((slots,), jnp.int32),
                           jnp.zeros((slots, 1), jnp.int32))

    def pool_bytes(self):
        """Static bytes of the shared page pools — the paged serving HBM
        bill (what ``tokens_per_sec_per_gb`` divides by), sized through
        the same width table as :meth:`cache_bytes`."""
        import jax.tree_util as jtu

        from .analysis.hlo_parse import shape_bytes, shape_str

        if self._manager is None:
            raise MXNetError("pool_bytes before any paged prefill/serve")
        if self._pools_template is None:
            self._pools_template = self._probe_cache_shapes()
        pp, pt = self._manager.pool_pages, self._page_tokens
        return sum(shape_bytes(shape_str((pp, pt, aval.shape[2]),
                                         aval.dtype))
                   for aval in jtu.tree_leaves(self._pools_template))

    # ------------------------------------------------------------------
    # AOT-serialized program preparation — the fleet cold-start path
    # (mxnet_tpu.programs.aot, docs/programs.md)
    # ------------------------------------------------------------------
    # donation maps of the paged serving programs, by kind (must mirror
    # the jit donate_argnums above; _donate off zeroes them all)
    _AOT_DONATE = {"decode": (1,), "verify": (1,), "chunk": (1,),
                   "commit": (0, 1), "fork": (0,), "extract": (),
                   "install": (0,)}

    def _aot_dispatches(self):
        """kind -> the :class:`~mxnet_tpu.programs.aot.AotDispatch`
        facade serving it (paged mode only)."""
        return {"chunk": self._chunk_fn, "decode": self._decode_fn,
                "verify": self._verify_fn, "commit": self._commit_fn,
                "fork": self._fork_fn, "extract": self._extract_fn,
                "install": self._install_fn}

    def _symbol_fingerprint(self):
        """Digest of the model graph — the program-identity component
        of the AOT cache key (two predictors with equal avals but
        different symbols must never share an executable).

        Auto-generated OP node names are canonicalized to their topo
        index before hashing: gensym counters depend on how many
        symbols a process built earlier, and two hosts constructing
        the same model after different warmup must still produce the
        SAME key (graph edges are index-based in the json, so op-node
        labels are decorative; variable names stay — they key the
        param env and are already part of the aval treedef)."""
        import hashlib
        import json as _json

        d = getattr(self, "_sym_digest", None)
        if d is None:
            g = _json.loads(self._symbol.tojson())
            for i, node in enumerate(g.get("nodes", ())):
                if node.get("op") not in (None, "null"):
                    node["name"] = "n%d" % i
            blob = _json.dumps(g, sort_keys=True)
            d = hashlib.blake2b(blob.encode(),
                                digest_size=16).hexdigest()
            self._sym_digest = d
        return d

    def serving_avals(self, slots, chunk_w=None, spec_k=0):
        """Abstract args of every paged serving program at batch width
        ``slots`` — the exact signatures the serving loop drives, built
        WITHOUT tracing, compiling or allocating pools (the cache-shape
        probe is ``jax.eval_shape`` only).  This is what lets a fleet
        host fingerprint and AOT-load its programs before it has served
        a single token."""
        import jax
        import jax.numpy as jnp

        from .analysis.artifact import aval_of
        from .ops.attention import QuantKV
        from .serve.manager import PagedKVManager

        if not self._paged:
            raise MXNetError("serving_avals needs a paged predictor")
        if self._pools_template is None:
            self._pools_template = self._probe_cache_shapes()
        slots = int(slots)
        pt = self._page_tokens
        m = self._cache_len // pt
        pp = PagedKVManager.pool_sizing(slots, self._cache_len, pt,
                                        self._pool_pages)
        sds = jax.ShapeDtypeStruct

        def build(shape_of):
            pools = []
            for kc, vc in self._pools_template:
                pair = []
                for aval in (kc, vc):
                    if isinstance(aval, QuantKV):
                        pair.append(QuantKV(
                            sds(shape_of(aval.data), aval.data.dtype),
                            sds(shape_of(aval.scale), aval.scale.dtype)))
                    else:
                        pair.append(sds(shape_of(aval), aval.dtype))
                pools.append(tuple(pair))
            return tuple(pools)

        caches = build(lambda a: (pp, pt, a.shape[2]))
        # one slot's extracted pages: the pool gathered at an (M,) row
        data = build(lambda a: (m, pt, a.shape[2]))
        env = {n: aval_of(v) for n, v in self._env.items()}
        lens = sds((slots,), jnp.int32)
        tok = sds((slots, 1), jnp.int32)
        state = DecodeState(caches, lens, tok)
        tables = sds((slots, m), jnp.int32)
        active = sds((slots,), jnp.int32)
        key = aval_of(self._zero_key)
        i32 = sds((), jnp.int32)
        row = sds((m,), jnp.int32)
        cw = int(chunk_w or self._prefill_chunk or self._cache_len)
        out = {
            "chunk": (env, caches, sds((1, m), jnp.int32),
                      sds((1, cw), jnp.float32), sds((1,), jnp.int32),
                      sds((1,), jnp.int32), key),
            "decode": (env, state, tables, active, key),
            "commit": (lens, tok, i32, sds((1,), jnp.int32),
                       sds((1, 1), jnp.int32)),
            "fork": (caches, i32, i32),
            "extract": (caches, row),
            "install": (caches, row, data),
        }
        if spec_k:
            out["verify"] = (env, state, tables, active,
                             sds((slots, int(spec_k)), jnp.int32), None,
                             key)
        return out

    def prepare_programs(self, slots, chunk_w=None, spec_k=0,
                         mode="aot", save_ok=True):
        """Make every paged serving program READY at batch width
        ``slots`` before the first request: load the AOT-serialized
        executable from the content-addressed program cache (a
        deserialize — milliseconds), or trace + lower + compile now on
        a miss (saved back when ``save_ok``, so the next host's cold
        start is a deserialize).  Loaded executables are armed on the
        dispatch facades: serving then runs them with ZERO traces and
        byte-identical results to the JIT path.

        ``mode="compile"`` bypasses the cache entirely (pure
        trace+lower+compile, nothing saved) — the cold-start bench's
        JIT baseline.  Returns the readiness report: per-program
        {source, key, seconds} plus hit/miss counts and total wall;
        idempotent per (slots, chunk width, spec_k) in ``"aot"`` mode.
        """
        import time as _time

        from .programs import aot as _aot, registry as _registry

        sig = (int(slots), int(chunk_w or 0), int(spec_k or 0))
        rep = self._aot_report
        if mode == "aot" and rep is not None \
                and rep.get("signature") == sig:
            return rep
        avals = self.serving_avals(slots, chunk_w=chunk_w, spec_k=spec_k)
        report = {"signature": sig, "programs": {}, "hits": 0,
                  "misses": 0, "wall_s": 0.0}
        t_all = _time.perf_counter()
        for kind, args in avals.items():
            spec = self._aot_spec(kind, args)
            disp = self._aot_dispatches()[kind]
            self._program_specs[kind] = _registry.register(spec)
            t0 = _time.perf_counter()
            if mode == "compile":
                key = spec.fingerprint(args)
                exe, source = spec.compiled(args), "compile"
            else:
                exe, source, key = _aot.load_or_compile(
                    spec, args, save_ok=save_ok)
            dt = _time.perf_counter() - t0
            if exe is not None:
                disp.arm(exe, source, key)
            report["programs"][kind] = {
                "name": disp.name, "source": source, "key": key,
                "seconds": round(dt, 6)}
            if source == "cache":
                report["hits"] += 1
            elif mode != "compile":
                report["misses"] += 1
        report["wall_s"] = round(_time.perf_counter() - t_all, 6)
        if mode == "aot":
            self._aot_report = report
        return report

    def _aot_spec(self, kind, args):
        """The :class:`~mxnet_tpu.programs.spec.ProgramSpec` of one
        paged serving program at concrete abstract args — donation map,
        partition rules, trace counter and the program-identity
        fingerprint extras all registered in one place."""
        from .programs.spec import ProgramSpec

        disp = self._aot_dispatches()[kind]
        extra = {"symbol": self._symbol_fingerprint(),
                 "cache_len": self._cache_len,
                 "page_tokens": self._page_tokens,
                 "kv_dtype": str(self._kv_dtype),
                 "temperature": self._temperature, "top_k": self._top_k,
                 "donate": self._donate, "kind": kind}
        return ProgramSpec(
            disp.name, disp, owner=self,
            donate_argnums=self._AOT_DONATE[kind] if self._donate else (),
            abstract_args=lambda a=args: a,
            trace_count=lambda c=kind: self.trace_counts.get(c),
            partition_rules=self._partition_rules,
            fingerprint_extra=extra)

    def program_fingerprints(self, slots, chunk_w=None, spec_k=0):
        """kind -> content-address of each paged serving program at this
        sizing — equal keys across hosts/workers PROVE byte-identical
        programs (the serve-what-was-audited invariant)."""
        avals = self.serving_avals(slots, chunk_w=chunk_w, spec_k=spec_k)
        return {kind: self._aot_spec(kind, args).fingerprint(args)
                for kind, args in avals.items()}

    def _run_forks(self, caches, copies):
        """Execute a manager-planned list of (src, dst) page copies —
        copy-on-write forks — before the append step that needs them."""
        import jax.numpy as jnp

        for src, dst in copies:
            caches = self._fork_fn(caches, jnp.int32(src), jnp.int32(dst))
        return caches

    def paged_prepare(self, state, lens_h, width, active=None):
        """Make positions [lens, lens + width) of every active row
        writable (allocate/fork through the manager, run the forks) and
        return ``(state', tables, active)`` ready for the step.  The
        device copies of the tables and the activity mask are cached
        against the manager's mutation version / the mask bytes — a
        steady-state decode tick (no page allocated, no fork, same
        occupancy) re-ships NOTHING to the device."""
        import jax.numpy as jnp

        mgr = self._manager
        act = np.ones(mgr.slots, np.int32) if active is None \
            else np.asarray(active).astype(np.int32).reshape(-1)
        caches = state.caches
        for s in range(mgr.slots):
            if act[s]:
                copies = mgr.ensure(s, int(lens_h[s]),
                                    int(lens_h[s]) + int(width))
                if copies:
                    caches = self._run_forks(caches, copies)
        cached = getattr(self, "_tables_dev", None)
        if cached is None or cached[0] is not mgr \
                or cached[1] != mgr.version:
            self._tables_dev = (mgr, mgr.version,
                                jnp.asarray(mgr.tables))
        act_key = act.tobytes()
        cached = getattr(self, "_act_dev", None)
        if cached is None or cached[0] != act_key:
            self._act_dev = (act_key, jnp.asarray(act))
        return (DecodeState(caches, state.lens, state.tok),
                self._tables_dev[2], self._act_dev[1])

    def paged_step(self, state, lens_h, key=None, active=None):
        """One paged decode step: ensure pages, run forks, step.  The
        caller owns the host length vector (``lens_h``) and advances it
        by the returned activity."""
        state, tables, act = self.paged_prepare(state, lens_h, 1, active)
        args = (self._env, state, tables, act,
                key if key is not None else self._zero_key)
        self._roofline_register("paged_decode_step", self._decode_fn, args)
        with _obs.program_span("paged_decode_step"):
            return self._decode_fn(*args)

    def paged_verify(self, state, lens_h, draft_toks, draft_probs=None,
                     key=None, active=None):
        """One paged speculative macro-step (see :meth:`verify_step`)."""
        import jax.numpy as jnp

        draft_toks = jnp.asarray(draft_toks, jnp.int32)
        k = draft_toks.shape[1]
        state, tables, act = self.paged_prepare(state, lens_h, k + 1,
                                                active)
        self._verify_shapes.add((draft_toks.shape[0], int(k),
                                 draft_probs is not None))
        args = (self._env, state, tables, act, draft_toks, draft_probs,
                key if key is not None else self._zero_key)
        self._roofline_register("paged_verify_step", self._verify_fn, args)
        with _obs.program_span("paged_verify_step"):
            return self._verify_fn(*args)

    def _paged_prefill(self, tokens, prompt_len=None, key=None):
        """Paged prefill = chunked cached-forward, one row at a time:
        match the prefix cache, map shared pages, compute only the tail
        through the chunk program, publish the prompt's pages.  Resets
        the page bookkeeping for a fresh (B,)-slot batch."""
        import jax
        import jax.numpy as jnp

        tokens = np.asarray(tokens)
        b, p = tokens.shape
        if p > self._cache_len:
            raise MXNetError("prompt width %d exceeds cache_len %d"
                             % (p, self._cache_len))
        if prompt_len is None:
            prompt_len = p
        lens_h = np.broadcast_to(
            np.asarray(prompt_len, np.int64).reshape(-1), (b,)).copy()
        state = self.paged_batch_state(b)
        mgr = self._manager
        key = key if key is not None else self._zero_key
        caches = state.caches
        toks_out, probs_out = [], []
        for row in range(b):
            prompt = tokens[row, :int(lens_h[row])].astype(np.int64)
            gate = mgr.gate(prompt, prompt.size, self._cache_len,
                            budget_wrap_forks=False)
            if gate is None:
                raise MXNetError(
                    "KV page pool cannot admit a %d-token prompt — raise "
                    "MXNET_KV_POOL_PAGES (pool: %d pages)"
                    % (prompt.size, mgr.pool_pages))
            matched, pages, reserve_n = gate
            mgr.map_slot(row, pages, reserve_n)
            caches, tok, probs = self._chunked_fill(
                caches, row, prompt, matched, jax.random.fold_in(key, row))
            mgr.publish(row, prompt, prompt.size)
            toks_out.append(tok)
            probs_out.append(probs)
        self._paged_lens = lens_h
        state = DecodeState(caches, jnp.asarray(lens_h, jnp.int32),
                            jnp.concatenate(toks_out, axis=0))
        return state, jnp.concatenate(probs_out, axis=0)

    def _chunked_fill(self, caches, slot, prompt, start, key, width=None):
        """Run [start, len(prompt)) of one row's prompt through the chunk
        program in fixed-width windows; returns (caches, first-token,
        first-token probs) from the final chunk."""
        import jax
        import jax.numpy as jnp

        mgr = self._manager
        total = int(prompt.size)
        w = int(width or self._prefill_chunk or (total - int(start)))
        w = max(1, min(w, self._cache_len))
        self._chunk_widths.add(w)
        pos = int(start)
        tok = probs = None
        greedy = self._greedy
        while pos < total:
            n = min(w, total - pos)
            copies = mgr.ensure(slot, pos, pos + n)
            if copies:
                caches = self._run_forks(caches, copies)
            # greedy sampling never reads the key: skip the per-chunk
            # split dispatch
            sub = key if greedy else None
            if sub is None:
                key, sub = jax.random.split(key)
            with _obs.program_span("prefill"):
                caches, probs, tok = self._chunk_fn(
                    self._env, caches,
                    jnp.asarray(mgr.tables[slot:slot + 1]),
                    jnp.asarray(_pad_window(prompt[pos:pos + n], w)),
                    jnp.asarray([pos], jnp.int32),
                    jnp.asarray([n], jnp.int32), sub)
            pos += n
        return caches, tok, probs

    # ------------------------------------------------------------------
    # public surface
    # ------------------------------------------------------------------
    def prefill(self, tokens, prompt_len=None, key=None):
        """Process a (B, P) prompt batch once; returns ``(state, probs)``.

        ``prompt_len`` (int or (B,)) marks the real length per row of a
        padded batch — cache slots past it stay masked until decode
        overwrites them.  ``probs`` is the model's (B, V) output at each
        row's last real position; ``state.tok`` the sampled first token.
        Jitted per (B, P) shape; repeated calls at one shape reuse the
        compiled program (the serving loop's fixed-shape prefill).  In
        paged mode this is chunked prefill over fresh page tables (one
        slot per row, prefix cache consulted per row).
        """
        import jax
        import jax.numpy as jnp

        if self._paged:
            return self._paged_prefill(tokens, prompt_len, key)
        tokens = self._place_tokens(tokens)
        b, p = tokens.shape
        if p > self._cache_len:
            # a wider window would have to wrap PADDED rows over real
            # tokens for rows shorter than the window — refuse instead of
            # silently attending pad K/V; bind a larger cache_len (decode
            # itself may still wrap past it)
            raise MXNetError("prompt width %d exceeds cache_len %d"
                             % (p, self._cache_len))
        if prompt_len is None:
            prompt_len = p
        lens = jnp.broadcast_to(
            jnp.asarray(prompt_len, jnp.int32).reshape(-1), (b,))
        fn = self._prefill_fns.get((b, p))
        if fn is None:
            fn = jax.jit(self._prefill_impl)
            self._prefill_fns[(b, p)] = fn
        args = (self._env, tokens, lens,
                key if key is not None else self._zero_key)
        self._roofline_register("prefill", fn, args)
        with _obs.program_span("prefill"):
            return fn(*args)

    def step(self, state, key=None):
        """One decode step: append ``state.tok``'s K/V, attend, sample.

        Returns ``(state', probs)`` with ``probs`` the (B, V) distribution
        the new ``state'.tok`` was drawn from.  The input state is donated
        (``MXNET_DECODE_DONATE``) — do not reuse it after the call.
        """
        if self._paged:
            out = self.paged_step(state, self._paged_lens, key)
            self._paged_lens += 1
            return out
        args = (self._env, state,
                key if key is not None else self._zero_key)
        self._roofline_register("decode_step", self._decode_fn, args)
        with _obs.program_span("decode_step"):
            return self._decode_fn(*args)

    def verify_step(self, state, draft_toks, draft_probs=None, key=None):
        """One speculative macro-step: verify k drafted tokens in ONE
        target forward, commit the accepted prefix plus a resampled
        token.

        ``draft_toks`` is (B, k) int32; ``draft_probs`` (B, k, V) are the
        proposal distributions they were drawn from (``None`` for a
        deterministic proposer — n-gram lookup or a greedy draft).
        Returns ``(state', out_toks, counts)``: ``out_toks`` (B, k+1) are
        the emitted tokens, valid through ``counts`` (B,) in [1, k+1];
        ``state'.tok`` is the last emitted token, ``state'.lens`` advanced
        by ``counts`` (rejection rollback — rejected cache entries stay
        masked until overwritten).  The caller must keep the verify
        window inside the ring: ``lens + k + 1 <= cache_len`` for every
        live row (the serving loop's host-side gate).  Fixed shape in k —
        one trace per (B, k, has-draft-probs) signature, donated like
        :meth:`step`.
        """
        import jax.numpy as jnp

        if self._paged:
            st, out, counts = self.paged_verify(
                state, self._paged_lens, draft_toks, draft_probs, key)
            self._paged_lens += np.asarray(counts, np.int64)
            return st, out, counts
        draft_toks = jnp.asarray(draft_toks, jnp.int32)
        self._verify_shapes.add((draft_toks.shape[0], draft_toks.shape[1],
                                 draft_probs is not None))
        args = (self._env, state, draft_toks, draft_probs,
                key if key is not None else self._zero_key)
        self._roofline_register("verify_step", self._verify_fn, args)
        with _obs.program_span("verify_step"):
            return self._verify_fn(*args)

    def generate_speculative(self, tokens, prompt_len=None,
                             max_new_tokens=16, seed=0, eos_id=None,
                             k=None, draft=None, proposer=None):
        """Speculative :meth:`generate`: a (B, N) int32 array of sampled
        tokens, but each loop iteration drafts ``k`` tokens and commits
        1..k+1 of them through one verify pass.  With ``eos_id``, a row
        retires AT its EOS — the speculation window's tail is discarded
        (the serving loop's rule) and the row pads with its last token,
        where plain :meth:`generate` keeps decoding garbage past EOS —
        slice per row in both cases.

        ``draft`` is an optional small draft model (a second
        ``DecodePredictor`` over the same vocabulary — wrapped in a
        :class:`DraftProposer`); without one, ``proposer`` defaults to the
        model-free :class:`NGramProposer`.  Greedy sampling
        (temperature=0) emits EXACTLY the target-only greedy sequence;
        stochastic sampling preserves the target distribution (the
        acceptance-rejection identity) though not the per-seed sample
        path.  Near the ring-wrap boundary the loop falls back to plain
        single-token steps — both programs already traced, so the
        fallback never retraces.
        """
        import jax

        from . import config as _config

        if k is None:
            k = int(_config.get("MXNET_SPEC_K")) or 4
        k = int(k)
        if k <= 0:
            raise MXNetError("speculative k must be positive (got %d)" % k)
        key = jax.random.PRNGKey(seed)
        key, sub = jax.random.split(key)
        tokens = np.asarray(tokens)
        b = tokens.shape[0]
        if prompt_len is None:
            prompt_len = tokens.shape[1]
        lens_h = np.broadcast_to(
            np.asarray(prompt_len, np.int64).reshape(-1), (b,)).copy()
        state, _ = self.prefill(tokens, prompt_len, sub)

        if proposer is None:
            proposer = DraftProposer(draft, k) if draft is not None \
                else NGramProposer(k)
        else:
            # the proposer's draft width IS the verify shape
            k = int(getattr(proposer, "k", k))
        hist = [list(tokens[i, :lens_h[i]].astype(np.int64))
                for i in range(b)]
        first = np.asarray(state.tok)[:, 0]
        rows = [[int(t)] for t in first]
        for i in range(b):
            hist[i].append(int(first[i]))
        if getattr(proposer, "needs_prefill", False):
            key, sub = jax.random.split(key)
            proposer.start(tokens, prompt_len, sub)

        done = np.array([eos_id is not None and rows[i][-1] == eos_id
                         for i in range(b)])
        # the verify window must not wrap the target ring; a draft model
        # appends k entries to its OWN ring too (proposer.cache_len)
        limit = self._cache_len
        if getattr(proposer, "cache_len", None):
            limit = min(limit, proposer.cache_len + 1)
        while True:
            live = [i for i in range(b) if len(rows[i]) < max_new_tokens
                    and not done[i]]
            if not live:
                break
            key, sub = jax.random.split(key)
            if max(lens_h[i] for i in live) + k + 1 <= limit:
                draft_toks, draft_probs = proposer.propose(
                    hist, state, lens_h, sub)
                key, sub = jax.random.split(key)
                state, out, counts = self.verify_step(
                    state, draft_toks, draft_probs, sub)
                out_h = np.asarray(out)
                counts_h = np.asarray(counts)
            else:
                state, _ = self.step(state, sub)
                out_h = np.asarray(state.tok)
                counts_h = np.ones(b, np.int64)
            lens_h += counts_h
            for i in range(b):
                emitted = [int(t) for t in out_h[i, :counts_h[i]]]
                # history tracks everything COMMITTED to the cache —
                # including any window tail past an EOS
                hist[i].extend(emitted)
                if i in live:
                    if eos_id is not None and eos_id in emitted:
                        # discard the speculation-window tail after EOS
                        # (same rule as DecodeServer's deliver)
                        emitted = emitted[:emitted.index(eos_id) + 1]
                        done[i] = True
                    rows[i].extend(emitted)
        n = min(max_new_tokens, max(len(r) for r in rows))
        out = np.zeros((b, n), np.int32)
        for i in range(b):
            row = (rows[i] + [rows[i][-1]] * n)[:n]
            out[i] = row
        return out

    def generate(self, tokens, prompt_len=None, max_new_tokens=16,
                 seed=0, eos_id=None):
        """Prefill + ``max_new_tokens`` decode steps; returns a (B, N)
        int32 numpy array of sampled tokens (rows keep decoding past
        their EOS — slice per row; the serving loop retires properly)."""
        import jax

        key = jax.random.PRNGKey(seed)
        key, sub = jax.random.split(key)
        state, _ = self.prefill(tokens, prompt_len, sub)
        out = [np.asarray(state.tok)]
        done = (out[0][:, 0] == eos_id) if eos_id is not None else None
        for _ in range(max_new_tokens - 1):
            if done is not None and done.all():
                break
            key, sub = jax.random.split(key)
            state, _ = self.step(state, sub)
            out.append(np.asarray(state.tok))
            if done is not None:
                done |= out[-1][:, 0] == eos_id
        return np.concatenate(out, axis=1)

    def _place_tokens(self, tokens):
        import jax

        from .ndarray import NDArray

        if isinstance(tokens, NDArray):
            tokens = tokens.data
        elif not isinstance(tokens, jax.Array):
            tokens = np.asarray(tokens, np.float32)
        return jax.device_put(tokens, self._token_sharding)

    def _paged_probe_args(self, state):
        """Concrete (tables, active) matching this state's batch — the
        extra decode/verify operands in paged mode."""
        import jax.numpy as jnp

        b = state.lens.shape[0]
        m = self._cache_len // self._page_tokens
        if self._manager is not None and self._manager.slots == b:
            tables = jnp.asarray(self._manager.tables)
        else:
            tables = jnp.zeros((b, m), jnp.int32)
        return tables, jnp.ones((b,), jnp.int32)

    def decode_step_text(self, state, key=None):
        """Lowered (pre-optimization) StableHLO of the decode-step program
        at this state's shapes — feed to ``parallel.hlo_stats.dot_flops``
        for the O(1)-in-prefix FLOP assertion (bench_decode.py)."""
        from .programs.spec import probe_lowered_text

        key = key if key is not None else self._zero_key
        if self._paged:
            tables, active = self._paged_probe_args(state)
            return probe_lowered_text(
                self, self._decode_fn,
                (self._env, state, tables, active, key))
        return probe_lowered_text(self, self._decode_fn,
                                  (self._env, state, key))

    def _prefill_args(self, b, p):
        import jax
        import jax.numpy as jnp

        env = {n: jax.ShapeDtypeStruct(v.shape, v.dtype)
               for n, v in self._env.items()}
        tokens = jax.ShapeDtypeStruct((b, p), jnp.float32)
        lens = jax.ShapeDtypeStruct((b,), jnp.int32)
        key = jax.ShapeDtypeStruct(self._zero_key.shape,
                                   self._zero_key.dtype)
        return env, tokens, lens, key

    def prefill_text(self, b, p):
        """Lowered StableHLO of the (b, p) prefill program — the
        recompute-the-prefix cost baseline for the FLOP assertion."""
        import jax

        if self._paged:
            raise MXNetError("paged mode prefills through the chunk "
                             "program; there is no one-shot prefill "
                             "program to probe")
        from .programs.spec import probe_lowered_text

        fn = self._prefill_fns.get((b, p)) or jax.jit(self._prefill_impl)
        return probe_lowered_text(self, fn, self._prefill_args(b, p))

    def prefill_artifact(self, b, p, name="prefill"):
        """:class:`~mxnet_tpu.analysis.artifact.ProgramArtifact` of the
        (b, p) prefill program.  Prefill donates nothing (its caches are
        born inside the program); expected traces = one per distinct
        admitted (B, P) shape."""
        import jax

        from .programs.spec import probe_artifact

        if self._paged:
            raise MXNetError("paged mode prefills through the chunk "
                             "program; there is no one-shot prefill "
                             "program to snapshot")
        fn = self._prefill_fns.get((b, p)) or jax.jit(self._prefill_impl)
        return probe_artifact(
            self, fn, self._prefill_args(b, p), name, donated_leaves=0,
            mesh_shape=dict(self._mesh.shape)
            if self._mesh is not None else None,
            trace_count=self.trace_counts["prefill"],
            expected_traces=max(len(self._prefill_fns), 1),
            cache_len=self._cache_len)

    def cache_bytes(self, state):
        """Static byte size of the ring caches behind ``state`` — data
        AND scale planes — sized through the analysis width table
        (``analysis.hlo_parse.shape_bytes``, f8/sub-byte aware), so the
        number mxlint budgets and the bench's tokens/s/GB headline share
        one accounting."""
        import jax.tree_util as jtu

        from .analysis.hlo_parse import shape_bytes, shape_str

        return sum(shape_bytes(shape_str(leaf.shape, leaf.dtype))
                   for leaf in jtu.tree_leaves(state.caches))

    def _cache_meta(self, state, fn=None):
        """Cache metadata for artifacts: the static byte budget plus the
        DATA dtypes actually stored (the cache-bytes pass flags an f32
        data plane inside a quantized config from these) and the cache
        layout (the pass flags a dense-ring allocation under a paged
        config — the memory-manager plumbing was dropped).  ``fn`` is
        the dispatch whose AOT provenance the artifact describes
        (default: the decode step's)."""
        from . import config as _config
        from .ops.attention import QuantKV

        dtypes = set()
        for kc, vc in state.caches:
            for c in (kc, vc):
                dtypes.add(str((c.data if isinstance(c, QuantKV)
                                else c).dtype))
        from .ops.attention import decode_kernel_mode

        meta = {"cache_bytes": self.cache_bytes(state),
                "kv_dtype": str(self._kv_dtype)
                if self._kv_dtype is not None else None,
                "cache_data_dtypes": sorted(dtypes),
                "cache_layout": "paged" if self._paged else "dense",
                "kv_paged": bool(self._paged or (
                    self._paged_from_env
                    and _config.get("MXNET_KV_PAGED"))),
                # the artifact-level PATH_TAKEN tripwire: when the fused
                # flash-decoding kernel is configured to engage (and no
                # mesh shards the cache away from it), the flop-dtype
                # pass demands a pallas_call in the program — a silent
                # einsum fallback becomes a lint error, not a perf loss.
                # _refine_pallas_meta withdraws the promise post-trace
                # when the shape gate VISIBLY refused the kernel
                # ("einsum-gated" — e.g. head dims off the Mosaic tile
                # on TPU), so only silent fallbacks trip the error
                "pallas_decode": bool(decode_kernel_mode()[0]
                                      and self._mesh is None)}
        if self._grouped_kv_heads is not None:
            # grouped-K/V promise + the widths actually allocated: the
            # cache-bytes pass errors when a cache/pool plane comes out
            # H_q heads wide under this promise (a dropped num_kv_heads
            # silently forfeits the G× pool shrink)
            meta["num_kv_heads"] = int(self._grouped_kv_heads)
            meta["attn_dims"] = [dict(d) for d in self._attn_dims]
            widths = set()
            for kc, vc in state.caches:
                for c in (kc, vc):
                    widths.add(int((c.data if isinstance(c, QuantKV)
                                    else c).shape[2]))
            meta["cache_kv_dims"] = sorted(widths)
        if self._paged:
            meta["page_tokens"] = self._page_tokens
            if self._manager is not None:
                meta["pool_pages"] = self._manager.pool_pages
            # AOT provenance: a dispatch armed with a cached
            # (deserialized) or freshly compiled executable serves with
            # zero traces BY CONSTRUCTION — the retrace pass reads this
            # instead of flagging the 0-count as uninstrumented.  Per
            # program: the verify artifact must not inherit the decode
            # step's source when only decode was prepared
            src = getattr(fn if fn is not None else self._decode_fn,
                          "source", "jit")
            if src != "jit":
                meta["aot"] = src
        # sharding-coverage lint surfaces: the per-leaf partition-rule
        # match records from placement time, plus every K/V degrade the
        # pspec helpers took (deduped — _place_pool runs per buffer)
        if getattr(self, "_sharding_coverage", None) is not None:
            meta["sharding_coverage"] = self._sharding_coverage
        degrades, seen = [], set()
        for rec in getattr(self, "_replicated_degrades", ()):
            key = (rec.get("site"), rec.get("reason"))
            if key not in seen:
                seen.add(key)
                degrades.append(rec)
        if degrades:
            meta["replicated_degrades"] = degrades
        return meta

    def _refine_pallas_meta(self, art):
        """Withdraw the artifact's fused-kernel promise when the dispatch
        visibly shape-gated it.  ``artifact_from_jit``'s trace (or the
        serving trace it reuses) just ran ``paged_attend``/
        ``cache_attend``, which recorded the taken path in
        ``self._decode_path``; a gated fallback is legitimate — the
        flop-dtype tripwire targets SILENT einsum regressions only."""
        if art.meta.get("pallas_decode") and \
                getattr(self, "_decode_path", None) == "einsum-gated":
            art.meta["pallas_decode"] = False
        return art

    def decode_artifact(self, state, key=None, name="decode_step"):
        """:class:`~mxnet_tpu.analysis.artifact.ProgramArtifact` of the
        donated decode-step program at this state's shapes — the "zero
        retraces / zero allocation per token" serving claims as checkable
        metadata (donated leaves = every cache/len/token buffer; cache
        byte + dtype meta for the cache-bytes pass)."""
        import jax.tree_util as jtu

        from .analysis.artifact import aval_of as _aval
        from .programs.spec import probe_artifact

        env = {n: _aval(v) for n, v in self._env.items()}
        astate = jtu.tree_map(_aval, state)
        akey = _aval(key if key is not None else self._zero_key)
        donated = len(jtu.tree_leaves(astate)) if self._donate else 0
        if self._paged:
            tables, active = self._paged_probe_args(state)
            args = (env, astate, _aval(tables), _aval(active), akey)
        else:
            args = (env, astate, akey)
        return probe_artifact(
            self, self._decode_fn, args, name,
            refine=self._refine_pallas_meta, donated_leaves=donated,
            mesh_shape=dict(self._mesh.shape)
            if self._mesh is not None else None,
            trace_count=self.trace_counts["decode"], expected_traces=1,
            cache_len=self._cache_len, **self._cache_meta(state))

    def verify_artifact(self, state, k, draft_probs=None, key=None,
                        name="verify_step"):
        """:class:`~mxnet_tpu.analysis.artifact.ProgramArtifact` of the
        donated speculative-verify program at this state's shapes and
        draft width ``k`` — same donation/retrace/cache-byte contract as
        the decode step (expected traces = one per driven (B, k, has-q)
        signature).  ``draft_probs`` (array or aval) selects the
        with-proposal-distribution variant; ``None`` the deterministic-
        proposer one."""
        import jax.numpy as jnp
        import jax.tree_util as jtu

        from .analysis.artifact import aval_of as _aval
        from .programs.spec import probe_artifact

        import jax

        env = {n: _aval(v) for n, v in self._env.items()}
        astate = jtu.tree_map(_aval, state)
        b = state.lens.shape[0]
        atoks = jax.ShapeDtypeStruct((b, int(k)), jnp.int32)
        aq = _aval(draft_probs) if draft_probs is not None else None
        akey = _aval(key if key is not None else self._zero_key)
        donated = len(jtu.tree_leaves(astate)) if self._donate else 0
        if self._paged:
            tables, active = self._paged_probe_args(state)
            args = (env, astate, _aval(tables), _aval(active), atoks,
                    aq, akey)
        else:
            args = (env, astate, atoks, aq, akey)
        return probe_artifact(
            self, self._verify_fn, args, name,
            refine=self._refine_pallas_meta, donated_leaves=donated,
            mesh_shape=dict(self._mesh.shape)
            if self._mesh is not None else None,
            trace_count=self.trace_counts["verify"],
            expected_traces=max(len(self._verify_shapes), 1),
            cache_len=self._cache_len, spec_k=int(k),
            **self._cache_meta(state, fn=self._verify_fn))


def _build_insert_fn():
    """Jitted splice of a batch-1 :class:`DecodeState` into slot ``slot``
    of a batch state (traced slot index — admission never retraces).
    Generic over the cache pytree, so quantized caches (data + scale
    leaves) and draft-model states ride the same machinery."""
    import jax

    from . import config as _config

    donate = (0,) if _config.get("MXNET_DECODE_DONATE") else ()

    def insert(state, one, slot):
        import jax.numpy as jnp
        import jax.tree_util as jtu

        slot = jnp.asarray(slot, jnp.int32)

        def put(full, single):
            idx = (slot,) + (jnp.int32(0),) * (full.ndim - 1)
            return jax.lax.dynamic_update_slice(full, single, idx)

        return jtu.tree_map(put, state, one)

    return jax.jit(insert, donate_argnums=donate)


def _empty_batch_state(one, slots):
    """An all-zero batch state with ``slots`` rows shaped like the
    batch-1 state ``one``."""
    import jax.numpy as jnp
    import jax.tree_util as jtu

    return jtu.tree_map(
        lambda x: jnp.zeros((slots,) + tuple(x.shape[1:]), x.dtype), one)


class NGramProposer:
    """Model-free draft proposer: n-gram lookup over each sequence's own
    history (prompt-lookup / self-speculation).

    Matches the last ``ngram`` committed tokens (``MXNET_SPEC_NGRAM``)
    against earlier history and proposes the k tokens that followed the
    most recent earlier occurrence, backing off to shorter suffixes and
    finally to repeating the last token — always exactly k proposals, so
    the verify shape stays fixed.  Deterministic, so its proposal
    distribution is a delta and :func:`ops.sample.speculative_accept`
    needs no q vectors (``draft_probs=None``).  Pure host-side numpy: the
    proposer costs no device program at all, which is what makes
    self-speculation profitable even at high rejection rates.
    """

    cache_len = None      # no draft ring to keep inside
    needs_prefill = False

    def __init__(self, k, ngram=None):
        from . import config as _config

        self.k = int(k)
        if self.k <= 0:
            raise MXNetError("NGramProposer k must be positive")
        self.ngram = int(ngram) if ngram is not None \
            else int(_config.get("MXNET_SPEC_NGRAM"))
        self.ngram = max(1, self.ngram)

    def propose(self, histories, state=None, lens=None, key=None):
        out = np.zeros((len(histories), self.k), np.int32)
        for r, h in enumerate(histories):
            out[r] = self._row(np.asarray(h, np.int64).reshape(-1))
        return out, None

    def _row(self, h):
        k = self.k
        if h.size == 0:
            return np.zeros(k, np.int32)
        for n in range(min(self.ngram, h.size - 1), 0, -1):
            # vectorized suffix match over every window start with a
            # continuation (body drops the last element, so i + n < |h|
            # holds for free and the suffix's own occurrence is excluded)
            body = h[:-1]
            if body.size < n:
                continue
            win = np.lib.stride_tricks.sliding_window_view(body, n)
            hits = np.flatnonzero((win == h[-n:]).all(axis=1))
            if hits.size:
                i = int(hits[-1])            # most recent earlier match
                cont = h[i + n:i + n + k]
                pad = np.full(k - cont.size, cont[-1], np.int64)
                return np.concatenate([cont, pad]).astype(np.int32)
        return np.full(k, h[-1], np.int32)


class DraftProposer:
    """Draft-model proposer: k autoregressive steps of a SMALL
    :class:`DecodePredictor` over the same vocabulary.

    The draft keeps its own ring caches in lockstep with the target's
    committed prefix: each macro-step it resumes from the target's
    (lens, tok) — rejection rollback is free, rejected draft cache
    entries sit past ``lens`` where the length mask hides them until the
    next append overwrites them.  Committed tokens the draft never
    stepped through (the k-th draft of a fully-accepted window; tokens
    decoded by plain near-wrap fallback steps) are healed by a
    teacher-forced CATCH-UP at the top of :meth:`propose`: per-row
    ``filled`` counters (host-side, fed by the caller's committed-token
    histories — no extra device sync) replay the missing inputs through
    the same decode-step program, so the draft cache never holds a
    permanent hole and acceptance does not decay over long serves.  A
    greedy draft proposes deterministically (``draft_probs=None``, delta
    proposals); a stochastic draft returns its exact per-step sampling
    distributions so the acceptance ratio p/q and the residual are
    well-defined.  One decode-step program on the draft, traced once —
    the "draft" program mxlint audits.
    """

    needs_prefill = True

    def __init__(self, predictor, k):
        self._pred = predictor
        if getattr(predictor, "_paged", False):
            raise MXNetError(
                "DraftProposer needs a dense-cache DecodePredictor: the "
                "draft's per-admission prefill would reset a paged "
                "predictor's page bookkeeping (drafts are small — dense "
                "ring buffers cost them little)")
        self.k = int(k)
        if self.k <= 0:
            raise MXNetError("DraftProposer k must be positive")
        self.cache_len = predictor.cache_len
        self._state = None
        self._insert = None
        self._filled = None     # (B,) host int64: cache valid through

    @property
    def predictor(self):
        return self._pred

    def start(self, tokens, prompt_len, key=None):
        """Prefill the draft on the same (B, P) prompt batch (the
        fixed-batch :meth:`DecodePredictor.generate_speculative` path)."""
        self._state, _ = self._pred.prefill(tokens, prompt_len, key)
        b = self._state.lens.shape[0]
        self._filled = np.broadcast_to(
            np.asarray(prompt_len, np.int64).reshape(-1), (b,)).copy()

    def admit(self, tokens, prompt_len, slot, slots, key=None):
        """Prefill ONE request and splice it into draft slot ``slot`` —
        the serving-loop path (mirrors the server's own admission)."""
        one, _ = self._pred.prefill(tokens, prompt_len, key)
        if self._state is None:
            self._state = _empty_batch_state(one, slots)
            self._filled = np.zeros(slots, np.int64)
        if self._insert is None:
            self._insert = _build_insert_fn()
        self._state = self._insert(self._state, one, np.int32(slot))
        self._filled[slot] = int(prompt_len)

    def _hist_tok(self, histories, pos):
        """(B, 1) int32 of each row's committed token at ``pos`` (host;
        clamped — rows past their history just replay their last
        token, which only touches already-dead cache slots)."""
        out = np.zeros((len(histories), 1), np.int32)
        for r, h in enumerate(histories):
            out[r, 0] = int(h[min(int(pos[r]), len(h) - 1)])
        return out

    def propose(self, histories, state, lens, key=None):
        """Teacher-forced catch-up to the target's committed prefix,
        then k draft steps; returns ``(draft_toks (B, k), draft_probs
        (B, k, V) | None)``.  ``lens`` is the caller's HOST-side
        committed-length vector (the serving loops already track it)."""
        import jax
        import jax.numpy as jnp

        if self._state is None:
            raise MXNetError("DraftProposer.propose before start()/admit()")
        if key is None:
            key = jax.random.PRNGKey(0)
        lens_h = np.broadcast_to(
            np.asarray(lens, np.int64).reshape(-1),
            (self._state.lens.shape[0],)).copy()

        # --- catch-up: replay committed tokens the draft never saw
        # (position `filled` onward) through the same step program.
        # Rows already caught up harmlessly re-append their pending
        # token at `lens` — the very slot the proposal steps below
        # overwrite first.  Usual gap is 0 or 1 (the k-th draft of a
        # fully-accepted window); fallback eras pay theirs here too.
        cur = np.minimum(self._filled, lens_h)
        st = self._state
        for _ in range(int((lens_h - cur).max()) if cur.size else 0):
            st = DecodeState(st.caches, jnp.asarray(cur, jnp.int32),
                             jnp.asarray(self._hist_tok(histories, cur)))
            key, sub = jax.random.split(key)
            st, _ = self._pred.step(st, sub)
            cur = np.minimum(cur + 1, lens_h)

        # --- k proposal steps from the target's committed (lens, tok).
        # Fresh copies: the draft step DONATES its state, and lens/tok
        # here are the target's live buffers.
        st = DecodeState(st.caches, state.lens + 0, state.tok + 0)
        toks, qs = [], []
        for _ in range(self.k):
            key, sub = jax.random.split(key)
            st, probs = self._pred.step(st, sub)
            # st.tok is donated into the NEXT draft step — keep a copy
            toks.append(st.tok + 0)
            if not self._pred._greedy:
                qs.append(self._pred._policy_probs(probs))
        self._state = st
        # appended inputs were [tok, d_1..d_{k-1}]: valid through the
        # accepted prefix, which the caller's next `lens` reveals
        self._filled = lens_h + self.k
        return (jnp.concatenate(toks, axis=1),
                jnp.stack(qs, axis=1) if qs else None)


class DecodeServer:
    """Continuous batching over a :class:`DecodePredictor`.

    ``slots`` in-flight sequences decode as ONE fixed-shape batch; between
    steps, finished sequences (EOS or per-request max-len) retire and free
    slots refill from the request queue via a single-sequence prefill
    spliced into the batch state with ``jax.lax.dynamic_update_slice``
    (slot index traced, so admission never retraces).  Single-threaded by
    design: the serving loop IS the schedule (Orca iteration-level
    scheduling), callers queue requests with :meth:`submit` and drain with
    :meth:`run`.

    The paged loop is additionally a fleet citizen
    (``mxnet_tpu.serve.fleet``, docs/serving_fleet.md): it runs as a
    persistent SESSION one :meth:`serve_tick` at a time so a router can
    interleave hosts, accepts page-restorable records through
    :meth:`inject` (migrated prefills, swapped-out requests), publishes
    its routing view via :meth:`serve_summary` (``/metrics.json``), and
    preempts under pressure — a higher-priority waiter, or any waiter
    after ``MXNET_FLEET_DECODE_BOUND`` pool-blocked iterations, swaps
    the lowest-priority slot's pages to host RAM
    (``MXNET_FLEET_SWAP``); the victim readmits bit-exactly here or on
    any other host.
    """

    def __init__(self, predictor, max_prefill, slots=None, eos_id=None,
                 max_new_tokens=None, seed=0, spec_k=None, proposer=None,
                 draft=None, metrics_port=None, host=None):
        from . import config as _config

        self._pred = predictor
        # fleet identity: the per-host label on the mx_fleet_* metric
        # families (serve.fleet sets it; standalone servers are "local")
        self._host = str(host) if host is not None else "local"
        self._max_prefill = int(max_prefill)
        if self._max_prefill > predictor.cache_len:
            raise MXNetError("max_prefill %d exceeds the predictor's "
                             "cache_len %d" % (self._max_prefill,
                                               predictor.cache_len))
        self._slots = int(slots or _config.get("MXNET_DECODE_SLOTS"))
        self._eos_id = eos_id
        self._max_new = int(max_new_tokens) if max_new_tokens is not None \
            else int(_config.get("MXNET_DECODE_MAX_NEW"))
        self._seed = seed
        self._queue = deque()
        self._next_id = 0
        self._insert_fn = None
        self._req = {}          # rid -> submit/admit/first/retire times
        self._done_rids = deque()   # retired rids, oldest first (pruning)
        # chunked-prefill width (paged mode): the predictor's configured
        # chunk, clamped to the admission window — ONE width, one trace
        self._chunk_w = min(
            int(getattr(predictor, "_prefill_chunk", 0) or max_prefill),
            int(max_prefill))
        # --- speculative decoding (MXNET_SPEC_K / explicit args) ---
        if spec_k is None:
            spec_k = int(_config.get("MXNET_SPEC_K"))
        if proposer is not None:
            spec_k = int(getattr(proposer, "k", spec_k))
        elif draft is not None:
            spec_k = int(spec_k) or 4
            proposer = DraftProposer(draft, spec_k)
        elif spec_k:
            proposer = NGramProposer(spec_k)
        self._spec_k = int(spec_k or 0)
        self._proposer = proposer
        if proposer is not None and getattr(proposer, "cache_len", None):
            if self._max_prefill > proposer.cache_len:
                raise MXNetError(
                    "max_prefill %d exceeds the draft's cache_len %d"
                    % (self._max_prefill, proposer.cache_len))
        self.steps = 0          # device steps executed (bench accounting)
        self.spec_steps = 0     # of which speculative verify steps
        self.tokens_out = 0     # tokens delivered to finished requests
        self.proposed = 0       # drafted tokens offered to verify
        self.accepted = 0       # drafted tokens accepted
        # registry mirrors of the loop counters (scrapeable over
        # /metrics; the python ints above stay the bench's source)
        self._m_steps = _obs.registry.counter(
            "mx_serve_steps", "device steps executed by the serving loop")
        self._m_spec = _obs.registry.counter(
            "mx_serve_spec_steps", "speculative verify steps")
        self._m_tokens = _obs.registry.counter(
            "mx_serve_tokens", "tokens delivered to finished requests")
        self._m_proposed = _obs.registry.counter(
            "mx_spec_proposed", "drafted tokens offered to verify")
        self._m_accepted = _obs.registry.counter(
            "mx_spec_accepted", "drafted tokens accepted by the target")
        # --- fleet/preemption state (paged loop) ---
        # fair admission: after this many consecutive pool-gate-blocked
        # iterations the lowest-priority slot is preempted (swap-out) so
        # a long decode can no longer wedge the admission gate
        self._fair_bound = int(_config.get("MXNET_FLEET_DECODE_BOUND"))
        self._swap_armed = bool(_config.get("MXNET_FLEET_SWAP"))
        self._preempt_cb = None     # serve.fleet routes records back out
        self._verify_restore = False   # tests: assert restore bit-parity
        self._ps = None             # persistent paged session (tick API)
        self.aot_report = None      # serve_open's AOT readiness report
        self.swap_outs = 0
        self.swap_ins = 0
        self._bind_host_metrics(self._host)
        # Prometheus-text exporter (heritage: kvstore_server.py's server
        # process contract): MXNET_METRICS_PORT / metrics_port= arms the
        # process-wide HTTP sidecar serving the registry + timeline —
        # shared per port, so sequential/concurrent servers coexist
        if metrics_port is None:
            metrics_port = int(_config.get("MXNET_METRICS_PORT"))
        self.metrics_server = _obs.serve_metrics(metrics_port) \
            if metrics_port else None
        # /metrics.json grows the fleet-routing summary: the chain
        # digest + load gauges a remote router scores this host by —
        # one mx_serve_summary:<host> section PER SERVER, so several
        # servers sharing the process-wide port cannot clobber each
        # other's routing view
        self._summary_key = None
        self._register_summary()

    def _register_summary(self):
        """(Re)register this server's ``/metrics.json`` section under
        its current host label (renames drop the old key)."""
        if getattr(self, "metrics_server", None) is None:
            return
        key = "mx_serve_summary:%s" % self._host
        if self._summary_key and self._summary_key != key:
            self.metrics_server.remove_json(self._summary_key)
        self._summary_key = key
        self.metrics_server.add_json(key, self.serve_summary)

    def _bind_host_metrics(self, host):
        """(Re)bind the per-host mx_fleet_* children — the fleet layer
        names its hosts after construction, and the labeled series must
        follow the name or every host's counts land on one label."""
        self._host = str(host)
        self._register_summary()
        lab = {"host": self._host}
        self._m_swapped_pages = _obs.registry.counter(
            "mx_fleet_swapped_pages",
            "pages moved to host RAM by preemption swap-outs",
            labels=("host",)).labels(**lab)
        self._m_migrated_pages = _obs.registry.counter(
            "mx_fleet_migrated_pages",
            "pages installed from migrated/restored records",
            labels=("host",)).labels(**lab)
        self._m_queue_depth = _obs.registry.gauge(
            "mx_fleet_queue_depth", "requests waiting in the host queue",
            labels=("host",)).labels(**lab)
        self._m_free_pages = _obs.registry.gauge(
            "mx_fleet_free_pages", "free pages in the host's KV pool",
            labels=("host",)).labels(**lab)
        self._m_ttft = _obs.registry.histogram(
            "mx_fleet_ttft", "seconds from submit to first token",
            labels=("host",)).labels(**lab)

    @property
    def accept_rate(self):
        """Fraction of drafted tokens the target accepted (the k-tuning
        signal: tokens/step = 1 + accept_rate * k on average)."""
        return self.accepted / max(self.proposed, 1)

    def _note_step(self, spec=False):
        """One device step executed (python counters + registry mirror)."""
        self.steps += 1
        self._m_steps.inc()
        if spec:
            self.spec_steps += 1
            self._m_spec.inc()

    def _note_accept(self, proposed, accepted):
        """One slot's speculative window accounted."""
        self.proposed += proposed
        self.accepted += accepted
        self._m_proposed.inc(proposed)
        self._m_accepted.inc(accepted)

    def submit(self, tokens, max_new_tokens=None, priority=0):
        """Queue a prompt (1-D int sequence); returns the request id.

        ``priority`` matters only under preemption (paged mode with
        ``MXNET_FLEET_SWAP``): higher values are swapped out LAST when
        the pool runs dry.  Admission order stays FIFO."""
        tokens = np.asarray(tokens).reshape(-1)
        if tokens.size > self._max_prefill:
            raise MXNetError("prompt length %d exceeds max_prefill %d"
                             % (tokens.size, self._max_prefill))
        rid = self._next_id
        self._next_id += 1
        cap = int(max_new_tokens) if max_new_tokens is not None \
            else self._max_new
        self._queue.append({"rid": rid, "prompt": tokens, "cap": cap,
                            "prio": int(priority), "swap": None})
        self._req[rid] = {"submit": time.time()}
        return rid

    def inject(self, record, front=False):
        """Queue a restorable :class:`~mxnet_tpu.serve.swap.
        SwappedRequest` — a page-migrated prefill from a dedicated
        prefill worker, or a request another host swapped out.  The
        record admits through the normal reservation gate and restores
        by installing its saved pages (no prefill); SLO timestamps carry
        over so fleet TTFT stays honest.  Returns this host's rid."""
        rid = self._next_id
        self._next_id += 1
        entry = {"rid": rid, "prompt": record.prompt, "cap": record.cap,
                 "prio": record.priority, "swap": record}
        (self._queue.appendleft if front else self._queue.append)(entry)
        rec = {"submit": record.submit_ts
               if record.submit_ts is not None else time.time()}
        self._req[rid] = rec
        return rid

    # retained retired-request records (stats percentiles); older ones
    # are pruned so a long-lived server cannot grow without bound (the
    # profiler-side store has the same cap)
    _REQ_CAP = 4096

    def _finish(self, rid, ntokens):
        """Close a request's SLO record and publish it to the profiler
        (queue wait, time to first token, decode tokens/s)."""
        from . import profiler as _prof

        rec = self._req.get(rid)
        if rec is None or "retire" in rec:
            return
        now = time.time()
        rec["retire"] = now
        rec["tokens"] = int(ntokens)
        first = rec.get("first", now)
        self._m_ttft.observe(max(first - rec["submit"], 0.0))
        _prof.record_request(
            rec.get("admit", rec["submit"]) - rec["submit"],
            first - rec["submit"], ntokens, now - first)
        _obs.instant("retire", cat="serve",
                     args={"rid": rid, "tokens": int(ntokens)})
        self._done_rids.append(rid)
        while len(self._done_rids) > self._REQ_CAP:
            self._req.pop(self._done_rids.popleft(), None)

    def _deliver(self, rec, emitted):
        """Append a window of emitted tokens to a request, honoring its
        cap and retiring at an EOS inside the window (shared by the
        dense and paged loops — ONE copy of the retirement rule)."""
        toks, max_new = rec["toks"], rec["cap"]
        for t in emitted:
            if len(toks) >= max_new:
                break
            toks.append(int(t))
            if self._eos_id is not None and t == self._eos_id:
                break

    def _retire_finished(self, active, results, on_retire=None):
        """Retire every finished request in ``active`` (EOS delivered or
        cap reached): record the result, close its SLO record, free the
        slot — plus ``on_retire(slot)`` for loop-specific cleanup (the
        paged loop frees the slot's pages here, immediately)."""
        for slot in list(active):
            rec = active[slot]
            rid, toks = rec["rid"], rec["toks"]
            if (self._eos_id is not None and toks
                    and toks[-1] == self._eos_id) \
                    or len(toks) >= rec["cap"]:
                results[rid] = np.asarray(toks, np.int32)
                self.tokens_out += len(toks)
                self._m_tokens.inc(len(toks))
                self._finish(rid, len(toks))
                del active[slot]
                if on_retire is not None:
                    on_retire(slot)

    def stats(self):
        """Serving-side SLO snapshot: loop counters, per-request
        percentiles (queue wait, TTFT, decode tokens/s) and — in paged
        mode — pool utilization and prefix-cache hit accounting."""
        from .profiler import _percentile

        done = [r for r in self._req.values() if "retire" in r]
        out = {"steps": self.steps, "spec_steps": self.spec_steps,
               "tokens_out": self.tokens_out,
               "accept_rate": self.accept_rate,
               "requests_completed": len(done),
               "requests_queued": len(self._queue)}
        if done:
            qw = sorted(r.get("admit", r["submit"]) - r["submit"]
                        for r in done)
            tf = sorted(r.get("first", r["retire"]) - r["submit"]
                        for r in done)
            out["queue_wait_p50_s"] = _percentile(qw, 0.50)
            out["queue_wait_p95_s"] = _percentile(qw, 0.95)
            out["ttft_p50_s"] = _percentile(tf, 0.50)
            out["ttft_p95_s"] = _percentile(tf, 0.95)
            rates = sorted(
                (r["tokens"] - 1)
                / max(r["retire"] - r.get("first", r["retire"]), 1e-9)
                for r in done if r["tokens"] > 1)
            if rates:
                out["decode_tokens_per_sec_p50"] = _percentile(rates, 0.50)
                out["decode_tokens_per_sec_p95"] = _percentile(rates, 0.95)
        if getattr(self._pred, "_paged", False) \
                and self._pred._manager is not None:
            out.update(self._pred._manager.stats())
        out["swap_outs"] = self.swap_outs
        out["swap_ins"] = self.swap_ins
        return out

    def serve_summary(self):
        """The routing view a fleet front-end scores this host by —
        served inside ``/metrics.json`` (the ``mx_serve_summary:<host>``
        key)
        when the metrics HTTP sidecar is armed, and read directly by an
        in-process :class:`~mxnet_tpu.serve.fleet.Router`: free-page /
        queue-depth load signals plus the prefix-cache CHAIN SUMMARY
        (content-free token-chain hashes, ``PrefixCache.summary``) the
        cache-aware policy matches prompts against."""
        mgr = getattr(self._pred, "_manager", None)
        active = len(self._ps["active"]) if self._ps is not None else 0
        pending = 1 if self._ps is not None and self._ps["pending"] else 0
        out = {"host": self._host,
               "slots": self._slots,
               "active": active + pending,
               "queue_depth": len(self._queue),
               "free_pages": mgr.allocator.free_pages
               if mgr is not None else None,
               "swap_outs": self.swap_outs,
               "chains": None}
        if mgr is not None and mgr.prefix_cache is not None:
            out["chains"] = mgr.prefix_cache.summary()
        return out

    def run(self):
        """Drain the queue; returns ``{request_id: np.int32 array}`` of
        generated tokens (EOS included when hit).

        With speculation armed (``spec_k``/``MXNET_SPEC_K``/``proposer``/
        ``draft``), each iteration drafts k tokens per slot and commits
        1..k+1 through ONE verify pass; a sequence that emits EOS or hits
        its cap MID-WINDOW retires immediately — the window's later
        tokens are discarded from the result (their cache entries are
        dead weight the next admission overwrites) and the freed slot
        refills before the next step.  Near the ring-wrap boundary the
        loop falls back to plain single-token steps (both programs
        already traced — still zero retraces).

        With a paged predictor the loop instead drives the page-managed
        schedule (:meth:`_run_paged`): prompts admit in fixed-size chunks
        interleaved with decode steps, prefix-cache hits skip their
        matched pages' prefill, copy-on-write forks run before divergent
        writes, and retirement frees pages immediately.
        """
        import jax

        if getattr(self._pred, "_paged", False):
            return self._run_paged()
        key = jax.random.PRNGKey(self._seed)
        state = None
        active = {}     # slot -> [rid, tokens list, max_new]
        results = {}
        histories = {}  # slot -> committed token list (proposer food)
        slot_lens = np.zeros(self._slots, np.int64)
        proposer = self._proposer
        k = self._spec_k
        limit = self._pred.cache_len
        if proposer is not None and getattr(proposer, "cache_len", None):
            limit = min(limit, proposer.cache_len + 1)
        if self._insert_fn is None:
            self._insert_fn = _build_insert_fn()

        def retire():
            self._retire_finished(active, results)

        deliver = self._deliver

        while self._queue or active:
            # admit: prefill one request per free slot, splice into batch
            while self._queue and len(active) < self._slots:
                entry = self._queue.popleft()
                rid, prompt = entry["rid"], entry["prompt"]
                padded = _pad_window(prompt, self._max_prefill)
                key, sub = jax.random.split(key)
                one, _ = self._pred.prefill(padded, prompt.size, sub)
                rec = self._req[rid]
                rec["admit"] = rec["first"] = time.time()
                slot = next(s for s in range(self._slots)
                            if s not in active)
                _obs.instant("admit", cat="serve",
                             args={"rid": rid, "slot": slot})
                if state is None:
                    state = _empty_batch_state(one, self._slots)
                first = int(np.asarray(one.tok)[0, 0])
                state = self._insert_fn(state, one, np.int32(slot))
                if proposer is not None \
                        and getattr(proposer, "needs_prefill", False):
                    key, sub = jax.random.split(key)
                    proposer.admit(padded, prompt.size, slot, self._slots,
                                   sub)
                active[slot] = {"rid": rid, "toks": [first],
                                "cap": entry["cap"],
                                "prio": entry["prio"], "prompt": prompt}
                histories[slot] = list(prompt.astype(np.int64)) + [first]
                slot_lens[slot] = prompt.size
            retire()
            if not active:
                continue
            key, sub = jax.random.split(key)
            can_spec = proposer is not None and k > 0 and \
                max(slot_lens[s] for s in active) + k + 1 <= limit
            if can_spec:
                hists = [histories.get(s) or [0] for s in range(self._slots)]
                draft_toks, draft_probs = proposer.propose(
                    hists, state, slot_lens, sub)
                key, sub = jax.random.split(key)
                state, out, counts = self._pred.verify_step(
                    state, draft_toks, draft_probs, sub)
                out_h = np.asarray(out)
                counts_h = np.asarray(counts).astype(np.int64)
                self._note_step(spec=True)
                for slot, rec in active.items():
                    emitted = out_h[slot, :counts_h[slot]]
                    self._note_accept(k, int(counts_h[slot]) - 1)
                    deliver(rec, emitted)
                    histories[slot].extend(int(t) for t in emitted)
                slot_lens += counts_h
            else:
                state, _ = self._pred.step(state, sub)
                self._note_step()
                toks = np.asarray(state.tok)[:, 0]
                for slot, rec in active.items():
                    deliver(rec, toks[slot:slot + 1])
                    histories[slot].append(int(toks[slot]))
                slot_lens += 1
            retire()
        return results

    # ------------------------------------------------------------------
    # the paged serving schedule — a persistent SESSION driven one
    # iteration at a time (:meth:`serve_tick`), so a fleet router
    # (``serve.fleet``) can interleave hosts, inject migrated state and
    # collect preemptions between iterations; :meth:`run` drives the
    # same tick loop to drain the local queue.
    # ------------------------------------------------------------------
    def serve_open(self):
        """Get-or-create the paged serving session: fresh page pools,
        manager and batch bookkeeping.  Idempotent while a session is
        live; :meth:`serve_reset` closes it (compiled programs are
        per-predictor and survive — a reopened session retraces
        nothing)."""
        import jax

        if self._ps is not None:
            return self._ps
        pred = self._pred
        slots = self._slots
        # AOT cold start (MXNET_AOT): before the first request, load
        # every serving program's serialized executable from the
        # content-addressed program cache (or compile-and-save on a
        # miss) — host readiness becomes a deserialize, and the loaded
        # programs serve with zero traces (docs/programs.md)
        from .programs import aot as _aot

        if _aot.enabled() and getattr(pred, "_paged", False) \
                and pred._mesh is None:
            # a proposer that supplies draft PROBABILITIES (a non-greedy
            # draft model) gives verify a different signature than the
            # deterministic-proposer one prepared here — leave verify on
            # the JIT path then, instead of arming an executable every
            # verify step would mismatch into a fallback
            prop = self._proposer
            probs_prop = getattr(prop, "predictor", None) is not None \
                and not prop.predictor._greedy
            self.aot_report = pred.prepare_programs(
                slots, chunk_w=self._chunk_w,
                spec_k=0 if probs_prop else self._spec_k)
        elif _aot.enabled():
            import logging

            logging.getLogger(__name__).info(
                "MXNET_AOT is armed but this server's predictor is %s; "
                "AOT preparation covers paged single-host predictors "
                "only (docs/programs.md) — keeping the JIT path",
                "mesh-sharded" if getattr(pred, "_mesh", None) is not None
                else "dense (non-paged)")
        self._ps = {
            "key": jax.random.PRNGKey(self._seed),
            "state": pred.paged_batch_state(slots),
            "active": {},       # slot -> request record dict
            "results": {},
            "histories": {},
            "slot_lens": np.zeros(slots, np.int64),
            "act_mask": np.zeros(slots, np.int32),
            "pending": None,    # the one admission mid-chunked-prefill
            "blocked": 0,       # consecutive pool-gate-blocked ticks
        }
        return self._ps

    def serve_reset(self):
        """Close the paged session (pools, manager, batch state).  The
        next :meth:`serve_open` starts cold — same compiled programs,
        fresh memory manager.  The predictor's manager is dropped NOW,
        not at reopen: a fleet router polls :meth:`serve_summary`
        before the first tick, and scoring prompts against the previous
        session's ghost chains would mis-route the whole first burst."""
        self._ps = None
        if getattr(self._pred, "_paged", False):
            self._pred._manager = None

    @property
    def has_work(self):
        """Whether the paged session still has queued, mid-prefill or
        decoding requests."""
        if self._ps is None:
            return bool(self._queue)
        ps = self._ps
        return bool(self._queue or ps["active"] or ps["pending"])

    def serve_results(self, clear=True):
        """``{rid: np.int32 tokens}`` finished since the session opened
        (or since the last ``clear``)."""
        if self._ps is None:
            return {}
        out = dict(self._ps["results"])
        if clear:
            self._ps["results"].clear()
        return out

    def _run_paged(self):
        """Drain the local queue through the tick loop (fresh session
        per call — :meth:`run`'s historical contract)."""
        self.serve_reset()
        self.serve_open()
        while self.has_work:
            self.serve_tick()
        return self.serve_results(clear=True)

    def _paged_limit(self):
        limit = self._pred.cache_len
        prop = self._proposer
        if prop is not None and getattr(prop, "cache_len", None):
            limit = min(limit, prop.cache_len + 1)
        return limit

    def _on_retire_paged(self, ps):
        def on_retire(slot):
            ps["act_mask"][slot] = 0
            # pages back to the pool NOW — the very next admission
            # gate sees them (not "at next admission")
            self._pred._manager.free_slot(slot)
        return on_retire

    def _admit_one(self, ps):
        """Gate the queue head: a fresh prompt starts chunked prefill
        (returns its pending dict, stored in ``ps``); a restorable
        record (swap-in / migrated prefill) installs its pages and the
        slot activates immediately (returns True).  None = the pool
        cannot cover it yet (backpressure)."""
        mgr = self._pred._manager
        entry = self._queue[0]
        if entry["swap"] is not None:
            return self._try_restore(ps, entry)
        rid, prompt, cap = entry["rid"], entry["prompt"], entry["cap"]
        gate = mgr.gate(prompt, prompt.size, cap, self._spec_k)
        if gate is None:
            return None
        self._queue.popleft()
        matched, pages, reserve_n = gate
        slot = next(s for s in range(self._slots)
                    if s not in ps["active"])
        mgr.map_slot(slot, pages, reserve_n)
        self._req[rid]["admit"] = time.time()
        _obs.instant("admit", cat="serve",
                     args={"rid": rid, "slot": slot,
                           "prefix_matched": int(matched)})
        ps["pending"] = {"slot": slot, "rid": rid,
                         "prompt": np.asarray(prompt).reshape(-1)
                         .astype(np.int64), "cap": cap,
                         "prio": entry["prio"], "pos": int(matched)}
        return ps["pending"]

    def _try_restore(self, ps, entry):
        """Admit a :class:`~mxnet_tpu.serve.swap.SwappedRequest` by
        restoring its pages: reserve through the normal gate, allocate
        fresh pages at the SAME ring positions, scatter the saved
        contents back (one traced install program), splice lens/tok.
        Zero prefill, zero retraces; bit-parity with the pre-swap pool
        (``_verify_restore`` re-extracts and asserts it in tests)."""
        import jax.numpy as jnp

        pred = self._pred
        mgr = pred._manager
        rec = entry["swap"]
        if getattr(rec, "kv_heads", None) != pred._grouped_kv_heads:
            # page planes are raw pool bytes with no head structure of
            # their own — installing a grouped record into an MHA host
            # (or across different G) would silently misread every page
            raise MXNetError(
                "swap restore: record kv layout (kv_heads=%r) does not "
                "match this host's (kv_heads=%r)"
                % (rec.kv_heads, pred._grouped_kv_heads))
        m = mgr.pages_per_slot
        remaining = max(rec.cap - len(rec.delivered), 0)
        total = rec.lens + remaining + self._spec_k + 1
        target = min(-(-min(total, pred.cache_len)
                       // mgr.page_tokens), m)
        # a record that re-publishes its prompt chain AND will wrap must
        # budget one fork per prompt page up front (the gate's
        # budget_wrap_forks rule): a later request may map the published
        # pages, turning the wrap recycle into a copy-on-write fork
        fork = -(-rec.prompt.size // mgr.page_tokens) \
            if rec.publish and total > pred.cache_len else 0
        need = rec.n_pages + max(target - rec.n_pages, 0) + fork
        if not mgr.gate_pages(need):
            return None
        self._queue.popleft()
        slot = next(s for s in range(self._slots)
                    if s not in ps["active"])
        row = mgr.restore_slot(slot, rec.row_valid, need)
        state = ps["state"]
        caches = pred.install_pages(state.caches, row, rec.data)
        lens2, tok2 = pred._commit_fn(
            state.lens, state.tok, np.int32(slot),
            jnp.asarray([rec.lens], jnp.int32),
            jnp.asarray([[rec.tok]], jnp.int32))
        ps["state"] = DecodeState(caches, lens2, tok2)
        if self._verify_restore:
            back = pred.extract_pages(ps["state"].caches, row)
            import jax.tree_util as jtu

            for a, b in zip(jtu.tree_leaves(back),
                            jtu.tree_leaves(rec.data)):
                assert np.array_equal(
                    np.asarray(a)[rec.row_valid],
                    np.asarray(b)[rec.row_valid]), \
                    "restored pages are not bit-identical"
        if rec.publish:
            mgr.publish(slot, rec.prompt, rec.prompt.size)
        if self._proposer is not None \
                and getattr(self._proposer, "needs_prefill", False):
            import jax

            ps["key"], sub = jax.random.split(ps["key"])
            self._proposer.admit(
                _pad_window(rec.prompt, self._max_prefill),
                rec.prompt.size, slot, self._slots, sub)
        rid = entry["rid"]
        req = self._req[rid]
        req["admit"] = time.time()
        if rec.first_ts is not None:
            req["first"] = rec.first_ts
        else:
            req["first"] = req["admit"]
        ps["active"][slot] = {"rid": rid, "toks": list(rec.delivered),
                              "cap": rec.cap, "prio": rec.priority,
                              "prompt": rec.prompt}
        ps["histories"][slot] = list(rec.history)
        ps["slot_lens"][slot] = rec.lens
        ps["act_mask"][slot] = 1
        if rec.kind == "swap":
            self.swap_ins += 1
        else:
            self._m_migrated_pages.inc(rec.n_pages)
        _obs.instant("swap_in" if rec.kind == "swap" else "page_migrate",
                     cat="serve", args={"rid": rid, "slot": slot,
                                        "pages": rec.n_pages})
        self._retire_finished(ps["active"], ps["results"],
                              self._on_retire_paged(ps))
        return True

    def _swap_out(self, ps, slot):
        """Preempt ``slot``: extract its pages to host RAM (one traced
        program), free them, and hand the restorable record to the
        fleet's preemption callback — or re-queue it locally at the
        back, so the blocked waiter admits and the victim resumes
        later.  Returns the record."""
        from .serve.swap import SwappedRequest

        pred = self._pred
        mgr = pred._manager
        rec = ps["active"][slot]
        row = mgr.tables[slot].copy()
        valid = row != 0
        data = pred.extract_pages(ps["state"].caches, row)
        req = self._req.get(rec["rid"], {})
        record = SwappedRequest(
            rec["prompt"], rec["toks"], ps["histories"][slot],
            rec["cap"], rec["prio"], int(ps["slot_lens"][slot]),
            int(np.asarray(ps["state"].tok)[slot, 0]),
            valid, data, kind="swap",
            submit_ts=req.get("submit"), first_ts=req.get("first"),
            rid=rec["rid"], kv_heads=pred._grouped_kv_heads)
        mgr.free_slot(slot)
        ps["act_mask"][slot] = 0
        ps["slot_lens"][slot] = 0
        del ps["active"][slot]
        del ps["histories"][slot]
        self.swap_outs += 1
        self._m_swapped_pages.inc(record.n_pages)
        _obs.instant("swap_out", cat="serve",
                     args={"rid": record.rid, "slot": int(slot),
                           "pages": record.n_pages})
        if self._preempt_cb is not None:
            # the SLO record travels WITH the record (submit/first ts);
            # the readmitting host creates its own — drop ours or a
            # fleet host under preemption churn leaks one _req entry
            # per swap-out forever (never retired, never pruned)
            self._req.pop(record.rid, None)
            self._preempt_cb(record)
        else:
            self._queue.append({"rid": record.rid,
                                "prompt": record.prompt,
                                "cap": record.cap,
                                "prio": record.priority,
                                "swap": record})
        return record

    def _preempt_for_waiter(self, ps, allow_bound):
        """ONE copy of the preemption rule, for both blocking modes
        (slot-full and pool-gate-blocked): the queue head evicts the
        lowest-priority (then longest-running) slot when it strictly
        outranks it — or, with ``allow_bound``, when the fair-admission
        bound has been exceeded.  Swaps, re-admits, resets the blocked
        counter on success; returns the re-admission result (None = no
        preemption or still blocked)."""
        active = ps["active"]
        if not (self._swap_armed and active and self._queue):
            return None
        victim = min(active,
                     key=lambda s: (active[s]["prio"],
                                    -int(ps["slot_lens"][s])))
        bound_hit = allow_bound and self._fair_bound > 0 \
            and ps["blocked"] >= self._fair_bound
        if active[victim]["prio"] >= self._queue[0]["prio"] \
                and not bound_hit:
            return None
        self._swap_out(ps, victim)
        got = self._admit_one(ps)
        # one swap per bound window: the counter restarts even when the
        # waiter is still blocked, so preemption cannot cascade through
        # every resident in consecutive ticks
        ps["blocked"] = 0
        return got

    def serve_tick(self):
        """ONE iteration of the paged serving schedule.

        (1) gate at most one queued request through the page allocator —
        reservation failure is BACKPRESSURE, the request stays queued
        until retirements free pages; fair admission: after
        ``MXNET_FLEET_DECODE_BOUND`` consecutive gate-blocked decode
        iterations the lowest-priority (then longest) slot is preempted
        to host RAM (``MXNET_FLEET_SWAP``), so a long decode can no
        longer wedge the admission gate; (2) advance the in-flight
        admission by ONE prefill chunk (prefix-cache-matched pages were
        mapped at the gate, only the tail computes), so a long prompt
        interleaves with decode instead of stalling the batch; (3) on
        the final chunk, splice the first token/length into the batch
        state, publish the prompt's pages to the prefix cache and
        activate the slot; (4) retire finished requests — freeing their
        pages IMMEDIATELY, EOS-mid-speculation-window included; (5) run
        one decode (or speculative verify) step over the active slots,
        inactive rows masked.  Every device program here was traced
        once — page tables, active masks, slot indices, page ids and
        swapped page contents are all data.
        """
        import jax
        import jax.numpy as jnp

        pred = self._pred
        ps = self.serve_open()
        mgr = pred._manager
        slots = self._slots
        greedy = pred._greedy

        def next_key():
            # greedy sampling never reads the key: skip the per-tick
            # split dispatches (a measurable slice of small-batch serve)
            if greedy:
                return pred._zero_key
            ps["key"], sub = jax.random.split(ps["key"])
            return sub

        active = ps["active"]
        histories = ps["histories"]
        slot_lens = ps["slot_lens"]
        act_mask = ps["act_mask"]
        proposer = self._proposer
        k = self._spec_k
        limit = self._paged_limit()
        on_retire = self._on_retire_paged(ps)

        def retire():
            self._retire_finished(active, ps["results"], on_retire)

        deliver = self._deliver

        # --- (1a) slot-full priority preemption: a waiter that OUTRANKS
        # the lowest-priority resident evicts it even when the block is
        # slots, not pages — priority scheduling; equal priorities keep
        # the classic wait-for-retirement behavior
        if ps["pending"] is None and len(active) >= slots:
            self._preempt_for_waiter(ps, allow_bound=False)
        # --- (1) admission gate: one request starts (or restores)
        if ps["pending"] is None and self._queue and len(active) < slots:
            got = self._admit_one(ps)
            if got is None:
                ps["blocked"] += 1
                if not active:
                    # nothing running to free pages: spill the whole
                    # prefix cache, then the pool is genuinely too small
                    if mgr.prefix_cache is not None:
                        mgr.prefix_cache.evict(mgr.pool_pages)
                        got = self._admit_one(ps)
                    if got is None:
                        raise MXNetError(
                            "KV page pool (%d pages) cannot admit a "
                            "%d-token request even with an empty batch — "
                            "raise MXNET_KV_POOL_PAGES"
                            % (mgr.pool_pages,
                               self._queue[0]["prompt"].size))
                else:
                    # pool-gate preemption: a HIGHER-priority waiter
                    # evicts immediately; any waiter evicts the
                    # lowest-priority slot once the gate has blocked
                    # MXNET_FLEET_DECODE_BOUND consecutive iterations.
                    # The waiter admits on the freed pages and the
                    # victim resumes bit-exactly
                    got = self._preempt_for_waiter(ps, allow_bound=True)
            if got is not None:
                ps["blocked"] = 0
        # --- (2) one prefill chunk of the in-flight admission
        if ps["pending"] is not None:
            p = ps["pending"]
            state = ps["state"]
            n = min(self._chunk_w, p["prompt"].size - p["pos"])
            copies = mgr.ensure(p["slot"], p["pos"], p["pos"] + n)
            caches = pred._run_forks(state.caches, copies) \
                if copies else state.caches
            sub = next_key()
            _obs.instant("prefill_chunk", cat="serve",
                         args={"slot": p["slot"], "pos": p["pos"],
                               "tokens": int(n)})
            with _obs.program_span("prefill"):
                caches, probs, tok = pred._chunk_fn(
                    pred._env, caches,
                    jnp.asarray(mgr.tables[p["slot"]:p["slot"] + 1]),
                    jnp.asarray(_pad_window(
                        p["prompt"][p["pos"]:p["pos"] + n],
                        self._chunk_w)),
                    jnp.asarray([p["pos"]], jnp.int32),
                    jnp.asarray([n], jnp.int32), sub)
            ps["state"] = state = DecodeState(caches, state.lens,
                                              state.tok)
            p["pos"] += n
            pred._chunk_widths.add(self._chunk_w)
            if p["pos"] >= p["prompt"].size:
                # --- (3) commit: the slot joins the batch
                slot, plen = p["slot"], p["prompt"].size
                first = int(np.asarray(tok)[0, 0])
                lens2, tok2 = pred._commit_fn(
                    state.lens, state.tok, np.int32(slot),
                    jnp.asarray([plen], jnp.int32), tok)
                ps["state"] = DecodeState(state.caches, lens2, tok2)
                mgr.publish(slot, p["prompt"], plen)
                if proposer is not None \
                        and getattr(proposer, "needs_prefill", False):
                    ps["key"], sub = jax.random.split(ps["key"])
                    proposer.admit(
                        _pad_window(p["prompt"], self._max_prefill),
                        plen, slot, slots, sub)
                active[slot] = {"rid": p["rid"], "toks": [first],
                                "cap": p["cap"], "prio": p["prio"],
                                "prompt": p["prompt"]}
                histories[slot] = list(p["prompt"]) + [first]
                slot_lens[slot] = plen
                act_mask[slot] = 1
                self._req[p["rid"]]["first"] = time.time()
                ps["pending"] = None
                retire()        # a first-token EOS / cap-1 request
        self._note_gauges()
        if not active:
            return
        # --- (5) one decode / verify step over the active slots
        sub = next_key()
        can_spec = proposer is not None and k > 0 \
            and ps["pending"] is None \
            and max(slot_lens[s] for s in active) + k + 1 <= limit
        if can_spec:
            hists = [histories.get(s) or [0] for s in range(slots)]
            draft_toks, draft_probs = proposer.propose(
                hists, ps["state"], slot_lens, sub)
            sub = next_key()
            state, out, counts = pred.paged_verify(
                ps["state"], slot_lens, draft_toks, draft_probs, sub,
                act_mask)
            ps["state"] = state
            out_h = np.asarray(out)
            counts_h = np.asarray(counts).astype(np.int64)
            self._note_step(spec=True)
            for slot, rec in active.items():
                emitted = out_h[slot, :counts_h[slot]]
                self._note_accept(k, int(counts_h[slot]) - 1)
                deliver(rec, emitted)
                histories[slot].extend(int(t) for t in emitted)
            slot_lens += counts_h
        else:
            state, _ = pred.paged_step(ps["state"], slot_lens, sub,
                                       act_mask)
            ps["state"] = state
            toks = np.asarray(state.tok)[:, 0]
            self._note_step()
            for slot, rec in active.items():
                deliver(rec, toks[slot:slot + 1])
                histories[slot].append(int(toks[slot]))
            slot_lens += act_mask.astype(np.int64)
        retire()

    def _note_gauges(self):
        """Refresh the per-host queue-depth / free-page gauges (the
        router's load + headroom signals)."""
        self._m_queue_depth.set(len(self._queue))
        mgr = getattr(self._pred, "_manager", None)
        if mgr is not None:
            self._m_free_pages.set(mgr.allocator.free_pages)
