"""Monitor — per-op output statistics for debugging (NaN hunting).

Same role as the reference's ``python/mxnet/monitor.py`` over the executor
monitor callback (`graph_executor.cc:758-778`): every op output (plus,
between tic/toc, every argument array) is reduced by a statistic function
and collected for printing.  Installing a monitor flips the executor into
its eager node-by-node path (the NaiveEngine analog) so intermediates exist
to observe — see Executor.forward.

Re-designed around plain records: statistics are materialized to host
floats/arrays at collection time, and formatting is a separate step.
"""
from __future__ import annotations

import logging
import re

import numpy as np

from . import ndarray as nd


def _default_stat(x):
    """Mean absolute value — cheap, scale-aware, NaN-propagating."""
    return nd.norm(x) / (x.size ** 0.5)


class _Tap:
    """Executor-facing callback wrapper exposing the monitor's armed state."""

    def __init__(self, monitor):
        self._monitor = monitor

    def __call__(self, name, array):
        self._monitor._observe(name, array)

    @property
    def active(self):
        return self._monitor._collecting


class Monitor:
    """Collects ``(step, name, stat)`` records during monitored batches.

    Parameters mirror the reference: ``interval`` (batches between
    collections), ``stat_func`` (NDArray -> NDArray statistic), ``pattern``
    (regex over tensor names), ``sort`` (order records by name in toc).
    """

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        self.interval = interval
        self.stat_func = stat_func or _default_stat
        self.sort = sort
        self._matches = re.compile(pattern).match
        self._records = []
        self._step = 0
        self._collecting = False
        self._executors = []

    # -- executor hookup ---------------------------------------------------
    def install(self, exe):
        """Register this monitor's tap with an executor.

        The tap carries an ``active`` property so the executor can keep
        non-collecting batches on the fast jitted path — the eager per-op
        pass only runs on the 1-in-``interval`` armed batches (the
        reference's inactive taps are similarly near-free no-ops).
        """
        exe.set_monitor_callback(_Tap(self))
        self._executors.append(exe)

    def _observe(self, name, array):
        if self._collecting and self._matches(name):
            self._records.append((self._step, name, self.stat_func(array)))

    # -- batch lifecycle ---------------------------------------------------
    def tic(self):
        """Call before a batch; arms collection every ``interval`` steps."""
        if self._step % self.interval == 0:
            self._records = []
            self._collecting = True
        self._step += 1

    def toc(self):
        """Call after the batch; returns [(step, name, rendered_stat)] and
        disarms.  Also samples every matching argument array (weights), so
        exploding params are visible alongside activations."""
        if not self._collecting:
            return []
        for exe in self._executors:
            for name, arr in zip(exe._symbol.list_arguments(),
                                 exe.arg_arrays):
                if self._matches(name):
                    self._records.append(
                        (self._step, name, self.stat_func(arr)))
        self._collecting = False

        records = sorted(self._records, key=lambda r: r[1]) if self.sort \
            else list(self._records)
        self._records = []
        return [(step, name, self._render(stat))
                for step, name, stat in records]

    def toc_print(self):
        """toc() + log each record."""
        for step, name, rendered in self.toc():
            logging.info("Batch: %7d %30s %s", step, name, rendered)

    @staticmethod
    def _render(stat):
        values = stat if isinstance(stat, list) else [stat]
        parts = []
        for v in values:
            host = v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v)
            parts.append(str(host.item()) if host.size == 1 else str(host))
        return "\t".join(parts)
