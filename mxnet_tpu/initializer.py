"""Weight initializers.

API parity with the reference's ``python/mxnet/initializer.py`` (same class
names, same name-suffix dispatch contract), rebuilt around a functional
core: every initializer produces its values via ``generate(name, shape)``
and a single assignment point writes them into the target buffer.  Role
detection is a data table, not an if-chain, so subclasses and tests can
inspect/extend it.
"""
from __future__ import annotations

import json
import re

import numpy as np

from .base import MXNetError

__all__ = ["Initializer", "Uniform", "Normal", "Orthogonal", "Xavier",
           "MSRAPrelu", "Bilinear", "One", "Zero", "Constant", "Load",
           "Mixed", "LSTMBias", "FusedRNN", "init_registry"]

init_registry = {}


def register(klass):
    init_registry[klass.__name__.lower()] = klass
    return klass


def create(spec):
    """Instantiate an initializer from its ``dumps()`` JSON form."""
    klass, kwargs = json.loads(spec)
    return init_registry[klass.lower()](**kwargs)


class InitDesc(str):
    """A parameter name plus its Variable attributes.

    ``Module.init_params`` passes these so a per-Variable ``__init__`` attr
    (e.g. ``Variable(..., init=LSTMBias(1.0))``) overrides the global
    initializer for that parameter.
    """

    def __new__(cls, name, attrs=None, global_init=None):
        desc = super().__new__(cls, name)
        desc.attrs = attrs or {}
        desc.global_init = global_init
        return desc


def _bilinear_kernel(shape):
    """Bilinear-interpolation upsampling kernel of the given (..., H, W)
    shape, vectorized over the spatial grid."""
    h, w = shape[-2], shape[-1]
    f = np.ceil(w / 2.0)
    center = (2 * f - 1 - f % 2) / (2.0 * f)
    ys, xs = np.ogrid[:h, :w]
    tap = (1 - np.abs(xs / f - center)) * (1 - np.abs(ys / f - center))
    return np.broadcast_to(tap, shape).astype(np.float32)


# suffix -> method name; longest suffix wins (checked in order), mirroring
# the reference's dispatch contract for BatchNorm/bias/weight param names
_ROLE_RULES = (
    ("moving_inv_var", "_init_zero"),
    ("moving_mean", "_init_zero"),
    ("moving_var", "_init_one"),
    ("moving_avg", "_init_zero"),
    ("weight", "_init_weight"),
    ("gamma", "_init_gamma"),
    ("beta", "_init_beta"),
    ("bias", "_init_bias"),
)


class Initializer:
    """Base class: routes a parameter to its role-specific rule.

    Subclasses typically override only ``generate`` (values for *weight*
    parameters); biases/BatchNorm statistics get their conventional
    constants regardless of scheme.
    """

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        """Serialized form consumed by FusedRNN(init=<str>)."""
        return json.dumps([type(self).__name__.lower(), self._kwargs])

    # -- dispatch ----------------------------------------------------------
    def __call__(self, name, arr):
        # a Variable-attached init (InitDesc attrs) takes precedence over
        # this (global) initializer, whatever the name suffix
        spec = getattr(name, "attrs", {}).get("__init__")
        if spec:
            create(spec)._init_weight(name, arr)
            return
        name_s = str(name)
        if name_s.startswith("upsampling"):
            arr[:] = _bilinear_kernel(arr.shape)
            return
        for suffix, method in _ROLE_RULES:
            if name_s.endswith(suffix):
                getattr(self, method)(name, arr)
                return
        self._init_default(name, arr)

    # -- role rules (constants unless overridden) --------------------------
    def _init_zero(self, _, arr):
        arr[:] = 0.0

    def _init_one(self, _, arr):
        arr[:] = 1.0

    _init_bias = _init_zero
    _init_beta = _init_zero
    _init_gamma = _init_one

    def _init_weight(self, name, arr):
        arr[:] = self.generate(name, arr.shape)

    def generate(self, name, shape):
        """Return a numpy array of weight values for ``shape``."""
        raise NotImplementedError(
            "%s must implement generate()" % type(self).__name__)

    def _init_default(self, name, arr):
        raise MXNetError(
            "No initialization rule matches parameter %r; recognized "
            "suffixes: %s (or attach an init attr to the Variable)"
            % (name, ", ".join(s for s, _ in _ROLE_RULES)))


# -- random schemes ---------------------------------------------------------


@register
class Uniform(Initializer):
    """U(-scale, scale)."""

    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def generate(self, name, shape):
        return np.random.uniform(-self.scale, self.scale, shape)


@register
class Normal(Initializer):
    """N(0, sigma^2)."""

    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def generate(self, name, shape):
        return np.random.normal(0.0, self.sigma, shape)


@register
class Orthogonal(Initializer):
    """Scaled orthogonal rows/columns (Saxe et al. 2013), via QR with sign
    correction rather than SVD."""

    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def generate(self, name, shape):
        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        if self.rand_type == "uniform":
            seed = np.random.uniform(-1.0, 1.0, (max(rows, cols),
                                                 min(rows, cols)))
        elif self.rand_type == "normal":
            seed = np.random.standard_normal((max(rows, cols),
                                              min(rows, cols)))
        else:
            raise ValueError("rand_type must be 'uniform' or 'normal'")
        q, r = np.linalg.qr(seed)
        # make the factorization unique (and q's distribution uniform over
        # the orthogonal group) by fixing the signs of r's diagonal
        q *= np.sign(np.diag(r))
        if rows < cols:
            q = q.T
        return (self.scale * q).reshape(shape)


def _fan_in_out(shape):
    """(fan_in, fan_out) with conv receptive-field scaling: dims beyond the
    first two multiply both fans."""
    receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
    return shape[1] * receptive, shape[0] * receptive


@register
class Xavier(Initializer):
    """Glorot-style variance scaling."""

    _FACTORS = {
        "avg": lambda fi, fo: (fi + fo) / 2.0,
        "in": lambda fi, fo: fi,
        "out": lambda fi, fo: fo,
    }

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        if factor_type not in self._FACTORS:
            raise ValueError("factor_type must be one of %s"
                             % sorted(self._FACTORS))
        if rnd_type not in ("uniform", "gaussian"):
            raise ValueError("rnd_type must be 'uniform' or 'gaussian'")
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def generate(self, name, shape):
        fan_in, fan_out = _fan_in_out(shape)
        bound = np.sqrt(self.magnitude
                        / self._FACTORS[self.factor_type](fan_in, fan_out))
        if self.rnd_type == "uniform":
            return np.random.uniform(-bound, bound, shape)
        return np.random.normal(0.0, bound, shape)


@register
class MSRAPrelu(Xavier):
    """He/Kaiming init adjusted for PReLU slope."""

    def __init__(self, factor_type="avg", slope=0.25):
        super().__init__("gaussian", factor_type, 2.0 / (1 + slope ** 2))
        self._kwargs = {"factor_type": factor_type, "slope": slope}


# -- constant schemes -------------------------------------------------------


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def generate(self, name, shape):
        return np.full(shape, self.value, np.float32)

    def _init_default(self, name, arr):
        arr[:] = self.value


@register
class One(Constant):
    def __init__(self):
        super().__init__(1.0)
        self._kwargs = {}


@register
class Zero(Constant):
    def __init__(self):
        super().__init__(0.0)
        self._kwargs = {}


@register
class Bilinear(Initializer):
    def generate(self, name, shape):
        return _bilinear_kernel(shape)


# -- composite / data-driven schemes ----------------------------------------


@register
class Load:
    """Serve values from a loaded ``{name: array}`` dict, falling back to
    ``default_init`` for names not present."""

    def __init__(self, param, default_init=None, verbose=False):
        self.param = {}
        for key, value in param.items():
            bare = key.split(":", 1)[1] if key[:4] in ("arg:", "aux:") \
                else key
            self.param[bare] = value
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        source = self.param.get(name)
        if source is not None:
            if tuple(source.shape) != tuple(arr.shape):
                raise MXNetError(
                    "Loaded parameter %r has shape %s, expected %s"
                    % (name, tuple(source.shape), tuple(arr.shape)))
            arr[:] = source
        elif self.default_init is not None:
            self.default_init(name, arr)
        else:
            raise MXNetError("Parameter %r is not in the loaded dict and no "
                             "default_init was given" % name)


@register
class Mixed:
    """First-match-wins regex routing to member initializers."""

    def __init__(self, patterns, initializers):
        if len(patterns) != len(initializers):
            raise ValueError("patterns and initializers must pair up")
        self.map = [(re.compile(p), init)
                    for p, init in zip(patterns, initializers)]

    def __call__(self, name, arr):
        for matcher, init in self.map:
            if matcher.match(name):
                init(name, arr)
                return
        raise MXNetError("Parameter %r matched no pattern (have: %s)"
                         % (name, [m.pattern for m, _ in self.map]))


# -- RNN-specific schemes ---------------------------------------------------


def _lstm_bias(shape, forget_bias):
    """Zero bias with the forget gate (second quarter, i/f/c/o gate order)
    set to ``forget_bias``."""
    bias = np.zeros(shape, np.float32)
    nh = shape[0] // 4
    bias[nh:2 * nh] = forget_bias
    return bias


@register
class LSTMBias(Initializer):
    """LSTM bias init with a configurable forget-gate bias (combats early
    vanishing gradients)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def generate(self, name, shape):
        return _lstm_bias(shape, self.forget_bias)

    # attr-dispatch enters through _init_weight whatever the target is
    _init_bias = Initializer._init_weight


@register
class FusedRNN(Initializer):
    """Initialize a FusedRNNCell's packed parameter blob by unpacking it,
    running an inner initializer per logical weight/bias, and re-packing."""

    def __init__(self, init, num_hidden, num_layers, mode,
                 bidirectional=False, forget_bias=1.0):
        if isinstance(init, str):
            klass, kwargs = json.loads(init)
            init = init_registry[klass.lower()](**kwargs)
        super().__init__(init=init.dumps() if init is not None else None,
                         num_hidden=num_hidden, num_layers=num_layers,
                         mode=mode, bidirectional=bidirectional,
                         forget_bias=forget_bias)
        self._init = init
        self._spec = dict(num_hidden=num_hidden, num_layers=num_layers,
                          mode=mode, bidirectional=bidirectional,
                          forget_bias=forget_bias)

    def _init_weight(self, name, arr):
        from .rnn.rnn_cell import FusedRNNCell

        inner = self._init or getattr(name, "global_init", None)
        if inner is None:
            raise MXNetError("FusedRNN needs an inner initializer (or a "
                             "global one via InitDesc) for its weights")
        spec = self._spec
        # bare prefix: this scratch cell only translates layout, and the
        # pieces dict below is keyed without the owning cell's prefix
        cell = FusedRNNCell(spec["num_hidden"], spec["num_layers"],
                            spec["mode"], spec["bidirectional"],
                            forget_bias=spec["forget_bias"], prefix="")
        pieces = cell.unpack_weights({"parameters": arr.copy()})
        for pname, piece in pieces.items():
            if spec["mode"] == "lstm" and pname.endswith("bias"):
                piece[:] = _lstm_bias(piece.shape, spec["forget_bias"])
            else:
                inner(pname, piece)
        arr[:] = cell.pack_weights(pieces)["parameters"]

    # '<prefix>parameters' has no role suffix; direct calls route here too
    _init_default = _init_weight
