"""Weight initializers (reference: python/mxnet/initializer.py, 612 LoC).

Dispatch by parameter-name suffix exactly as the reference does: *_bias → 0,
*_gamma → 1, *_beta → 0, *moving_mean → 0, *moving_var → 1, *weight → the
chosen scheme.
"""
from __future__ import annotations

import json

import numpy as np

from .base import MXNetError

__all__ = ["Initializer", "Uniform", "Normal", "Orthogonal", "Xavier",
           "MSRAPrelu", "Bilinear", "One", "Zero", "Constant", "Load", "Mixed",
           "LSTMBias", "FusedRNN", "init_registry"]

init_registry = {}


def register(klass):
    init_registry[klass.__name__.lower()] = klass
    return klass


class Initializer:
    """Base initializer: name-pattern dispatch (reference: initializer.py:20)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, name, arr):
        if not isinstance(name, str):
            name = str(name)
        if name.startswith("upsampling"):
            self._init_bilinear(name, arr)
        elif name.endswith("bias"):
            self._init_bias(name, arr)
        elif name.endswith("gamma"):
            self._init_gamma(name, arr)
        elif name.endswith("beta"):
            self._init_beta(name, arr)
        elif name.endswith("weight"):
            self._init_weight(name, arr)
        elif name.endswith("moving_mean"):
            self._init_zero(name, arr)
        elif name.endswith("moving_var"):
            self._init_one(name, arr)
        elif name.endswith("moving_inv_var"):
            self._init_zero(name, arr)
        elif name.endswith("moving_avg"):
            self._init_zero(name, arr)
        else:
            self._init_default(name, arr)

    def _init_bilinear(self, _, arr):
        weight = np.zeros(arr.size, dtype=np.float32)
        shape = arr.shape
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(arr.size):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = weight.reshape(shape)

    def _init_zero(self, _, arr):
        arr[:] = 0.0

    def _init_one(self, _, arr):
        arr[:] = 1.0

    def _init_bias(self, _, arr):
        arr[:] = 0.0

    def _init_gamma(self, _, arr):
        arr[:] = 1.0

    def _init_beta(self, _, arr):
        arr[:] = 0.0

    def _init_weight(self, name, arr):
        raise NotImplementedError("Must override _init_weight")

    def _init_default(self, name, arr):
        raise MXNetError(
            "Unknown initialization pattern for %s. Default initialization is now "
            "limited to weight/bias/gamma/beta; use mx.sym.Variable(init=...) to "
            "set initialization pattern" % name)


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, _, arr):
        arr[:] = np.random.uniform(-self.scale, self.scale, arr.shape)


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, _, arr):
        arr[:] = np.random.normal(0, self.sigma, arr.shape)


@register
class One(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 1.0

    _init_default = _init_weight


@register
class Zero(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 0.0

    _init_default = _init_weight


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        arr[:] = self.value

    _init_default = _init_weight


@register
class Orthogonal(Initializer):
    """Orthogonal matrix init (reference: initializer.py:177, Saxe et al.)."""

    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = np.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = np.random.normal(0.0, 1.0, (nout, nin))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        arr[:] = (self.scale * q).reshape(arr.shape)


@register
class Xavier(Initializer):
    """Xavier/Glorot (reference: initializer.py:203)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, _, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) > 2:
            hw_scale = np.prod(shape[2:])
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise ValueError("Incorrect factor type")
        scale = np.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            arr[:] = np.random.uniform(-scale, scale, shape)
        elif self.rnd_type == "gaussian":
            arr[:] = np.random.normal(0, scale, shape)
        else:
            raise ValueError("Unknown random type")


@register
class MSRAPrelu(Xavier):
    """Kaiming init (reference: initializer.py:239)."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    def _init_weight(self, name, arr):
        self._init_bilinear(name, arr)


@register
class Load:
    """Init from a dict of saved arrays (reference: initializer.py:86)."""

    def __init__(self, param, default_init=None, verbose=False):
        self.param = {k[4:] if k.startswith("arg:") or k.startswith("aux:") else k: v
                      for k, v in param.items()}
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        if name in self.param:
            if self.param[name].shape != arr.shape:
                raise MXNetError("Parameter %s shape mismatch: %s vs %s"
                                 % (name, self.param[name].shape, arr.shape))
            arr[:] = self.param[name]
        else:
            if self.default_init is None:
                raise MXNetError("Cannot init %s: not in loaded param and no "
                                 "default_init" % name)
            self.default_init(name, arr)


@register
class Mixed:
    """Pattern-matched mix of initializers (reference: initializer.py:115)."""

    def __init__(self, patterns, initializers):
        import re

        assert len(patterns) == len(initializers)
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise MXNetError("Parameter %s did not match any pattern" % name)


@register
class LSTMBias(Initializer):
    """Init LSTM biases with custom forget-gate bias (reference: :260)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_bias(self, name, arr):
        b = np.zeros(arr.shape, dtype=np.float32)
        num_hidden = arr.shape[0] // 4
        b[num_hidden:2 * num_hidden] = self.forget_bias  # i, f, c, o gate order
        arr[:] = b


@register
class FusedRNN(Initializer):
    """Init fused RNN packed parameters (reference: initializer.py:285)."""

    def __init__(self, init, num_hidden, num_layers, mode, bidirectional=False,
                 forget_bias=1.0):
        if isinstance(init, str):
            klass, kwargs = json.loads(init)
            init = init_registry[klass.lower()](**kwargs)
        super().__init__(init=init.dumps() if init is not None else None,
                         num_hidden=num_hidden, num_layers=num_layers, mode=mode,
                         bidirectional=bidirectional, forget_bias=forget_bias)
        self._init = init
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._forget_bias = forget_bias

    def _init_weight(self, name, arr):
        from .rnn.rnn_cell import FusedRNNCell

        cell = FusedRNNCell(self._num_hidden, self._num_layers, self._mode,
                            self._bidirectional, forget_bias=self._forget_bias)
        args = cell.unpack_weights({"parameters": arr.copy()})
        for pname, value in args.items():
            desc = pname
            if self._init is None:
                raise MXNetError("FusedRNN requires an inner init")
            if pname.endswith("bias") and self._forget_bias is not None and \
                    self._mode == "lstm":
                value[:] = 0.0
                nh = self._num_hidden
                value[nh:2 * nh] = self._forget_bias
            else:
                self._init(desc, value)
            args[pname] = value
        arr[:] = cell.pack_weights(args)["parameters"]
