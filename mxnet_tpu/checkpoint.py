"""Sharded checkpointing — the multi-host-scale upgrade.

The reference's checkpoint is a host-gathered binary blob
(`src/ndarray/ndarray.cc:605-700`; kept here as `model.save_checkpoint`
for format parity).  That requires every parameter on one host — fine for
one chip, impossible for pod-scale models.  This module adds the TPU-era
path on orbax: each host writes only ITS shards of the mesh-sharded
params/aux/optimizer state, and restore re-shards onto the live mesh.

    from mxnet_tpu import checkpoint
    checkpoint.save_sharded(prefix_dir, step, module)
    checkpoint.load_sharded(prefix_dir, step, module)

Works on any module bound over a mesh (or a single device — then it is
simply an async, atomic checkpoint directory).

Commit protocol: a step directory counts as a checkpoint only once it is
*committed* — orbax's atomic rename has landed AND the commit marker
(:data:`COMMIT_MARKER`, written last) is present.  ``latest_step`` skips
uncommitted/torn directories — including post-rename crash debris that
carries orbax's own metadata but never reached the marker — so a crash
mid-save can never poison resume by becoming the "latest" checkpoint.
Adopt a checkpoint written by external orbax tooling with
:func:`commit_step`.  The elastic subsystem
(``mxnet_tpu.elastic``) builds its fence checkpoints on these exact
primitives and adds a sidecar with loop state (RNG chain, metric sums,
iterator cursor) for deterministic resume.
"""
from __future__ import annotations

import os

from .base import MXNetError

__all__ = ["save_sharded", "load_sharded", "latest_step", "save_state_tree",
           "commit_step", "is_committed", "COMMIT_MARKER"]

# written LAST, inside the finalized step directory; mirrors the name orbax
# itself uses on non-atomic filesystems (GCS) so external tooling recognizes it
COMMIT_MARKER = "commit_success.txt"


def _state_of(module):
    """The module's live state as a pytree of (possibly sharded) jax
    arrays: params + aux from the executor buffers (flushed if a fused
    step holds newer state), optimizer slots when present.  Requires a
    bound ``Module``."""
    module._flush_fused()   # fused master state -> executor buffers
    exe = module._exec_group.exec_
    state = {
        "params": {n: exe.arg_dict[n].data
                   for n in module._exec_group.param_names},
        "aux": {n: exe.aux_dict[n].data
                for n in module._exec_group.aux_names},
    }
    step = getattr(module, "_fused_step", None)
    if step is not None and step.slots:
        state["slots"] = {n: list(s) for n, s in step.slots.items()}
    return state


def save_state_tree(directory, step, state):
    """Write an arbitrary pytree of jax arrays as the step's orbax
    checkpoint and commit it (marker written after the atomic rename).
    The building block ``save_sharded`` and the elastic fence writer
    share; safe to call from a background writer thread."""
    import orbax.checkpoint as ocp

    path = os.path.join(os.path.abspath(directory), str(step))
    with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as ckptr:
        ckptr.save(path, state, force=True)
    return path


def commit_step(path):
    """Drop the commit marker into a finalized step directory — the LAST
    write of a checkpoint.  ``latest_step`` only ever returns committed
    steps, so a crash anywhere before this leaves the previous checkpoint
    as the resume point instead of a torn directory."""
    with open(os.path.join(path, COMMIT_MARKER), "w") as f:
        f.write("committed\n")
    return path


def is_committed(directory, step):
    """Whether ``directory/step`` is a complete, committed checkpoint —
    the marker file is the ONLY accepted evidence.  Orbax writes its own
    ``_CHECKPOINT_METADATA`` inside the renamed directory, so accepting
    it would count the debris of a crash *between* the rename and the
    sidecar/marker writes as committed; checkpoints produced by external
    orbax tooling must be adopted explicitly with :func:`commit_step`."""
    path = os.path.join(os.path.abspath(directory), str(step))
    return os.path.isdir(path) and \
        os.path.exists(os.path.join(path, COMMIT_MARKER))


def save_sharded(directory, step, module):
    """Write an orbax checkpoint of the module's params/aux (+fused
    optimizer slots) at ``directory/step`` — every host writes its own
    shards; the directory commit is atomic and marker-finalized."""
    assert module.binded and module.params_initialized
    path = save_state_tree(directory, step, _state_of(module))
    return commit_step(path)


def _disk_tree(ckptr, path):
    """The saved checkpoint's structure-with-array-metadata, across orbax
    API generations: modern releases return the tree dict directly from
    ``metadata()``; older ones wrap it as ``.item_metadata.tree``."""
    md = ckptr.metadata(path)
    if isinstance(md, dict):
        return md
    item = getattr(md, "item_metadata", None)
    tree = getattr(item, "tree", None)
    if tree is not None:
        return tree
    if isinstance(item, dict):
        return item
    raise MXNetError("unrecognized orbax metadata layout for %s: %r"
                     % (path, type(md).__name__))


def load_sharded(directory, step, module):
    """Restore params/aux (+slots when both sides have them) in place,
    re-sharded to the module's live mesh placement.  Structure differences
    are tolerated: a training checkpoint (with optimizer slots) restores
    into an inference module, and vice versa — a slot-less checkpoint
    loaded into a training module synthesizes FRESH optimizer slots (zero
    moments) rather than keeping moments from whatever the module trained
    on before."""
    import jax
    import logging

    import orbax.checkpoint as ocp

    assert module.binded and module.params_initialized
    if step is None:
        raise MXNetError("no checkpoint step to load from %s (is the "
                         "directory empty?)" % directory)
    path = os.path.join(os.path.abspath(directory), str(step))
    if not os.path.isdir(path):
        raise MXNetError("no sharded checkpoint at %s" % path)

    template = _state_of(module)
    abstract = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
        template)

    with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as ckptr:
        # orbax requires the restore target to match the SAVED structure;
        # synthesize plain abstract leaves for on-disk sections the module
        # does not carry (e.g. slots into an inference module), and drop
        # module sections absent on disk (restored state leaves them as-is)
        disk_tree = _disk_tree(ckptr, path)
        target = {}
        for key, sub in disk_tree.items():
            if key in abstract:
                target[key] = abstract[key]
            else:
                target[key] = jax.tree_util.tree_map(
                    lambda m: jax.ShapeDtypeStruct(tuple(m.shape), m.dtype),
                    sub)
        for key in abstract:
            if key not in disk_tree:
                logging.info("sharded checkpoint %s has no %r section; "
                             "leaving the module's live state", path, key)
        state = ckptr.restore(path, args=ocp.args.StandardRestore(target))

    exe = module._exec_group.exec_
    for name, val in state["params"].items():
        exe.arg_dict[name]._set_data(val)
    for name, val in state.get("aux", {}).items():
        exe.aux_dict[name]._set_data(val)
    fused = getattr(module, "_fused_step", None)
    if fused is not None:
        # master store must adopt the restored executor buffers
        fused.load_from_executor()
        module._step_stale = False
        if "slots" in state and fused.slots:
            fused.slots = {n: tuple(s) for n, s in state["slots"].items()}
            # restored slots are now the live optimizer state — a later
            # fused step must not re-import stale eager updater moments
            module._opt_owner = "fused"
        elif fused.slots:
            # slot-less (inference) checkpoint into a training module: the
            # restored params deserve FRESH moments, not the moments of the
            # weights they just replaced; owning them as "fused" keeps a
            # stale eager updater from re-importing the old ones either
            fused.reset_slots()
            module._opt_owner = "fused"
    module._params_dirty = True
    return module


def latest_step(directory):
    """Highest COMMITTED step number checkpointed under ``directory`` (or
    None).  Torn directories — a crash mid-save, an in-flight async write,
    an orbax tmp dir — are skipped, never returned as the resume point."""
    if not os.path.isdir(directory):
        return None
    steps = [int(d) for d in os.listdir(directory)
             if d.isdigit() and is_committed(directory, d)]
    return max(steps) if steps else None
