"""Sharded checkpointing — the multi-host-scale upgrade.

The reference's checkpoint is a host-gathered binary blob
(`src/ndarray/ndarray.cc:605-700`; kept here as `model.save_checkpoint`
for format parity).  That requires every parameter on one host — fine for
one chip, impossible for pod-scale models.  This module adds the TPU-era
path on orbax: each host writes only ITS shards of the mesh-sharded
params/aux/optimizer state, and restore re-shards onto the live mesh.

    from mxnet_tpu import checkpoint
    checkpoint.save_sharded(prefix_dir, step, module)
    checkpoint.load_sharded(prefix_dir, step, module)

Works on any module bound over a mesh (or a single device — then it is
simply an async, atomic checkpoint directory).
"""
from __future__ import annotations

import os

from .base import MXNetError

__all__ = ["save_sharded", "load_sharded", "latest_step"]


def _state_of(module):
    """The module's live state as a pytree of (possibly sharded) jax
    arrays: params + aux from the executor buffers (flushed if a fused
    step holds newer state), optimizer slots when present.  Requires a
    bound ``Module``."""
    module._flush_fused()   # fused master state -> executor buffers
    exe = module._exec_group.exec_
    state = {
        "params": {n: exe.arg_dict[n].data
                   for n in module._exec_group.param_names},
        "aux": {n: exe.aux_dict[n].data
                for n in module._exec_group.aux_names},
    }
    step = getattr(module, "_fused_step", None)
    if step is not None and step.slots:
        state["slots"] = {n: list(s) for n, s in step.slots.items()}
    return state


def save_sharded(directory, step, module):
    """Write an orbax checkpoint of the module's params/aux (+fused
    optimizer slots) at ``directory/step`` — every host writes its own
    shards; the directory commit is atomic."""
    import orbax.checkpoint as ocp

    assert module.binded and module.params_initialized
    path = os.path.join(os.path.abspath(directory), str(step))
    with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as ckptr:
        ckptr.save(path, _state_of(module), force=True)
    return path


def load_sharded(directory, step, module):
    """Restore params/aux (+slots when both sides have them) in place,
    re-sharded to the module's live mesh placement.  Structure differences
    are tolerated: a training checkpoint (with optimizer slots) restores
    into an inference module, and vice versa."""
    import jax
    import logging

    import orbax.checkpoint as ocp

    assert module.binded and module.params_initialized
    if step is None:
        raise MXNetError("no checkpoint step to load from %s (is the "
                         "directory empty?)" % directory)
    path = os.path.join(os.path.abspath(directory), str(step))
    if not os.path.isdir(path):
        raise MXNetError("no sharded checkpoint at %s" % path)

    template = _state_of(module)
    abstract = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
        template)

    with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as ckptr:
        # orbax requires the restore target to match the SAVED structure;
        # synthesize plain abstract leaves for on-disk sections the module
        # does not carry (e.g. slots into an inference module), and drop
        # module sections absent on disk (restored state leaves them as-is)
        disk_tree = ckptr.metadata(path).item_metadata.tree
        target = {}
        for key, sub in disk_tree.items():
            if key in abstract:
                target[key] = abstract[key]
            else:
                target[key] = jax.tree_util.tree_map(
                    lambda m: jax.ShapeDtypeStruct(tuple(m.shape), m.dtype),
                    sub)
        for key in abstract:
            if key not in disk_tree:
                logging.info("sharded checkpoint %s has no %r section; "
                             "leaving the module's live state", path, key)
        state = ckptr.restore(path, args=ocp.args.StandardRestore(target))

    exe = module._exec_group.exec_
    for name, val in state["params"].items():
        exe.arg_dict[name]._set_data(val)
    for name, val in state.get("aux", {}).items():
        exe.aux_dict[name]._set_data(val)
    fused = getattr(module, "_fused_step", None)
    if fused is not None:
        # master store must adopt the restored executor buffers
        fused.load_from_executor()
        module._step_stale = False
        if "slots" in state and fused.slots:
            fused.slots = {n: tuple(s) for n, s in state["slots"].items()}
            # restored slots are now the live optimizer state — a later
            # fused step must not re-import stale eager updater moments
            module._opt_owner = "fused"
    module._params_dirty = True
    return module


def latest_step(directory):
    """Highest step number checkpointed under ``directory`` (or None)."""
    if not os.path.isdir(directory):
        return None
    steps = [int(d) for d in os.listdir(directory) if d.isdigit()]
    return max(steps) if steps else None
