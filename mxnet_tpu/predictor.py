"""Standalone inference: symbol JSON + params -> jitted forward.

TPU-native analog of the reference's C predict API
(``c_predict_api.cc``, ``include/mxnet/c_predict_api.h:59-169``):

==============================  =======================================
reference                       here
==============================  =======================================
``MXPredCreate``                ``Predictor(symbol, params, shapes)``
``MXPredCreatePartialOut``      ``Predictor(..., output_names=[...])``
``MXPredReshape``               ``Predictor.reshape({...})``
``MXPredSetInput/Forward``      ``Predictor.forward(**inputs)``
``MXPredGetOutputShape``        ``Predictor.output_shapes``
``MXPredGetOutput``             ``Predictor.get_output(i)``
==============================  =======================================

Where the reference amalgamates a NaiveEngine build for deployment, the
TPU path exports the jitted forward as **StableHLO** (`Predictor.export`
/ `load_exported`) — a self-contained artifact an XLA runtime can execute
with no Python graph machinery, the analog of the amalgamation's
single-file predict build.
"""
from __future__ import annotations

import numpy as np

from .base import MXNetError
from . import context as ctx_mod
from . import ndarray as nd
from . import symbol as sym_mod

__all__ = ["Predictor", "load_exported", "DecodePredictor",
           "DecodeServer", "NGramProposer", "DraftProposer"]


def _shape_key(input_shapes):
    """Canonical cache key for a set of bound input shapes."""
    return tuple(sorted((n, tuple(s)) for n, s in input_shapes.items()))


class Predictor:
    """Inference-only executor from a trained model.

    Parameters
    ----------
    symbol : Symbol or str
        The network — a Symbol, a JSON string, or a path to a
        ``*-symbol.json`` file.
    params : dict, str, or bytes
        ``{name: NDArray/ndarray}`` (``arg:``/``aux:`` prefixes optional),
        a ``.params`` file path, or the file's bytes.
    input_shapes : dict
        ``{input_name: shape}`` for every data input.
    ctx : Context, optional
        Device; defaults to cpu.
    output_names : list of str, optional
        Predict a subset / internal nodes instead of the symbol's outputs
        (``MXPredCreatePartialOut``).  Names may be given with or without
        the ``_output`` suffix.
    type_dict : dict, optional
        Input dtypes (defaults come from graph dtype inference).
    """

    def __init__(self, symbol, params, input_shapes, ctx=None,
                 output_names=None, type_dict=None):
        import jax

        if isinstance(symbol, str):
            if symbol.lstrip().startswith("{"):
                symbol = sym_mod.load_json(symbol)
            else:
                symbol = sym_mod.load(symbol)
        if output_names:
            internals = symbol.get_internals()
            available = internals.list_outputs()
            picked = []
            for name in output_names:
                cands = [name, name + "_output"]
                hit = next((c for c in cands if c in available), None)
                if hit is None:
                    raise MXNetError(
                        "output %r not found among internal nodes" % name)
                picked.append(internals[hit])
            symbol = sym_mod.Group(picked)

        arg_params, aux_params = _as_param_dicts(params)
        self._symbol = symbol
        self._ctx = ctx if ctx is not None else ctx_mod.cpu()
        self._input_shapes = dict(input_shapes)
        self._type_dict = dict(type_dict) if type_dict else None

        arg_names = symbol.list_arguments()
        # free inputs = args without stored weights; ones the caller gave no
        # shape for (e.g. loss labels) are inferred and fed zeros — the
        # reference predictor likewise keeps label inputs unbound
        self._data_names = [n for n in arg_names
                            if n not in arg_params and n not in aux_params]
        extra = [n for n in self._input_shapes if n not in self._data_names]
        if extra:
            raise MXNetError("input_shapes names %s are not free inputs of "
                             "the symbol" % extra)

        self._exec = symbol.simple_bind(
            self._ctx, grad_req="null", type_dict=self._type_dict,
            **self._input_shapes)
        self._exec.copy_params_from(arg_params, aux_params,
                                    allow_extra_params=True)
        self._outputs = None
        self._arg_params = arg_params
        self._aux_params = aux_params
        # bound executors keyed by input shapes, SHARED with reshape()
        # clones: flipping between shapes (bucketed serving) reuses the
        # executor — and its per-shape jitted forward — instead of
        # re-binding and re-compiling from scratch every time
        self._bind_cache = {_shape_key(self._input_shapes): self._exec}

    # ------------------------------------------------------------------
    @classmethod
    def from_checkpoint(cls, prefix, epoch, input_shapes, **kwargs):
        """Build from ``prefix-symbol.json`` + ``prefix-####.params``."""
        from .model import load_checkpoint

        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        params = {("arg:%s" % k): v for k, v in arg_params.items()}
        params.update({("aux:%s" % k): v for k, v in aux_params.items()})
        return cls(symbol, params, input_shapes, **kwargs)

    # ------------------------------------------------------------------
    @property
    def output_names(self):
        return self._symbol.list_outputs()

    @property
    def output_shapes(self):
        _, out_shapes, _ = self._symbol.infer_shape(**self._input_shapes)
        return list(zip(self.output_names, out_shapes))

    def forward(self, **inputs):
        """Run inference; returns the list of output NDArrays."""
        feeds = {}
        for name, value in inputs.items():
            if name not in self._data_names:
                raise MXNetError("unknown input %r (inputs are %s)"
                                 % (name, self._data_names))
            if not isinstance(value, nd.NDArray):
                value = nd.array(np.asarray(value), ctx=self._ctx)
            bound = self._input_shapes.get(
                name, self._exec.arg_dict[name].shape)
            if tuple(value.shape) != tuple(bound):
                raise MXNetError(
                    "input %r shape %s does not match bound shape %s — use "
                    "reshape()" % (name, value.shape, bound))
            feeds[name] = value
        self._exec.forward(is_train=False, **feeds)
        self._outputs = self._exec.outputs
        return list(self._outputs)

    def get_output(self, index=0):
        if self._outputs is None:
            raise MXNetError("call forward() before get_output()")
        return self._outputs[index]

    def reshape(self, input_shapes):
        """New Predictor bound to different input shapes, sharing weights
        (``MXPredReshape``) AND the bind cache: reshaping back to a
        previously-bound shape reuses that shape's executor and its jitted
        forward instead of re-binding from scratch."""
        shapes = dict(self._input_shapes)
        shapes.update(input_shapes)
        clone = Predictor.__new__(Predictor)
        clone._symbol = self._symbol
        clone._ctx = self._ctx
        clone._input_shapes = shapes
        clone._type_dict = self._type_dict
        clone._data_names = self._data_names
        clone._arg_params = self._arg_params
        clone._aux_params = self._aux_params
        clone._bind_cache = self._bind_cache
        key = _shape_key(shapes)
        exec_ = self._bind_cache.get(key)
        if exec_ is None:
            exec_ = self._symbol.simple_bind(
                self._ctx, grad_req="null", type_dict=self._type_dict,
                **shapes)
            exec_.copy_params_from(self._arg_params, self._aux_params,
                                   allow_extra_params=True)
            self._bind_cache[key] = exec_
        clone._exec = exec_
        clone._outputs = None
        return clone

    # ------------------------------------------------------------------
    def _pure_fn(self):
        """The forward pass as a pure jax function of the *provided* data
        inputs; weights — and unfed inputs like labels — are captured so
        export folds them into the artifact."""
        import jax

        exe = self._exec
        feed_names = [n for n in self._data_names if n in self._input_shapes]
        params = {n: exe.arg_dict[n].data for n in exe._arg_names
                  if n not in feed_names}
        aux = {n: exe.aux_dict[n].data for n in exe._aux_names}

        def fn(*data_vals):
            env_args = dict(params)
            env_args.update(zip(feed_names, data_vals))
            outs, _ = exe._run_graph(env_args, dict(aux),
                                     jax.random.PRNGKey(0), False)
            return tuple(outs)

        return fn, feed_names

    def _pure_fn_specs(self):
        """``(pure_fn, input avals)`` — the one builder behind every
        export/analysis surface, so spec construction cannot diverge
        between them."""
        import jax

        fn, feed_names = self._pure_fn()
        specs = []
        for n in feed_names:
            dt = self._exec.arg_dict[n].data.dtype
            specs.append(
                jax.ShapeDtypeStruct(tuple(self._input_shapes[n]), dt))
        return fn, specs

    def export(self, path=None):
        """Serialize the jitted forward as a StableHLO artifact
        (``jax.export`` bytes).  The analog of the reference's
        amalgamated predict-only build: the artifact embeds the weights
        and needs only an XLA runtime to execute."""
        import jax
        from jax import export as jax_export

        fn, specs = self._pure_fn_specs()
        exported = jax_export.export(jax.jit(fn))(*specs)
        blob = exported.serialize()
        if path is not None:
            with open(path, "wb") as f:
                f.write(blob)
        return blob

    def artifact(self, name="predict_forward"):
        """:class:`~mxnet_tpu.analysis.artifact.ProgramArtifact` of the
        inference forward at the bound input shapes — the same uniform
        jaxpr/StableHLO/compiled-HLO surface the training-step and decode
        programs expose, so the analysis passes can audit a deployment
        graph (host-callback lint, FLOP coverage) before it ships."""
        import jax

        from .analysis.artifact import artifact_from_jit

        fn, specs = self._pure_fn_specs()
        return artifact_from_jit(jax.jit(fn), specs, name=name,
                                 donated_leaves=0)

    def export_stablehlo_text(self):
        """Human-readable StableHLO of the forward program."""
        import jax
        from jax import export as jax_export

        fn, specs = self._pure_fn_specs()
        exported = jax_export.export(jax.jit(fn))(*specs)
        return exported.mlir_module()


def load_exported(blob_or_path):
    """Deserialize a `Predictor.export` artifact into a callable taking the
    data inputs (numpy/jax arrays) and returning output arrays."""
    from jax import export as jax_export

    if isinstance(blob_or_path, str):
        with open(blob_or_path, "rb") as f:
            blob = f.read()
    else:
        blob = bytes(blob_or_path)
    exported = jax_export.deserialize(blob)

    def run(*data_vals):
        return exported.call(*data_vals)

    return run


# incremental decoding (prefill/decode split, KV caches, batched serving) —
# re-exported here so the deployment surface is one import, mirroring how
# the reference groups every predict entry point in c_predict_api.h
from .decode import (DecodePredictor, DecodeServer,  # noqa: E402
                     DraftProposer, NGramProposer)


def _as_param_dicts(params):
    """Normalize any accepted params form into (arg_params, aux_params)."""
    if isinstance(params, (str, bytes, bytearray, memoryview)):
        params = nd.load(params)
    if not isinstance(params, dict):
        raise MXNetError("params must be a dict, a .params path, or bytes")
    arg_params, aux_params = {}, {}
    for key, value in params.items():
        if not isinstance(value, nd.NDArray):
            value = nd.array(np.asarray(value))
        if key.startswith("arg:"):
            arg_params[key[4:]] = value
        elif key.startswith("aux:"):
            aux_params[key[4:]] = value
        else:
            arg_params[key] = value
    return arg_params, aux_params
