"""Native runtime loader.

The reference's runtime core is C++ (`src/` — engine, storage, io, C ABI).
On TPU, XLA subsumes the engine/storage layers, but byte-pushing IO is
still native here: `src/recordio.cc` implements the RecordIO codec behind
a small C ABI, loaded over ctypes (this environment has no pybind11; the
CPython-free C ABI also keeps the door open for non-Python frontends,
reference `include/mxnet/c_api.h`).

The shared library is built on demand from the repo's `src/` with g++ and
cached in `mxnet_tpu/lib/`; everything degrades to the pure-Python
implementations when a toolchain is unavailable.
"""
from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(os.path.dirname(_HERE), "src")
_LIBDIR = os.path.join(_HERE, "lib")

_lock = threading.Lock()
_recordio = None
_recordio_tried = False


def _build(src_path, lib_path):
    os.makedirs(os.path.dirname(lib_path), exist_ok=True)
    # compile to a private temp name, then rename: the build must be atomic
    # against concurrent processes (dist tests spawn several), and an
    # in-place rewrite would truncate an inode another process has mapped
    tmp_path = "%s.tmp.%d" % (lib_path, os.getpid())
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
           src_path, "-o", tmp_path]
    try:
        subprocess.run(cmd, check=True, capture_output=True)
        os.rename(tmp_path, lib_path)
    finally:
        if os.path.exists(tmp_path):
            os.unlink(tmp_path)


def _configure_recordio(lib):
    lib.rio_last_error.restype = ctypes.c_char_p
    lib.rio_writer_open.restype = ctypes.c_void_p
    lib.rio_writer_open.argtypes = [ctypes.c_char_p]
    lib.rio_writer_tell.restype = ctypes.c_int64
    lib.rio_writer_tell.argtypes = [ctypes.c_void_p]
    lib.rio_writer_write.restype = ctypes.c_int64
    lib.rio_writer_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_uint64]
    lib.rio_writer_close.argtypes = [ctypes.c_void_p]
    lib.rio_reader_open.restype = ctypes.c_void_p
    lib.rio_reader_open.argtypes = [ctypes.c_char_p]
    lib.rio_reader_seek.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.rio_reader_tell.restype = ctypes.c_int64
    lib.rio_reader_tell.argtypes = [ctypes.c_void_p]
    lib.rio_reader_next.restype = ctypes.c_int
    lib.rio_reader_next.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_void_p),
                                    ctypes.POINTER(ctypes.c_uint64)]
    lib.rio_reader_close.argtypes = [ctypes.c_void_p]
    lib.rio_build_index.restype = ctypes.c_int64
    lib.rio_build_index.argtypes = [ctypes.c_char_p,
                                    ctypes.POINTER(ctypes.POINTER(ctypes.c_int64))]
    lib.rio_free.argtypes = [ctypes.c_void_p]
    return lib


def recordio_lib():
    """The native RecordIO library, building it on first use.  Returns the
    configured CDLL, or None when native IO is unavailable."""
    global _recordio, _recordio_tried
    with _lock:
        if _recordio_tried:
            return _recordio
        _recordio_tried = True
        src = os.path.join(_SRC, "recordio.cc")
        lib_path = os.path.join(_LIBDIR, "libmxtpu_io.so")
        try:
            if not os.path.isfile(src):
                return None
            if (not os.path.isfile(lib_path)
                    or os.path.getmtime(lib_path) < os.path.getmtime(src)):
                _build(src, lib_path)
            _recordio = _configure_recordio(ctypes.CDLL(lib_path))
        except (OSError, subprocess.CalledProcessError) as exc:
            logging.info("native RecordIO unavailable (%s); using the "
                         "pure-Python codec", exc)
            _recordio = None
        return _recordio


def native_error(lib):
    return lib.rio_last_error().decode()
