"""mxnet_tpu — a TPU-native deep learning framework.

A from-scratch re-design of the reference MXNet (v0.9.5) API surface for TPU
hardware: JAX/XLA replaces mshadow kernels, the memory planner, and the
dependency engine; jit-compiled graph programs replace the graph executor;
XLA collectives over a device mesh replace KVStore comm.  See SURVEY.md at
the repo root for the capability map.

Typical usage matches the reference:

    import mxnet_tpu as mx
    data = mx.sym.Variable('data')
    net = mx.sym.FullyConnected(data, num_hidden=128)
    mod = mx.mod.Module(net, ...)
    mod.fit(train_iter, ...)
"""
from . import base
from .base import MXNetError, AttrScope, NameManager
from .context import Context, cpu, gpu, tpu, current_context, num_devices
from . import attrs
from . import registry
from . import ops  # registers all operators
from . import operator  # registers the Custom user-op framework
from . import ndarray
from . import ndarray as nd
from . import random
from . import autograd
from . import symbol
from . import symbol as sym
from .symbol import Variable, Group

ndarray._init_ndarray_module()
symbol._init_symbol_module()

from . import executor
from .executor import Executor
from . import initializer
from .initializer import init_registry  # noqa: F401
from . import optimizer
from .optimizer import Optimizer
from . import lr_scheduler
from . import metric
from . import io
from . import image
from . import recordio
from . import kvstore
from . import kvstore_server
from . import callback
from . import monitor
from . import module
from . import module as mod
from . import model
from .model import FeedForward
from . import predictor
from . import rtc
from .predictor import Predictor
from . import decode
from .decode import DecodePredictor, DecodeServer
from . import rnn
from . import parallel
from . import analysis
from . import checkpoint
from . import obs
from . import profiler
from . import visualization
from . import visualization as viz
from . import contrib
from . import test_utils

__version__ = "0.1.0"
