"""Megatron-style tensor-parallel planning over the 'model' mesh axis.

The round-3 executor column-sharded *every* parameter whose leading dim
divided the model axis — correct under GSPMD but communication-naive: the
partitioner inserts an all-gather after every layer to re-replicate
activations.  The Megatron pairing (column-parallel FC1, row-parallel FC2 —
Shoeybi et al., and the scaling-book "1D weight-stationary" recipe) leaves
the intermediate activation feature-sharded so one all-reduce per *pair*
replaces per-layer all-gathers.

This module derives that pairing from the graph rather than from user
annotations: a single topological walk tracks whether each activation is
feature-sharded ('feat': last/channel dim split over 'model') or replicated
('rep'), and assigns each FullyConnected / Convolution weight a column or
row role accordingly:

    input 'rep'  -> column parallel: W[out_dim] on 'model', bias sharded,
                    output becomes 'feat'          (no collective)
    input 'feat' -> row parallel: W[in_dim] on 'model', bias replicated,
                    output 'rep'                   (one psum, from GSPMD)

Elementwise ops (Activation, Dropout, Cast, adds) propagate 'feat';
BatchNorm on a 'feat' activation shards its per-channel params/aux the same
way (its statistics reductions are per-channel, so they stay local); any
other op conservatively resets to 'rep', which GSPMD realizes with an
all-gather exactly where the naive plan paid one per layer.

The result is a {param_name: partition-axes-tuple} plan consumed by
DataParallelExecutorGroup._param_sharding; communication is *measured* by
``parallel.hlo_stats`` (collective count/bytes from compiled HLO) — see
tests/test_tensor_parallel.py and tools/bandwidth.py.
"""
from __future__ import annotations

__all__ = ["plan_tensor_parallel", "kv_cache_pspec", "kv_pool_pspec",
           "ELEMENTWISE_OPS"]

# ops through which a feature-sharded activation stays feature-sharded
# (their compute is pointwise over the sharded dim, or reduces other dims)
ELEMENTWISE_OPS = {
    "Activation", "LeakyReLU", "Dropout", "Cast", "relu", "sigmoid", "tanh",
    "exp", "log", "negative", "abs", "_plus", "_minus", "_mul", "_div",
    "elemwise_add", "elemwise_sub", "elemwise_mul", "elemwise_div",
    "_plus_scalar", "_minus_scalar", "_mul_scalar", "_div_scalar",
    "_maximum", "_minimum", "clip", "identity", "BlockGrad", "stop_gradient",
}


def _kv_head_axis(sizes, head_axis, num_kv_heads, what, degrades=None):
    """The trailing-dim mesh axis for a K/V cache/pool, kv-head aware.

    MHA (``num_kv_heads`` None/0) keeps the unconditional E-split.  A
    grouped layout's trailing dim is H_kv head slices, so the E-split IS
    an H_kv-split: legal only when ``num_kv_heads % axis_size == 0``.
    Otherwise degrade VISIBLY to replicated-group sharding (every model
    shard holds all H_kv kv heads; q heads still split) with a warning —
    wrong-but-silent sharding of a grouped pool would interleave kv-head
    slices across shards and score q-heads against the wrong group.
    ``degrades``, when a list, also records the event as
    ``{"site", "reason"}`` for the artifact's ``replicated_degrades``
    meta, which the sharding-coverage lint pass surfaces.
    """
    size = sizes.get(head_axis, 1)
    if size <= 1:
        return None
    if num_kv_heads:
        kvh = int(num_kv_heads)
        if kvh % size:
            import warnings

            warnings.warn(
                "%s: num_kv_heads=%d not divisible by %r axis size %d — "
                "degrading to replicated-group sharding (each model shard "
                "holds the full grouped K/V)" % (what, kvh, head_axis,
                                                 size))
            if degrades is not None:
                degrades.append({
                    "site": what,
                    "reason": "num_kv_heads=%d %% %s=%d != 0"
                    % (kvh, head_axis, size)})
            return None
    return head_axis


def kv_cache_pspec(mesh_shape, batch_axis="data", head_axis="model",
                   num_kv_heads=None, degrades=None):
    """PartitionSpec for a (B, C, E_kv) decode KV cache on a mesh.

    The Megatron invariant this module's plan rests on — an E-split IS a
    head-group split (heads are contiguous hd-wide slices of E) — carries
    straight to the cache: shard the trailing E dim on ``head_axis`` and
    each model shard holds, appends to, and scores only its own head
    group's K/V slice, with zero collectives in the decode step (the Pope
    et al. inference sharding).  The ring-slot dim stays replicated
    (appends index it dynamically); the batch dim shards on ``batch_axis``
    so serving slots spread over the data axis.  Axes of size 1 drop out.

    ``num_kv_heads`` (grouped-query caches) gates the trailing split on
    ``num_kv_heads % axis == 0``; otherwise the kv dim degrades visibly
    to replicated (see :func:`_kv_head_axis`).
    """
    from jax.sharding import PartitionSpec as P

    sizes = dict(mesh_shape)
    return P(batch_axis if sizes.get(batch_axis, 1) > 1 else None, None,
             _kv_head_axis(sizes, head_axis, num_kv_heads,
                           "kv_cache_pspec", degrades=degrades))


def kv_pool_pspec(mesh_shape, head_axis="model", num_kv_heads=None,
                  degrades=None):
    """PartitionSpec for a (P, page_tokens, E_kv) paged KV pool on a mesh.

    Same Megatron invariant as :func:`kv_cache_pspec` — the trailing E dim
    shards on ``head_axis`` so each model shard holds and scores only its
    own head group's slice of every page.  The page dim replicates: pages
    are a GLOBAL id space shared by every serving slot (batch never enters
    the pool's shape — slots meet the pool through their page tables), so
    there is no batch axis to spread, and the page-id gathers/scatters
    stay local per shard.  Axes of size 1 drop out.  ``num_kv_heads``
    behaves as in :func:`kv_cache_pspec`.
    """
    from jax.sharding import PartitionSpec as P

    sizes = dict(mesh_shape)
    return P(None, None,
             _kv_head_axis(sizes, head_axis, num_kv_heads,
                           "kv_pool_pspec", degrades=degrades))


def plan_tensor_parallel(symbol):
    """One topological walk -> {param_name: partition axes tuple}.

    Axes tuples use the mesh axis name 'model' (e.g. ``('model', None)`` for
    a column-parallel FC weight); params absent from the plan replicate.
    Divisibility of the sharded dim is checked by the consumer at placement
    time, per param — an unshardable member of a pair degrades to
    replicated without breaking correctness (GSPMD re-derives).
    """
    plan = {}
    state = {}  # (id(node), out_idx) -> 'rep' | 'feat'

    def instate(entry):
        return state.get((id(entry[0]), entry[1]), "rep")

    for node in symbol._topo():
        if node.is_variable:
            state[(id(node), 0)] = "rep"
            continue
        attrs = node.parsed_attrs()
        n_args = node.op.n_inputs(attrs)
        ins = node.inputs[:n_args]
        aux_ins = node.inputs[n_args:]
        name = node.op.name
        out_state = "rep"

        if name == "FullyConnected":
            data_st = instate(ins[0])
            wnode = ins[1][0]
            bnode = ins[2][0] if len(ins) > 2 else None
            if wnode.is_variable:
                if data_st == "feat":
                    # row parallel: contract over the sharded feature dim,
                    # GSPMD inserts the pair's single psum here
                    plan[wnode.name] = (None, "model")
                    out_state = "rep"
                else:
                    plan[wnode.name] = ("model", None)
                    if bnode is not None and bnode.is_variable:
                        plan[bnode.name] = ("model",)
                    out_state = "feat"
        elif name == "FusedLNLinear":
            # the LM step's fused LN->linear segment (ops/fused_lm.py)
            # carries FC's (num_hidden, K) weight with optional
            # gamma/beta/residual inputs ahead of it — same Megatron
            # column/row pairing as FullyConnected, located through the
            # op's argument list.  gamma/beta are per-INPUT-feature and
            # only valid replicated, so the row-parallel role (sharded
            # input features) is taken only for no_affine segments.
            from ..ops.fused_lm import _arg_names

            args = _arg_names(attrs)
            data_st = instate(ins[0])
            wnode = ins[args.index("weight")][0]
            bnode = ins[args.index("bias")][0]
            if wnode.is_variable:
                if data_st == "feat" and attrs.get("no_affine", False):
                    plan[wnode.name] = (None, "model")
                    out_state = "rep"
                else:
                    plan[wnode.name] = ("model", None)
                    if bnode.is_variable:
                        plan[bnode.name] = ("model",)
                    out_state = "feat"
        elif name == "Convolution":
            data_st = instate(ins[0])
            wnode = ins[1][0]
            bnode = ins[2][0] if len(ins) > 2 else None
            if wnode.is_variable and attrs.get("num_group", 1) == 1:
                if data_st == "feat":
                    # row parallel over input channels (OIHW dim 1)
                    plan[wnode.name] = (None, "model", None, None)
                    out_state = "rep"
                else:
                    plan[wnode.name] = ("model", None, None, None)
                    if bnode is not None and bnode.is_variable:
                        plan[bnode.name] = ("model",)
                    out_state = "feat"
        elif name == "BatchNorm":
            data_st = instate(ins[0])
            if data_st == "feat":
                for pnode, _ in ins[1:]:
                    if pnode.is_variable:
                        plan[pnode.name] = ("model",)
                for anode, _ in aux_ins:
                    plan[anode.name] = ("model",)
                out_state = "feat"
        elif name == "Pooling":
            # pooling reduces spatial dims only — the channel dim (NCHW or
            # NHWC alike) is untouched, so a feature-sharded activation
            # stays feature-sharded through it (round-4 verdict: the walk
            # reset here and an all-gather appeared after every pool)
            out_state = instate(ins[0])
        elif name == "Embedding":
            # Megatron vocab-dim sharding: each device holds a vocab slice,
            # GSPMD realizes the lookup as masked-local-gather + one psum,
            # and the REPLICATED output lets the following q/k/v
            # projections start column-parallel (feature-dim sharding here
            # would instead force them row-parallel: three psums where the
            # attention block needs one)
            wnode = ins[1][0]
            if wnode.is_variable:
                plan[wnode.name] = ("model", None)
                out_state = "rep"
        elif name == "dot_product_attention":
            # Megatron attention: with q/k/v all feature-sharded (their
            # projections column-parallel over heads), each device computes
            # attention for ITS head group locally — the op's (B,T,E) ->
            # (B,T,H,hd) reshape maps an E-split to an H-split — and the
            # output stays 'feat', so the out-projection becomes
            # row-parallel and the whole block costs ONE psum.  Head-count
            # divisibility by the mesh axis is GSPMD's to realize; a
            # non-divisible split degrades to resharding, never to wrong
            # numbers.
            sts = [instate(e) for e in ins]
            out_state = "feat" if sts and all(s == "feat" for s in sts) \
                else "rep"
        elif name in ELEMENTWISE_OPS:
            sts = [instate(e) for e in ins]
            out_state = "feat" if sts and all(s == "feat" for s in sts) \
                else "rep"

        for i in range(node.op.n_outputs(attrs)):
            state[(id(node), i)] = out_state
    return plan
