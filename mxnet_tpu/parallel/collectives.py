"""Cross-process collectives.

Replaces the reference's ps-lite push/pull network path
(`src/kvstore/kvstore_dist.h`) with XLA collectives spanning all processes'
devices.  Used by the dist KVStore facade; inside jitted training steps the
collectives are instead inserted by the SPMD partitioner from sharding
annotations (no explicit calls needed).
"""
from __future__ import annotations

import numpy as np

__all__ = ["global_sum", "barrier"]


def _global_mesh():
    import jax
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()), ("all",))


def global_sum(value):
    """Sum a (process-local) array across all processes; returns the global
    sum replicated locally.  The KVStoreDist Push/Pull analog."""
    import jax
    import jax.numpy as jnp

    if jax.process_count() == 1:
        return value
    from jax.experimental import multihost_utils

    stacked = multihost_utils.process_allgather(value)
    return jnp.sum(jnp.asarray(stacked), axis=0)


def barrier():
    import jax

    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices("mxnet_tpu_kvstore_barrier")
