"""Pipeline parallelism — GPipe-style microbatching over the 'pipe' axis.

The reference's "pipeline" is emergent: ``group2ctx`` places layer blocks
on different devices and the async engine overlaps them
(docs/how_to/model_parallel_lstm.md) — no microbatching, so bubbles are
full-stage.  This module is the leapfrog: an explicit software pipeline
under ``shard_map`` where each device owns ONE stage's weights and
microbatches flow device-to-device via ``lax.ppermute``.

The schedule is the classic GPipe fill-drain: with S stages and M
microbatches, step s ∈ [0, M+S-1) has device d working on microbatch
s - d (when valid).  Activations move one hop per step.  Because the
whole schedule is a differentiable ``lax.scan`` over ``ppermute``,
``jax.grad`` of a pipelined loss yields the reverse pipeline
automatically — no hand-written backward schedule.

Constraint (standard for this primitive): every stage maps activations of
one fixed shape to the same shape (stack projection layers inside a stage
if widths change at its boundary).
"""
from __future__ import annotations

__all__ = ["pipeline_apply", "stack_stage_params"]


def stack_stage_params(per_stage_params):
    """Stack a list of per-stage pytrees into one pytree with a leading
    stage axis — the array you shard on the 'pipe' mesh axis."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, axis=0), *per_stage_params)


def pipeline_apply(stage_fn, stage_params, x, axis_name, num_microbatches,
                   remat=False):
    """Run a stage-per-device pipeline; call under ``shard_map``.

    Args:
      stage_fn: ``(params, activation, mb_id) -> activation`` for ONE
        stage.  ``mb_id`` is the (traced int32) microbatch index this call
        processes — fold it into any stochastic-op RNG key so each
        microbatch draws its own masks; ignore it for deterministic
        stages.
      remat: checkpoint each schedule step — backward recomputes the
        stage body instead of storing its internals for every one of the
        M + S - 1 steps.  This is the scan-compatible answer to 1F1B's
        memory motivation: GPipe + autodiff stores O(steps) per-layer
        activations per device, remat caps the stored state at the step
        BOUNDARIES (one activation per step) and re-runs the stage in
        backward, trading ~1 extra forward for the peak-memory cap.
      stage_params: this device's slice of the stage-stacked params — under
        ``shard_map`` with ``P('pipe', ...)`` in_spec each device receives a
        leading dim of 1; it is squeezed before calling ``stage_fn``.
      x: the full (replicated) batch, microbatched on axis 0:
        shape (num_microbatches, mb_size, ...).  Stage 0 consumes it.
      axis_name: the pipeline mesh axis.
      num_microbatches: M; the schedule runs M + S - 1 steps.

    Returns the pipelined output (M, mb_size, ...), replicated (the last
    stage's results are psum-broadcast so every device returns them).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    m = num_microbatches
    assert x.shape[0] == m, "x must be microbatched: (M, mb, ...)"

    params = jax.tree_util.tree_map(
        lambda p: p.reshape(p.shape[1:]) if p.shape[0] == 1 else p,
        stage_params)

    def probe(mb):
        return jax.eval_shape(lambda p, a: stage_fn(p, a, jnp.int32(0)),
                              params, mb)

    out_sd = probe(jax.eval_shape(lambda v: v[0], x))
    assert tuple(out_sd.shape) == tuple(x.shape[1:]), \
        "stage_fn must preserve the activation shape (got %s from %s)" % (
            out_sd.shape, x.shape[1:])

    steps = m + n - 1
    # carries become device-varying over the pipe axis inside the scan, so
    # the initial values must be marked varying too (shard_map vma typing;
    # identity on jax versions without the vma type system); zeros_like
    # inherits whatever axes x already varies over (e.g. 'data')
    from .compat import pvary

    state0 = pvary(jnp.zeros_like(x[0]), (axis_name,))
    buf0 = pvary(jnp.zeros_like(x), (axis_name,))

    def step(carry, s):
        state, buf = carry
        # stage 0 ingests microbatch s; later stages take the handed-off
        # activation.  Invalid (bubble) slots compute on zeros — wasted
        # FLOPs in the bubble, matching GPipe.
        mb = x[jnp.clip(s, 0, m - 1)]
        inp = jnp.where(idx == 0, mb, state)
        # microbatch id at this device this step: s - idx, valid in [0, m)
        mb_id = s - idx
        pos = jnp.clip(mb_id, 0, m - 1)
        out = stage_fn(params, inp, pos)
        valid = jnp.logical_and(mb_id >= 0, mb_id < m)
        # last stage records its result
        write = jnp.logical_and(valid, idx == n - 1)
        buf = lax.dynamic_update_index_in_dim(
            buf, jnp.where(write, out, buf[pos]), pos, 0)
        # hand off to the next stage
        nxt = lax.ppermute(out, axis_name,
                           [(i, (i + 1) % n) for i in range(n)])
        return (nxt, buf), None

    if remat:
        # prevent_cse=False: safe (and recommended) under lax.scan — the
        # default optimization barriers would block CSE for no benefit
        step = jax.checkpoint(step, prevent_cse=False)
    (_, buf), _ = lax.scan(step, (state0, buf0), jnp.arange(steps))
    # broadcast the last stage's buffer to every device
    buf = jnp.where(idx == n - 1, buf, jnp.zeros_like(buf))
    return lax.psum(buf, axis_name)
