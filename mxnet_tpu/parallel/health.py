"""Worker liveness — heartbeats grown into a reconfiguration protocol.

The reference's elastic story (SURVEY §5 "Failure detection"): ps-lite
heartbeats surface ``get_num_dead_node`` (include/mxnet/kvstore.h:235-244),
restarted workers set ``is_recovery`` to skip the startup barrier
(kvstore_dist.h:39,77), and recovery itself is manual resume from epoch
checkpoints.  The TPU build keeps exactly that surface — a heartbeat
registry over a shared directory (local disk for single-host multi-process,
NFS/GCS-fuse for pods), ``num_dead_nodes``, ``is_recovery`` from the
environment — and grows it into the liveness half of the elastic training
protocol (``mxnet_tpu.elastic``): a :class:`FailureMonitor` polled at step
fences turns heartbeat transitions (a rank going stale, a dead rank
returning) into :class:`ReconfigEvent`\\ s the training loop consumes to
shrink or regrow the mesh's 'data' axis and resume from the last fence
checkpoint.

XLA collectives are synchronous: a dead worker stalls the next collective
rather than corrupting state, so detection's job is to let the training
loop notice at a fence — where nothing is in flight — and reconfigure.
"""
from __future__ import annotations

import atexit
import json
import os
import threading
import time
import weakref

__all__ = ["Heartbeat", "ensure_heartbeat", "stop_heartbeat",
           "num_dead_nodes", "dead_nodes", "is_recovery",
           "FailureMonitor", "ReconfigEvent",
           "DEFAULT_INTERVAL", "DEFAULT_TIMEOUT", "DEFAULT_GRACE"]

DEFAULT_INTERVAL = 2.0     # seconds between stamps
DEFAULT_TIMEOUT = 10.0     # stale-after threshold (ps-lite heartbeat
                           # timeout is likewise a few intervals)
DEFAULT_GRACE = 30.0       # missing-first-stamp allowance for workers that
                           # registered but have not stamped yet

_EPOCH_FILE = ".heartbeat-epoch"


def _stamp_path(directory, rank):
    return os.path.join(directory, "worker-%d.heartbeat" % rank)


def _ensure_epoch(directory):
    """Create-once epoch marker for the heartbeat directory; its mtime is
    the zero point the ``grace`` window for not-yet-stamped workers is
    measured from.  First creator wins (O_EXCL), so every monitor and
    worker agrees on one epoch."""
    path = os.path.join(directory, _EPOCH_FILE)
    try:
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        os.write(fd, b"%f\n" % time.time())
        os.close(fd)
    except FileExistsError:
        pass
    except OSError:
        return None
    try:
        return os.stat(path).st_mtime
    except OSError:
        return None


class Heartbeat:
    """Periodic liveness stamp for one worker process.

    Start on worker startup (the dist KVStore does this automatically when
    ``MXNET_HEARTBEAT_DIR`` is set); the daemon thread rewrites this rank's
    stamp file every ``interval`` seconds.  The thread is stopped by
    :meth:`stop`, by garbage collection (``__del__``), or by the module's
    ``atexit`` hook — an interpreter shutting down mid-fit must not leave
    a zombie stamper making a dead process look alive on shared storage.
    """

    def __init__(self, directory, rank, interval=DEFAULT_INTERVAL):
        self.directory = directory
        self.rank = rank
        self.interval = interval
        self._stop = threading.Event()
        self._thread = None
        os.makedirs(directory, exist_ok=True)
        _ensure_epoch(directory)

    def start(self):
        if self._thread is not None:
            return self
        if self._stop.is_set():
            # restarting after stop(): a fresh event, not a cleared one —
            # the old worker (if any straggler) keeps seeing its stop
            self._stop = threading.Event()
        self.beat()
        # the worker holds only a WEAK reference to this object: a
        # Heartbeat dropped without stop() is collected, its __del__ sets
        # the stop event, and the thread exits at the next tick — a bound
        # self._run target would pin the object (and stamp) forever
        self._thread = threading.Thread(
            target=_stamp_loop,
            args=(weakref.ref(self), self._stop, self.interval),
            daemon=True, name="mxtpu-heartbeat-%d" % self.rank)
        self._thread.start()
        return self

    def beat(self):
        """Write one stamp (atomic rename so readers never see a torn
        file)."""
        path = _stamp_path(self.directory, self.rank)
        tmp = "%s.tmp.%d" % (path, os.getpid())
        with open(tmp, "w") as f:
            json.dump({"rank": self.rank, "time": time.time(),
                       "pid": os.getpid()}, f)
        os.replace(tmp, path)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval + 1)
            self._thread = None

    def __del__(self):
        # best-effort: interpreter teardown may have torn down threading
        # internals already, so never let collection raise
        try:
            self._stop.set()
        except Exception:
            pass


def _stamp_loop(ref, stop, interval):
    """Worker body for Heartbeat.start (module-level so the thread keeps
    no strong reference to the Heartbeat: GC can reclaim it)."""
    while not stop.wait(interval):
        hb = ref()
        if hb is None:
            return  # owner collected without stop(); go stale
        try:
            hb.beat()
        except OSError:
            pass  # shared dir hiccup; next beat retries
        del hb  # don't pin the owner across the sleep


# one stamping thread per (dir, rank) per process, however many KVStores
# are created over it; stop_heartbeat ends it process-wide
_active = {}
_active_lock = threading.Lock()


def _stop_all_heartbeats():
    """atexit: stop every process-wide stamper so a clean interpreter exit
    reads as a (soon-to-be-stale) departure, not an eternal liveness."""
    with _active_lock:
        beats = list(_active.values())
        _active.clear()
    for hb in beats:
        try:
            hb.stop()
        except Exception:
            pass


atexit.register(_stop_all_heartbeats)


def ensure_heartbeat(directory, rank, interval=DEFAULT_INTERVAL):
    """The process-wide heartbeat for (directory, rank), started on first
    use and shared by every dist KVStore."""
    key = (os.path.abspath(directory), rank)
    with _active_lock:
        hb = _active.get(key)
        if hb is None:
            hb = Heartbeat(directory, rank, interval).start()
            _active[key] = hb
        return hb


def stop_heartbeat(directory, rank):
    """Stop (and forget) the process-wide heartbeat for (directory, rank)."""
    key = (os.path.abspath(directory), rank)
    with _active_lock:
        hb = _active.pop(key, None)
    if hb is not None:
        hb.stop()


def dead_nodes(directory, num_workers, timeout=DEFAULT_TIMEOUT, now=None,
               grace=0.0):
    """Ranks considered dead: stamp missing or older than ``timeout``.
    (``get_num_dead_node(node_id, timeout)`` analog, kvstore.h:235-244.)

    ``grace`` protects just-started workers: a rank whose stamp file does
    not exist yet (registered in the roster but first stamp pending) is
    NOT reported dead within ``grace`` seconds of the heartbeat
    directory's epoch marker.  A stamp that exists but is stale is always
    dead — grace covers startup, not silence."""
    now = time.time() if now is None else now
    epoch = None
    if grace > 0:
        epoch = _ensure_epoch(directory) if os.path.isdir(directory) else None
    dead = []
    for rank in range(num_workers):
        path = _stamp_path(directory, rank)
        try:
            with open(path) as f:
                stamp = json.load(f)
            if now - stamp["time"] > timeout:
                dead.append(rank)
        except FileNotFoundError:
            if epoch is not None and now - epoch <= grace:
                continue  # first stamp still pending; within grace
            dead.append(rank)
        except (OSError, ValueError, KeyError):
            dead.append(rank)
    return dead


def num_dead_nodes(directory, num_workers, timeout=DEFAULT_TIMEOUT):
    return len(dead_nodes(directory, num_workers, timeout))


def is_recovery():
    """Whether this worker is a restart (skip startup-only work like the
    initial barrier — kvstore_dist.h:39,77 ``is_recovery`` branches)."""
    return os.environ.get("MXNET_IS_RECOVERY", "0") not in ("", "0",
                                                            "false", "False")


class ReconfigEvent:
    """A liveness transition the training loop must react to.

    ``dead`` is the full current dead set; ``newly_dead`` / ``returned``
    are the deltas since the previous poll (a returned rank triggers
    regrow, a newly dead one triggers shrink)."""

    def __init__(self, dead, newly_dead, returned):
        self.dead = sorted(dead)
        self.newly_dead = sorted(newly_dead)
        self.returned = sorted(returned)

    @property
    def kind(self):
        return "shrink" if self.newly_dead else "regrow"

    def __repr__(self):
        return ("ReconfigEvent(kind=%s, dead=%s, newly_dead=%s, returned=%s)"
                % (self.kind, self.dead, self.newly_dead, self.returned))


class FailureMonitor:
    """Poll the heartbeat directory and report liveness TRANSITIONS.

    The elastic training loop calls :meth:`poll` at step fences (cheap:
    ``num_workers`` stat/read calls, no device work).  The first poll
    establishes the baseline dead set; every later poll returns a
    :class:`ReconfigEvent` when the set changed — rank(s) newly stale
    (shrink the mesh) or previously-dead rank(s) stamping again (regrow) —
    and None when nothing moved.  ``my_rank`` is never reported dead to
    itself: a worker that cannot see its own stamp has a storage problem,
    not a liveness one.
    """

    def __init__(self, directory, num_workers, my_rank=0,
                 timeout=None, grace=None):
        from .. import config as _config

        self.directory = directory
        self.num_workers = num_workers
        self.my_rank = my_rank
        self.timeout = float(_config.get("MXNET_ELASTIC_TIMEOUT")
                             if timeout is None else timeout)
        self.grace = float(_config.get("MXNET_ELASTIC_GRACE")
                           if grace is None else grace)
        self.current_dead = None   # unknown until the first poll
        os.makedirs(directory, exist_ok=True)
        _ensure_epoch(directory)

    def poll(self, now=None):
        dead = set(dead_nodes(self.directory, self.num_workers,
                              timeout=self.timeout, now=now,
                              grace=self.grace))
        dead.discard(self.my_rank)
        from .. import obs as _obs

        _obs.registry.gauge(
            "mx_dead_workers",
            "ranks the failure monitor currently reads as dead").set(
                len(dead))
        if self.current_dead is None:
            # the first poll is NOT a free pass: a rank that died between
            # launch and the first fence (e.g. while step 0 compiled) must
            # shrink now, not become an invisible baseline whose eventual
            # return fires a regrow for a shrink that never happened.
            # Workers that merely haven't stamped yet are covered by the
            # grace window, not by baseline adoption.
            self.current_dead = dead
            if dead:
                event = ReconfigEvent(dead, dead, set())
                _obs.instant("heartbeat_" + event.kind, cat="elastic",
                             args={"dead": event.dead,
                                   "newly_dead": event.newly_dead,
                                   "returned": event.returned})
                return event
            return None
        if dead == self.current_dead:
            return None
        prev, self.current_dead = self.current_dead, dead
        event = ReconfigEvent(dead, dead - prev, prev - dead)
        # the liveness transition itself (the controller marks the mesh
        # re-form separately — a transition can also be observed by
        # monitors outside a training loop)
        _obs.instant("heartbeat_" + event.kind, cat="elastic",
                     args={"dead": event.dead,
                           "newly_dead": event.newly_dead,
                           "returned": event.returned})
        return event
