"""Worker failure detection — the ps-lite heartbeat analog.

The reference's elastic story (SURVEY §5 "Failure detection"): ps-lite
heartbeats surface ``get_num_dead_node`` (include/mxnet/kvstore.h:235-244),
restarted workers set ``is_recovery`` to skip the startup barrier
(kvstore_dist.h:39,77), and recovery itself is manual resume from epoch
checkpoints.  The TPU build keeps exactly that surface: a heartbeat
registry over a shared directory (local disk for single-host multi-process,
NFS/GCS-fuse for pods), ``num_dead_nodes``, and ``is_recovery`` from the
environment (``MXNET_IS_RECOVERY``, matching the reference's
``DMLC_PS_VAN_START`` recovery flag in spirit).

XLA collectives are synchronous: a dead worker stalls the next collective
rather than corrupting state, so detection's job is to let the launcher /
training loop notice and restart from the last checkpoint — the same
recovery contract as the reference.
"""
from __future__ import annotations

import json
import os
import threading
import time

__all__ = ["Heartbeat", "ensure_heartbeat", "stop_heartbeat",
           "num_dead_nodes", "dead_nodes", "is_recovery",
           "DEFAULT_INTERVAL", "DEFAULT_TIMEOUT"]

DEFAULT_INTERVAL = 2.0     # seconds between stamps
DEFAULT_TIMEOUT = 10.0     # stale-after threshold (ps-lite heartbeat
                           # timeout is likewise a few intervals)


def _stamp_path(directory, rank):
    return os.path.join(directory, "worker-%d.heartbeat" % rank)


class Heartbeat:
    """Periodic liveness stamp for one worker process.

    Start on worker startup (the dist KVStore does this automatically when
    ``MXNET_HEARTBEAT_DIR`` is set); the daemon thread rewrites this rank's
    stamp file every ``interval`` seconds.
    """

    def __init__(self, directory, rank, interval=DEFAULT_INTERVAL):
        self.directory = directory
        self.rank = rank
        self.interval = interval
        self._stop = threading.Event()
        self._thread = None
        os.makedirs(directory, exist_ok=True)

    def start(self):
        if self._thread is not None:
            return self
        self.beat()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="mxtpu-heartbeat-%d" % self.rank)
        self._thread.start()
        return self

    def beat(self):
        """Write one stamp (atomic rename so readers never see a torn
        file)."""
        path = _stamp_path(self.directory, self.rank)
        tmp = "%s.tmp.%d" % (path, os.getpid())
        with open(tmp, "w") as f:
            json.dump({"rank": self.rank, "time": time.time(),
                       "pid": os.getpid()}, f)
        os.replace(tmp, path)

    def _run(self):
        while not self._stop.wait(self.interval):
            try:
                self.beat()
            except OSError:
                pass  # shared dir hiccup; next beat retries

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval + 1)
            self._thread = None


# one stamping thread per (dir, rank) per process, however many KVStores
# are created over it; stop_heartbeat ends it process-wide
_active = {}
_active_lock = threading.Lock()


def ensure_heartbeat(directory, rank, interval=DEFAULT_INTERVAL):
    """The process-wide heartbeat for (directory, rank), started on first
    use and shared by every dist KVStore."""
    key = (os.path.abspath(directory), rank)
    with _active_lock:
        hb = _active.get(key)
        if hb is None:
            hb = Heartbeat(directory, rank, interval).start()
            _active[key] = hb
        return hb


def stop_heartbeat(directory, rank):
    """Stop (and forget) the process-wide heartbeat for (directory, rank)."""
    key = (os.path.abspath(directory), rank)
    with _active_lock:
        hb = _active.pop(key, None)
    if hb is not None:
        hb.stop()


def dead_nodes(directory, num_workers, timeout=DEFAULT_TIMEOUT, now=None):
    """Ranks considered dead: stamp missing or older than ``timeout``.
    (``get_num_dead_node(node_id, timeout)`` analog, kvstore.h:235-244.)"""
    now = time.time() if now is None else now
    dead = []
    for rank in range(num_workers):
        path = _stamp_path(directory, rank)
        try:
            with open(path) as f:
                stamp = json.load(f)
            if now - stamp["time"] > timeout:
                dead.append(rank)
        except (OSError, ValueError, KeyError):
            dead.append(rank)
    return dead


def num_dead_nodes(directory, num_workers, timeout=DEFAULT_TIMEOUT):
    return len(dead_nodes(directory, num_workers, timeout))


def is_recovery():
    """Whether this worker is a restart (skip startup-only work like the
    initial barrier — kvstore_dist.h:39,77 ``is_recovery`` branches)."""
    return os.environ.get("MXNET_IS_RECOVERY", "0") not in ("", "0",
                                                            "false", "False")
