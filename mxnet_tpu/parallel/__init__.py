"""Parallelism utilities: device meshes, collectives, multi-host launch.

TPU-native replacement for the reference's distributed stack (SURVEY §2.4/
§2.5): ps-lite/ZMQ + Comm reduce become XLA collectives over an ICI/DCN
mesh.  `tools/launch.py` (dmlc-tracker ssh/mpi) becomes
`mxnet_tpu.parallel.launch.init()` → jax.distributed.
"""
from . import collectives
from . import compat
from .compat import shard_map
from .mesh import build_mesh, data_parallel_mesh, MeshConfig
from . import launch
from . import ring
from .ring import ring_attention
from . import pipeline
from .pipeline import pipeline_apply, stack_stage_params
from . import health

__all__ = ["collectives", "compat", "shard_map", "build_mesh",
           "data_parallel_mesh", "MeshConfig", "launch", "ring",
           "ring_attention", "pipeline", "pipeline_apply",
           "stack_stage_params", "health"]
