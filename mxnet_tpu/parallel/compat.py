"""jax version compatibility for the explicit-collective parallel paths.

The framework's shard_map regions (ring attention, pipeline schedules) are
written against the current jax API — ``jax.shard_map`` with the
``check_vma`` relaxation knob.  Older jax releases (< 0.5) ship the same
machinery as ``jax.experimental.shard_map.shard_map`` with the knob named
``check_rep``.  One wrapper here keeps every call site on the new spelling
so nothing else in the tree branches on the jax version.
"""
from __future__ import annotations

__all__ = ["shard_map", "pvary"]

_IMPL = None  # (callable, vma_kwarg_name) resolved once


def _resolve():
    global _IMPL
    if _IMPL is None:
        try:
            from jax import shard_map as sm  # jax >= 0.5
            _IMPL = (sm, "check_vma")
        except ImportError:
            from jax.experimental.shard_map import shard_map as sm
            _IMPL = (sm, "check_rep")
    return _IMPL


def shard_map(f, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` across jax versions.

    ``check_vma=None`` keeps the backend's default; an explicit bool maps
    onto whichever knob the installed jax spells it as (``check_vma`` new,
    ``check_rep`` old — both gate the same replication/varying-axes typing
    that e.g. pallas interpreter mode cannot satisfy).
    """
    sm, knob = _resolve()
    kwargs = {} if check_vma is None else {knob: check_vma}
    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)


def pvary(x, axis_names):
    """Mark ``x`` as device-varying over mesh axes (vma typing).

    New jax spells this ``lax.pcast(..., to="varying")``; the releases
    that introduced vma typing spell it ``lax.pvary``; older releases
    have no varying-mesh-axes type system, where replicated and varying
    values unify — the identity is exactly right there.
    """
    from jax import lax

    pcast = getattr(lax, "pcast", None)
    if pcast is not None:
        return pcast(x, tuple(axis_names), to="varying")
    pv = getattr(lax, "pvary", None)
    if pv is not None:
        return pv(x, tuple(axis_names))
    return x
