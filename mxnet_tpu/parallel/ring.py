"""Ring attention — explicit-collective sequence parallelism.

The memory-optimal long-context path (vs the GSPMD all-gather path the
``dot_product_attention`` op gets from seq-axis input sharding): each
device holds one sequence block of Q, K, V; K/V blocks rotate around the
``seq`` mesh axis via ``lax.ppermute`` while each device accumulates its
queries' attention over every block with streaming (log-sum-exp) softmax —
flash-attention numerics, so no device ever materializes the full
(T, T) score matrix or the full K/V.

No reference analog (2017-era MXNet handles long sequences by bucketing;
SURVEY §2.5) — this is the leapfrog path the SURVEY §7 north star names.

Usage (under shard_map over a mesh with a ``seq`` axis):

    out = shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name="seq"),
        mesh=mesh,
        in_specs=(P(None, "seq", None),) * 3,
        out_specs=P(None, "seq", None),
    )(q, k, v)
"""
from __future__ import annotations

import numpy as np

__all__ = ["ring_attention", "dense_attention"]


def dense_attention(q, k, v, num_heads=1, causal=False, scale=None):
    """Single-device reference: the ``dot_product_attention`` op's own
    kernel (one copy of the numerics — ``ops.attention.sdpa``)."""
    import jax.numpy as jnp

    from ..ops.attention import sdpa

    return sdpa(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                num_heads=num_heads, causal=causal, scale=scale)


def ring_attention(q, k, v, axis_name, num_heads=1, causal=False,
                   scale=None):
    """Blockwise ring attention over the ``axis_name`` mesh axis.

    Args are the LOCAL sequence blocks (B, T_local, E).  Device i starts
    with K/V block i; each of the ``n`` ring steps attends Q_local against
    the currently-held K/V block, then rotates K/V to the next device with
    ``lax.ppermute``.  A running (max, sum, acc) triple merges blocks with
    exact flash-attention numerics, and causal masking uses the global
    block offsets, so the result equals dense attention on the gathered
    sequence.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, t_local, e = q.shape
    hd = e // num_heads
    ev = v.shape[2] // num_heads
    scale = scale or 1.0 / np.sqrt(hd)

    qh = q.reshape(b, t_local, num_heads, hd) * scale
    kh = k.reshape(b, t_local, num_heads, hd)
    vh = v.reshape(b, t_local, num_heads, ev)

    # flash-attention accumulator state in fp32 (bf16-safe streaming sums)
    neg_inf = jnp.finfo(jnp.float32).min
    m0 = jnp.full((b, num_heads, t_local), neg_inf, jnp.float32)
    l0 = jnp.zeros((b, num_heads, t_local), jnp.float32)
    acc0 = jnp.zeros((b, t_local, num_heads, ev), jnp.float32)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, r):
        m, l, acc, kb, vb = carry
        # the K/V block currently held started at device (idx - r) mod n
        src = (idx - r) % n
        logits = jnp.einsum("bqhd,bkhd->bhqk", qh, kb).astype(jnp.float32)
        if causal:
            # global positions: queries idx*T+iq, keys src*T+ik
            iq = idx * t_local + jnp.arange(t_local)
            ik = src * t_local + jnp.arange(t_local)
            mask = iq[:, None] >= ik[None, :]
            logits = jnp.where(mask[None, None], logits, neg_inf)
        blk_m = jnp.max(logits, axis=-1)
        new_m = jnp.maximum(m, blk_m)
        # guard fully-masked rows: exp(neg_inf - neg_inf) must stay 0
        safe_new_m = jnp.where(new_m == neg_inf, 0.0, new_m)
        correction = jnp.where(m == neg_inf, 0.0, jnp.exp(m - safe_new_m))
        p = jnp.exp(logits - safe_new_m[..., None])
        p = jnp.where(logits == neg_inf, 0.0, p)
        new_l = l * correction + p.sum(-1)
        new_acc = acc * correction.transpose(0, 2, 1)[..., None] + \
            jnp.einsum("bhqk,bkhe->bqhe", p, vb.astype(jnp.float32))
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return (new_m, new_l, new_acc, kb, vb), None

    carry = (m0, l0, acc0, kh, vh)
    for r in range(n):            # n is a static mesh size: unrolled ring
        carry, _ = step(carry, r)
    m, l, acc, _, _ = carry
    denom = jnp.where(l == 0.0, 1.0, l)
    out = (acc / denom.transpose(0, 2, 1)[..., None]).astype(v.dtype)
    return out.reshape(b, t_local, v.shape[2])
