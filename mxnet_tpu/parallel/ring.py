"""Ring attention — explicit-collective sequence parallelism.

The memory-optimal long-context path (vs the GSPMD all-gather path the
``dot_product_attention`` op gets from seq-axis input sharding): each
device holds one sequence block of Q, K, V; K/V blocks rotate around the
``seq`` mesh axis via ``lax.ppermute`` while each device accumulates its
queries' attention over every block with streaming (log-sum-exp) softmax —
flash-attention numerics, so no device ever materializes the full
(T, T) score matrix or the full K/V.

No reference analog (2017-era MXNet handles long sequences by bucketing;
SURVEY §2.5) — this is the leapfrog path the SURVEY §7 north star names.

Usage (under shard_map over a mesh with a ``seq`` axis):

    out = shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name="seq"),
        mesh=mesh,
        in_specs=(P(None, "seq", None),) * 3,
        out_specs=P(None, "seq", None),
    )(q, k, v)

Ring×TP composition (the full (data, seq, model) mesh): attention is
per-head independent, so Megatron head-group sharding on 'model' rides
along by additionally splitting the embed dim in the specs —
``P(None, "seq", "model")`` — and passing ``head_axis="model"`` with the
global head count; each model shard then rotates only its own K/V slice.
``dot_product_attention``'s mesh dispatch (ops/attention.py) builds
exactly this region.

Schedules: ring attention's premise (Liu et al. 2023) is that the K/V
rotation hides behind the per-hop attention compute.  The *serial*
schedule (``double_buffer=False``) issues each hop's ppermute after the
hop's kernel in program order; the *double-buffered* schedule (the
default) issues the ppermute fetching hop r+1's K/V — and, in the
backward ring, the traveling dK/dV accumulator rotation carrying hops
<= r-1 — BEFORE invoking hop r's kernel on the already-resident buffer,
so the collective has no data dependence on the hop's compute and XLA
backends with async collectives (TPU: ``collective-permute-start`` /
``-done`` pairs) overlap the wire time with the Pallas kernel.  Both
schedules visit blocks in the same order and merge (m, l, acc) partials
in the same sequence, so they are bit-identical — asserted in
tests/test_seq_parallel.py.  The final hop's K/V rotation is elided in
every ring (the rotated buffers would be discarded), so an n-hop ring
moves n-1 K/V slices per tensor.
"""
from __future__ import annotations

import numpy as np

__all__ = ["ring_attention", "dense_attention"]

# which per-hop compute the last ring_attention trace used ("flash" |
# "streaming") — path-selection tripwire, same pattern as
# ops.attention.PATH_TAKEN
RING_PATH = {"last": None}

# interpreter-mode warn-once latch: use_flash=True resolving to Pallas
# interpreter mode warns once per PROCESS, not once per trace — jit
# retraces (new shapes, new meshes) would otherwise repeat it dozens of
# times per run.  Tests reset the latch to re-arm the warning.
_INTERPRET_WARNED = {"done": False}


def dense_attention(q, k, v, num_heads=1, causal=False, scale=None,
                    num_kv_heads=0):
    """Single-device reference: the ``dot_product_attention`` op's own
    kernel (one copy of the numerics — ``ops.attention.sdpa``)."""
    import jax.numpy as jnp

    from ..ops.attention import sdpa

    return sdpa(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                num_heads=num_heads, causal=causal, scale=scale,
                num_kv_heads=num_kv_heads)


def ring_attention(q, k, v, axis_name, num_heads=1, causal=False,
                   scale=None, use_flash=None, interpret=None,
                   head_axis=None, double_buffer=None, num_kv_heads=0):
    """Blockwise ring attention over the ``axis_name`` mesh axis.

    Args are the LOCAL sequence blocks (B, T_local, E_local).  Device i
    starts with K/V block i; each of the ``n`` ring steps attends Q_local
    against the currently-held K/V block, then rotates K/V to the next
    device with ``lax.ppermute``.  A running (max, sum, acc) triple merges
    blocks with exact flash-attention numerics, and causal masking uses
    the global block offsets, so the result equals dense attention on the
    gathered sequence.

    ``head_axis`` composes the ring with Megatron tensor parallelism
    (ring×TP): attention is per-head independent, so when the embed dim is
    additionally sharded over a 'model' mesh axis in whole head groups
    (E_local = E / model, heads contiguous hd-wide slices of E), pass
    ``head_axis='model'`` with the GLOBAL ``num_heads`` — the per-shard
    head count is derived from the axis size, and every ppermute moves
    only this shard's (B, T_local, E/model) K/V slice: collectives shrink
    by the model degree while the 'seq' ring math is untouched (the same
    holds for the custom-VJP backward ring, whose dK/dV accumulators are
    sliced identically).

    Per-hop compute dispatches to the Pallas flash kernel
    (``ops.pallas_attention``) when the local block fits it (T_local
    tile-aligned, head_dim lane-aligned) — the fused kernel IS the
    distributed path, mirroring the reference's cuDNN-RNN-everywhere
    precedent (src/operator/cudnn_rnn-inl.h) — and falls back to jnp
    streaming math otherwise.  ``use_flash`` forces the choice;
    ``interpret`` runs the kernels in interpreter mode (CPU tests).

    ``double_buffer`` selects the communication schedule: True (the
    default, via ``MXNET_RING_DOUBLE_BUFFER``) issues each hop's K/V
    fetch — and the backward ring's traveling dK/dV rotation — before the
    hop's kernel so async-collective backends overlap wire time with
    compute; False restores the serial issue order for A/B measurement.
    Both schedules are bit-identical (same block visit order, same
    (m, l, acc) merge sequence) and both elide the final hop's discarded
    K/V rotation.

    Measured on-chip (benchmarks/ROOFLINE.md round-5): flash wins fwd at
    every block size and fwd+bwd from T_local >= 4096 (1.3x), and is the
    ONLY trainable path at T_local = 8192 (the streaming backward's
    rematerialized (T_local, T_local) f32 block logits exceed HBM).  At
    T_local = 2048 streaming trains ~1.2x faster — pass use_flash=False
    there if training short blocks on a wide mesh.
    """
    import jax
    from jax import lax

    from .. import config as _config

    from ..ops.attention import check_head_groups

    b, t_local, e = q.shape
    if head_axis is not None:
        # head-group sharding: axis sizes are static, so psum(1, axis)
        # folds to a Python int and num_heads becomes the per-shard count.
        # Grouped K/V shard by the SAME axis at kv-head granularity, so
        # both counts must divide — loud ValueErrors naming the dims, not
        # a reshape trace error inside the shard_map region.
        head_par = lax.psum(1, head_axis)
        if num_heads % head_par != 0:
            raise ValueError(
                "ring_attention: num_heads=%d not divisible by %r axis "
                "size %d" % (num_heads, head_axis, head_par))
        kvh_global = int(num_kv_heads) or int(num_heads)
        if kvh_global % head_par != 0:
            raise ValueError(
                "ring_attention: num_kv_heads=%d not divisible by %r "
                "axis size %d" % (kvh_global, head_axis, head_par))
        num_heads //= head_par
        num_kv_heads = kvh_global // head_par
    num_kv_heads, group = check_head_groups(
        num_heads, num_kv_heads, e, v.shape[2], k.shape[2],
        where="ring_attention")
    hd = e // num_heads
    ev = v.shape[2] // num_kv_heads
    scale = scale or 1.0 / np.sqrt(hd)
    if double_buffer is None:
        double_buffer = _config.get("MXNET_RING_DOUBLE_BUFFER")

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
        if use_flash and interpret and not _INTERPRET_WARNED["done"]:
            # use_flash=True on a non-TPU backend silently resolves to
            # Pallas interpreter mode — every ring hop runs orders of
            # magnitude slower than the compiled kernel.  Tests opt in
            # with an explicit interpret=True; anything else should hear
            # about it (once per process — see _INTERPRET_WARNED).
            import warnings

            _INTERPRET_WARNED["done"] = True
            warnings.warn(
                "ring_attention(use_flash=True) on the %r backend resolves"
                " to Pallas interpreter mode (orders of magnitude slower "
                "than the compiled TPU kernel); pass interpret=True to "
                "acknowledge, or use_flash=False for the jnp streaming "
                "path" % jax.default_backend(), RuntimeWarning,
                stacklevel=2)
    if use_flash is None:
        # auto: the real kernel on TPU whenever the local block fits it;
        # interpreter-mode emulation is opt-in (tests), not a default.
        # Eligibility delegates to the kernel's own gate (ONE copy of the
        # rule); the ring additionally requires ev == hd (the kernel's
        # folded blocks assume one value width)
        from ..ops import pallas_attention as _pa

        use_flash = (jax.default_backend() == "tpu" and ev == hd
                     and _pa.supported(q.shape, k.shape, causal, num_heads,
                                       num_kv_heads=num_kv_heads))
    if use_flash:
        RING_PATH["last"] = "flash"
        return _ring_flash_fn(axis_name, bool(causal), float(scale),
                              bool(interpret), num_heads,
                              bool(double_buffer),
                              num_kv_heads)(q, k, v)
    RING_PATH["last"] = "streaming"

    if group == 1:
        # ungrouped path kept verbatim (G=1 bit-identity)
        qh = q.reshape(b, t_local, num_heads, hd) * scale
        kh = k.reshape(b, t_local, num_heads, hd)
        vh = v.reshape(b, t_local, num_heads, ev)
        out = _ring_stream(qh, kh, vh, axis_name, causal, double_buffer)
        return out.astype(v.dtype).reshape(b, t_local, v.shape[2])
    # grouped: only the (B, T_local, H_kv*hd) K/V blocks enter the ring —
    # every ppermute moves G× fewer bytes (asserted by the hlo_stats
    # collective-byte budget in tests/test_seq_parallel.py)
    qh = q.reshape(b, t_local, num_kv_heads, group, hd) * scale
    kh = k.reshape(b, t_local, num_kv_heads, hd)
    vh = v.reshape(b, t_local, num_kv_heads, ev)
    out = _ring_stream_grouped(qh, kh, vh, axis_name, causal,
                               double_buffer)
    return out.astype(v.dtype).reshape(b, t_local, num_heads * ev)


def _ring_stream(qh, kh, vh, axis_name, causal, double_buffer):
    """The jnp streaming ring: per-hop blockwise attention with a running
    (max, sum, acc) flash merge, differentiable by plain autodiff.

    Inputs are head-split (B, T_local, H, hd/ev) with ``qh`` pre-scaled;
    returns the normalized (B, T_local, H, ev) float32 output.  The hop
    loop is unrolled (n is a static mesh size), with the communication
    schedule chosen by ``double_buffer`` — see ``ring_attention``.
    """
    import jax.numpy as jnp
    from jax import lax

    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, t_local, num_heads, _ = qh.shape
    ev = vh.shape[3]

    # flash-attention accumulator state in fp32 (bf16-safe streaming sums)
    neg_inf = jnp.finfo(jnp.float32).min
    m0 = jnp.full((b, num_heads, t_local), neg_inf, jnp.float32)
    l0 = jnp.zeros((b, num_heads, t_local), jnp.float32)
    acc0 = jnp.zeros((b, t_local, num_heads, ev), jnp.float32)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def rotate(kb, vb):
        return (lax.ppermute(kb, axis_name, perm),
                lax.ppermute(vb, axis_name, perm))

    def step(carry, r):
        m, l, acc, kb, vb = carry
        last = r == n - 1
        # double-buffered: kick off the fetch of hop r+1's K/V before this
        # hop's kernel touches the resident buffer — the ppermute depends
        # only on kb/vb, never on the hop's compute, so async backends
        # overlap it.  The final hop's rotation is elided either way (the
        # rotated buffers would be discarded).
        nxt = rotate(kb, vb) if double_buffer and not last else None
        # the K/V block currently held started at device (idx - r) mod n
        src = (idx - r) % n
        logits = jnp.einsum("bqhd,bkhd->bhqk", qh, kb).astype(jnp.float32)
        if causal:
            # global positions: queries idx*T+iq, keys src*T+ik
            iq = idx * t_local + jnp.arange(t_local)
            ik = src * t_local + jnp.arange(t_local)
            mask = iq[:, None] >= ik[None, :]
            logits = jnp.where(mask[None, None], logits, neg_inf)
        blk_m = jnp.max(logits, axis=-1)
        new_m = jnp.maximum(m, blk_m)
        # guard fully-masked rows: exp(neg_inf - neg_inf) must stay 0
        safe_new_m = jnp.where(new_m == neg_inf, 0.0, new_m)
        correction = jnp.where(m == neg_inf, 0.0, jnp.exp(m - safe_new_m))
        p = jnp.exp(logits - safe_new_m[..., None])
        p = jnp.where(logits == neg_inf, 0.0, p)
        new_l = l * correction + p.sum(-1)
        new_acc = acc * correction.transpose(0, 2, 1)[..., None] + \
            jnp.einsum("bhqk,bkhe->bqhe", p, vb.astype(jnp.float32))
        if not last:
            kb, vb = rotate(kb, vb) if nxt is None else nxt
        return (new_m, new_l, new_acc, kb, vb), None

    carry = (m0, l0, acc0, kh, vh)
    for r in range(n):            # n is a static mesh size: unrolled ring
        carry, _ = step(carry, r)
    m, l, acc, _, _ = carry
    denom = jnp.where(l == 0.0, 1.0, l)
    return acc / denom.transpose(0, 2, 1)[..., None]


def _ring_stream_grouped(qh, kh, vh, axis_name, causal, double_buffer):
    """Grouped-query twin of :func:`_ring_stream`: ``qh`` is head-split
    (B, T_local, H_kv, G, hd) (pre-scaled), K/V stay at their physical
    kv width (B, T_local, H_kv, hd/ev) — each ring hop rotates only the
    H_kv-wide blocks and q-head (h, g) scores kv-head h inside the
    einsum, never through a materialized broadcast.  Returns the
    normalized (B, T_local, H_kv, G, ev) float32 output."""
    import jax.numpy as jnp
    from jax import lax

    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, t_local, kv_heads, group, _ = qh.shape
    ev = vh.shape[3]

    neg_inf = jnp.finfo(jnp.float32).min
    m0 = jnp.full((b, kv_heads, group, t_local), neg_inf, jnp.float32)
    l0 = jnp.zeros((b, kv_heads, group, t_local), jnp.float32)
    acc0 = jnp.zeros((b, t_local, kv_heads, group, ev), jnp.float32)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def rotate(kb, vb):
        return (lax.ppermute(kb, axis_name, perm),
                lax.ppermute(vb, axis_name, perm))

    def step(carry, r):
        m, l, acc, kb, vb = carry
        last = r == n - 1
        nxt = rotate(kb, vb) if double_buffer and not last else None
        src = (idx - r) % n
        logits = jnp.einsum("bqhgd,bkhd->bhgqk", qh,
                            kb).astype(jnp.float32)
        if causal:
            iq = idx * t_local + jnp.arange(t_local)
            ik = src * t_local + jnp.arange(t_local)
            mask = iq[:, None] >= ik[None, :]
            logits = jnp.where(mask[None, None, None], logits, neg_inf)
        blk_m = jnp.max(logits, axis=-1)
        new_m = jnp.maximum(m, blk_m)
        safe_new_m = jnp.where(new_m == neg_inf, 0.0, new_m)
        correction = jnp.where(m == neg_inf, 0.0, jnp.exp(m - safe_new_m))
        p = jnp.exp(logits - safe_new_m[..., None])
        p = jnp.where(logits == neg_inf, 0.0, p)
        new_l = l * correction + p.sum(-1)
        new_acc = acc * correction.transpose(0, 3, 1, 2)[..., None] + \
            jnp.einsum("bhgqk,bkhe->bqhge", p, vb.astype(jnp.float32))
        if not last:
            kb, vb = rotate(kb, vb) if nxt is None else nxt
        return (new_m, new_l, new_acc, kb, vb), None

    carry = (m0, l0, acc0, kh, vh)
    for r in range(n):
        carry, _ = step(carry, r)
    m, l, acc, _, _ = carry
    denom = jnp.where(l == 0.0, 1.0, l)
    return acc / denom.transpose(0, 3, 1, 2)[..., None]


_RING_FLASH_CACHE = {}


def _ring_flash_fn(axis_name, causal, scale, interpret, num_heads,
                   double_buffer, num_kv_heads=0):
    """custom_vjp-wrapped flash ring: forward runs a ring of forward flash
    kernels whose per-block (out, lse) partials merge with logsumexp
    weights; backward runs a second ring of the backward kernels using the
    GLOBAL lse/delta (the true softmax denominators), with dK/dV
    accumulators rotating in lockstep with their K/V blocks so each
    block's gradient arrives home after n hops.  Per hop, ``lax.switch``
    picks full / causal-diagonal / skip compute from the block's global
    offset — the causal skip saves the same ~2x the kernel's internal
    block skipping does, one ring-hop coarser.

    ``double_buffer`` reorders the communication issue only (see
    ``ring_attention``): forward prefetches hop r+1's K/V before hop r's
    kernel; backward additionally folds hop r-1's dK/dV contribution and
    rotates the traveling accumulators at the START of iteration r, so
    the rotation depends on the previous hop's kernel, not the current
    one — the only dataflow ordering under which XLA can overlap the
    accumulator wire time.  Contribution r is still folded before
    rotation r+1 and rotated exactly n-r times, so serial and
    double-buffered gradients are bit-identical.

    ``num_kv_heads`` < ``num_heads`` runs the grouped (GQA) ring: K/V
    fold to (B*H_kv, T, hd) — so every ppermute (K/V forward, traveling
    dK/dV backward) moves G× fewer bytes — and the hop kernels map
    q-head ``h`` onto kv block ``h // G`` in their BlockSpec index maps
    (``groups=`` in ``pa._fwd_call``/``_bwd_call``), accumulating dK/dV
    at the grouped width in-kernel."""
    key = (axis_name, causal, scale, interpret, num_heads, double_buffer,
           num_kv_heads)
    hit = _RING_FLASH_CACHE.get(key)
    if hit is not None:
        return hit

    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..ops import pallas_attention as pa

    kv_heads = int(num_kv_heads) or int(num_heads)
    group = num_heads // kv_heads

    def fold(x, b, t, h, hd):
        return x.reshape(b, t, h, hd).transpose(0, 2, 1, 3) \
            .reshape(b * h, t, hd)

    def unfold(x, b, t, h, hd):
        return x.reshape(b, h, t, hd).transpose(0, 2, 1, 3) \
            .reshape(b, t, h * hd)

    def fwd_pass(q, k, v):
        n = lax.psum(1, axis_name)
        idx = lax.axis_index(axis_name)
        b, tl, e = q.shape
        hd = e // num_heads
        qf = fold(q, b, tl, num_heads, hd)
        kb = fold(k, b, tl, kv_heads, hd)
        vb = fold(v, b, tl, kv_heads, hd)
        bh = b * num_heads
        perm = [(i, (i + 1) % n) for i in range(n)]
        neg_inf = jnp.float32(-jnp.inf)

        def rotate(kk, vv):
            return (lax.ppermute(kk, axis_name, perm),
                    lax.ppermute(vv, axis_name, perm))

        def full_blk(args):
            qq, kk, vv = args
            ob, lb = pa._fwd_call(qq, kk, vv, scale, False, interpret,
                                  with_lse=True, groups=group)
            return ob.astype(jnp.float32), lb[:, :, 0]

        def diag_blk(args):
            qq, kk, vv = args
            ob, lb = pa._fwd_call(qq, kk, vv, scale, True, interpret,
                                  with_lse=True, groups=group)
            return ob.astype(jnp.float32), lb[:, :, 0]

        def skip_blk(args):
            return (jnp.zeros((bh, tl, hd), jnp.float32),
                    jnp.full((bh, tl), neg_inf, jnp.float32))

        # streaming merge state: o_w = sum_b out_b * exp(lse_b - m),
        # l_w = sum_b exp(lse_b - m), m = running max of block lses
        o_w = jnp.zeros((bh, tl, hd), jnp.float32)
        l_w = jnp.zeros((bh, tl), jnp.float32)
        m = jnp.full((bh, tl), neg_inf, jnp.float32)
        for r in range(n):
            last = r == n - 1
            # prefetch hop r+1's K/V before this hop's kernel (final hop
            # elided — the rotated buffers would be discarded)
            nxt = rotate(kb, vb) if double_buffer and not last else None
            src = (idx - r) % n
            if causal:
                case = jnp.where(src < idx, 0, jnp.where(src == idx, 1, 2))
                ob, lseb = lax.switch(case, [full_blk, diag_blk, skip_blk],
                                      (qf, kb, vb))
            else:
                ob, lseb = full_blk((qf, kb, vb))
            m_new = jnp.maximum(m, lseb)
            m_safe = jnp.where(m_new == neg_inf, 0.0, m_new)
            c = jnp.where(m == neg_inf, 0.0, jnp.exp(m - m_safe))
            cb = jnp.where(lseb == neg_inf, 0.0, jnp.exp(lseb - m_safe))
            o_w = o_w * c[..., None] + ob * cb[..., None]
            l_w = l_w * c + cb
            m = m_new
            if not last:
                kb, vb = rotate(kb, vb) if nxt is None else nxt
        denom = jnp.where(l_w == 0.0, 1.0, l_w)
        of = (o_w / denom[..., None])
        lse = jnp.where(l_w == 0.0, neg_inf, m + jnp.log(denom))
        out = unfold(of.astype(v.dtype), b, tl, num_heads, hd)
        return out, of, lse

    @jax.custom_vjp
    def rf(q, k, v):
        out, _, _ = fwd_pass(q, k, v)
        return out

    def rf_fwd(q, k, v):
        out, of, lse = fwd_pass(q, k, v)
        return out, (q, k, v, of, lse)

    def rf_bwd(res, do):
        q, k, v, of, lse = res
        n = lax.psum(1, axis_name)
        idx = lax.axis_index(axis_name)
        b, tl, e = q.shape
        hd = e // num_heads
        bh = b * num_heads
        qf = fold(q, b, tl, num_heads, hd)
        kb = fold(k, b, tl, kv_heads, hd)
        vb = fold(v, b, tl, kv_heads, hd)
        dof = fold(do, b, tl, num_heads, hd)
        ofd = of.astype(qf.dtype)  # _bwd_call recomputes delta from do*o
        lse3 = jnp.broadcast_to(lse[..., None], (bh, tl, pa.LANES))
        perm = [(i, (i + 1) % n) for i in range(n)]

        def rotate(kk, vv):
            return (lax.ppermute(kk, axis_name, perm),
                    lax.ppermute(vv, axis_name, perm))

        def full_blk(args):
            qq, kk, vv = args
            dq_b, dk_b, dv_b = pa._bwd_call(qq, kk, vv, ofd, lse3, dof,
                                            scale, False, interpret,
                                            groups=group)
            return (dq_b.astype(jnp.float32), dk_b.astype(jnp.float32),
                    dv_b.astype(jnp.float32))

        def diag_blk(args):
            qq, kk, vv = args
            dq_b, dk_b, dv_b = pa._bwd_call(qq, kk, vv, ofd, lse3, dof,
                                            scale, True, interpret,
                                            groups=group)
            return (dq_b.astype(jnp.float32), dk_b.astype(jnp.float32),
                    dv_b.astype(jnp.float32))

        def skip_blk(args):
            zq = jnp.zeros((bh, tl, hd), jnp.float32)
            zkv = jnp.zeros((b * kv_heads, tl, hd), jnp.float32)
            return zq, zkv, zkv

        def hop(r):
            src = (idx - r) % n
            if causal:
                case = jnp.where(src < idx, 0, jnp.where(src == idx, 1, 2))
                return lax.switch(case, [full_blk, diag_blk, skip_blk],
                                  (qf, kb, vb))
            return full_blk((qf, kb, vb))

        dq = jnp.zeros((bh, tl, hd), jnp.float32)
        # traveling dK/dV accumulate at the GROUPED width — together with
        # the folded kb/vb above, every backward-ring ppermute is G×
        # smaller than the MHA ring's
        dkb = jnp.zeros((b * kv_heads, tl, hd), jnp.float32)
        dvb = jnp.zeros((b * kv_heads, tl, hd), jnp.float32)
        if double_buffer:
            # gradient accumulators travel WITH their K/V blocks, but hop
            # r's contribution need not leave until rotation r+1 — so fold
            # hop r-1's pending contribution and rotate the accumulators
            # at the START of iteration r, before this hop's kernel: the
            # rotation's only dependence is the PREVIOUS kernel, and the
            # wire time overlaps the current one.  Each contribution is
            # still rotated exactly n - r times, arriving home with its
            # block after the final fold+rotate below.
            dk_pend = dv_pend = None
            for r in range(n):
                last = r == n - 1
                if r > 0:
                    dkb = lax.ppermute(dkb + dk_pend, axis_name, perm)
                    dvb = lax.ppermute(dvb + dv_pend, axis_name, perm)
                nxt = rotate(kb, vb) if not last else None
                dq_b, dk_pend, dv_pend = hop(r)
                dq = dq + dq_b
                if not last:
                    kb, vb = nxt
            dkb = lax.ppermute(dkb + dk_pend, axis_name, perm)
            dvb = lax.ppermute(dvb + dv_pend, axis_name, perm)
        else:
            for r in range(n):
                last = r == n - 1
                dq_b, dk_b, dv_b = hop(r)
                dq = dq + dq_b
                dkb = dkb + dk_b
                dvb = dvb + dv_b
                # gradient accumulators travel WITH their K/V blocks; after
                # n rotations each block's gradient is back at its owner.
                # K/V's own final rotation is elided (discarded buffers).
                dkb = lax.ppermute(dkb, axis_name, perm)
                dvb = lax.ppermute(dvb, axis_name, perm)
                if not last:
                    kb, vb = rotate(kb, vb)
        dq_out = unfold(dq, b, tl, num_heads, hd).astype(q.dtype)
        dk_out = unfold(dkb, b, tl, kv_heads, hd).astype(k.dtype)
        dv_out = unfold(dvb, b, tl, kv_heads, hd).astype(v.dtype)
        return dq_out, dk_out, dv_out

    rf.defvjp(rf_fwd, rf_bwd)
    _RING_FLASH_CACHE[key] = rf
    return rf
