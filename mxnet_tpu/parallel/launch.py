"""Multi-host launcher.

Reference: `tools/launch.py` spawns scheduler/server/worker processes with
DMLC_* env vars through dmlc-tracker (ssh/mpi/yarn/sge).  TPU-native: every
host is a worker; process group formation is jax.distributed (GRPC), driven
either by TPU metadata (on Cloud TPU pods, automatic) or by the same
environment-variable contract (DMLC_PS_ROOT_URI/PORT reused as the
coordinator address so reference launch tooling keeps working).
"""
from __future__ import annotations

import os

__all__ = ["init", "shutdown"]

_initialized = False


def init(coordinator_address=None, num_processes=None, process_id=None):
    """Initialize the distributed runtime (idempotent)."""
    global _initialized
    import jax

    if _initialized:
        return
    if coordinator_address is None and "DMLC_PS_ROOT_URI" in os.environ:
        coordinator_address = "%s:%s" % (os.environ["DMLC_PS_ROOT_URI"],
                                         os.environ.get("DMLC_PS_ROOT_PORT", "9091"))
        num_processes = int(os.environ.get("DMLC_NUM_WORKER", "1"))
        process_id = int(os.environ.get("DMLC_WORKER_ID",
                                        os.environ.get("DMLC_RANK", "0")))
    if coordinator_address is not None:
        try:
            # CPU processes federate through gloo (TCP); TPU uses ICI and
            # ignores this.  Must be set before the backend exists.
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
    else:
        try:
            jax.distributed.initialize()  # TPU pod metadata path
        except Exception:
            pass  # single-process
    _initialized = True


def shutdown():
    global _initialized
    import jax

    if _initialized:
        try:
            jax.distributed.shutdown()
        except Exception:
            pass
        _initialized = False
