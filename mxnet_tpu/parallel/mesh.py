"""Device-mesh construction.

The scaling-book recipe: pick a mesh (axes: data / model-tensor / pipeline /
sequence / expert), annotate shardings, let XLA insert the collectives so
they ride ICI.  This module owns mesh construction for both the Module data
path (executor_group) and the standalone training-step API (models/).
"""
from __future__ import annotations

from dataclasses import dataclass, field, replace

import numpy as np

__all__ = ["MeshConfig", "build_mesh", "data_parallel_mesh",
           "rank_devices", "survivor_submesh"]


@dataclass
class MeshConfig:
    """Logical mesh shape.  Axis size -1 means 'absorb remaining devices'."""

    data: int = -1     # data parallel (gradient psum)
    model: int = 1     # tensor parallel (matmul sharding)
    pipe: int = 1      # pipeline stages
    seq: int = 1       # sequence/context parallel (ring attention axis)
    expert: int = 1    # expert parallel (MoE all-to-all)
    names: tuple = ("data", "model", "pipe", "seq", "expert")

    def resolve(self, n_devices):
        sizes = [self.data, self.model, self.pipe, self.seq, self.expert]
        fixed = 1
        for s in sizes:
            if s != -1:
                fixed *= s
        free = [i for i, s in enumerate(sizes) if s == -1]
        if free:
            assert n_devices % fixed == 0, \
                "devices %d not divisible by fixed axes %d" % (n_devices, fixed)
            rem = n_devices // fixed
            sizes[free[0]] = rem
            for i in free[1:]:
                sizes[i] = 1
        total = int(np.prod(sizes))
        assert total == n_devices, \
            "mesh %s does not cover %d devices" % (sizes, n_devices)
        return sizes


def build_mesh(config=None, devices=None):
    """Build a jax Mesh from a MeshConfig over the given (default: all) devices."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    config = config or MeshConfig()
    sizes = config.resolve(len(devices))
    arr = np.array(devices).reshape(sizes)
    return Mesh(arr, config.names)


def data_parallel_mesh(devices=None):
    return build_mesh(MeshConfig(data=-1), devices)


# ---------------------------------------------------------------------------
# Elastic reconfiguration (mxnet_tpu.elastic): the 'data' axis is the
# worker-ownership axis — rank r owns a contiguous block of data-axis rows,
# and every other axis (model/pipe/seq/expert) lives entirely within one
# worker's devices.  Shrinking on failure therefore means dropping the dead
# ranks' data rows and re-forming the mesh over the survivors' devices;
# regrowing is the same computation with the returned ranks back in.
# ---------------------------------------------------------------------------

def rank_devices(devices, num_workers, config=None):
    """Partition ``devices`` into per-rank slices along the data axis.

    The mesh layout is row-major over (data, model, pipe, seq, expert), so
    each data-axis row is a contiguous run of ``len(devices)/data`` devices
    and rank ``r`` owns rows ``[r*data/W, (r+1)*data/W)``.  Returns a list
    of ``num_workers`` device lists."""
    config = config or MeshConfig()
    sizes = config.resolve(len(devices))
    data = sizes[config.names.index("data")]
    if data % num_workers != 0:
        raise ValueError("data axis %d not divisible by %d workers"
                         % (data, num_workers))
    rows_per = data // num_workers
    block = len(devices) // data          # devices per data-axis row
    per = rows_per * block
    return [list(devices[r * per:(r + 1) * per]) for r in range(num_workers)]


def survivor_submesh(devices, num_workers, survivors, config=None):
    """Devices + shrunk MeshConfig for the surviving worker ranks.

    ``devices`` is the FULL original device (or context) list the mesh was
    built over; ``survivors`` the ranks still alive.  The returned config
    keeps every non-data axis and scales 'data' to the survivors' share —
    the per-replica batch grows by the same factor, the global batch stays
    fixed.  Passing all ranks back reproduces the original mesh (regrow).
    """
    survivors = sorted(set(survivors))
    if not survivors:
        raise ValueError("no surviving workers to re-form the mesh on")
    config = config or MeshConfig()
    parts = rank_devices(devices, num_workers, config)
    sizes = config.resolve(len(devices))
    data = sizes[config.names.index("data")]
    rows_per = data // num_workers
    devs = []
    for r in survivors:
        if r >= num_workers:
            raise ValueError("survivor rank %d out of range (%d workers)"
                             % (r, num_workers))
        devs.extend(parts[r])
    # pin every axis to its RESOLVED size (a -1 in the original config must
    # not re-absorb the shrunk device count into the wrong axis)
    resolved = dict(zip(config.names, sizes))
    resolved["data"] = rows_per * len(survivors)
    new_cfg = replace(config, **resolved)
    return devs, new_cfg
