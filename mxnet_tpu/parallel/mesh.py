"""Device-mesh construction.

The scaling-book recipe: pick a mesh (axes: data / model-tensor / pipeline /
sequence / expert), annotate shardings, let XLA insert the collectives so
they ride ICI.  This module owns mesh construction for both the Module data
path (executor_group) and the standalone training-step API (models/).
"""
from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

__all__ = ["MeshConfig", "build_mesh", "data_parallel_mesh"]


@dataclass
class MeshConfig:
    """Logical mesh shape.  Axis size -1 means 'absorb remaining devices'."""

    data: int = -1     # data parallel (gradient psum)
    model: int = 1     # tensor parallel (matmul sharding)
    pipe: int = 1      # pipeline stages
    seq: int = 1       # sequence/context parallel (ring attention axis)
    expert: int = 1    # expert parallel (MoE all-to-all)
    names: tuple = ("data", "model", "pipe", "seq", "expert")

    def resolve(self, n_devices):
        sizes = [self.data, self.model, self.pipe, self.seq, self.expert]
        fixed = 1
        for s in sizes:
            if s != -1:
                fixed *= s
        free = [i for i, s in enumerate(sizes) if s == -1]
        if free:
            assert n_devices % fixed == 0, \
                "devices %d not divisible by fixed axes %d" % (n_devices, fixed)
            rem = n_devices // fixed
            sizes[free[0]] = rem
            for i in free[1:]:
                sizes[i] = 1
        total = int(np.prod(sizes))
        assert total == n_devices, \
            "mesh %s does not cover %d devices" % (sizes, n_devices)
        return sizes


def build_mesh(config=None, devices=None):
    """Build a jax Mesh from a MeshConfig over the given (default: all) devices."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    config = config or MeshConfig()
    sizes = config.resolve(len(devices))
    arr = np.array(devices).reshape(sizes)
    return Mesh(arr, config.names)


def data_parallel_mesh(devices=None):
    return build_mesh(MeshConfig(data=-1), devices)
