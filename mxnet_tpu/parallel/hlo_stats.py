"""Collective-communication accounting from compiled HLO text.

Absorbed into the static-analysis package as its parsing layer
(``mxnet_tpu/analysis/hlo_parse.py``): the counting here grew from a
bandwidth probe into the substrate of the pass framework's budget /
FLOP / donation audits, so the implementation now lives beside the
passes that consume it.  This module remains the stable import path for
the test-suite tripwires and the benches (``collective_stats``,
``shape_bytes``, ``dot_flops`` — plus the newer report forms).
"""
from __future__ import annotations

from ..analysis.hlo_parse import (collective_stats, dot_flops,
                                  dot_flops_report, input_output_aliases,
                                  shape_bytes, shape_bytes_report)

__all__ = ["collective_stats", "shape_bytes", "shape_bytes_report",
           "dot_flops", "dot_flops_report", "input_output_aliases"]
