"""Collective-communication accounting from compiled HLO text.

The reference measures distributed communication empirically
(``tools/bandwidth/measure.py``); under XLA the collectives are explicit in
the optimized HLO, so the framework can *statically* count them and total
their payload bytes.  Used by tests/test_tensor_parallel.py (asserting the
Megatron plan emits fewer collectives than naive sharding) and
tools/bandwidth.py (comm volume per training step).
"""
from __future__ import annotations

import re

__all__ = ["collective_stats", "shape_bytes", "dot_flops"]

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2, "f8e4m3fn": 1, "f8e5m2": 1,
    "s64": 8, "u64": 8, "s32": 4, "u32": 4, "s16": 2, "u16": 2,
    "s8": 1, "u8": 1, "pred": 1, "c64": 8, "c128": 16,
}

_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")

# an instruction line: '%name = SHAPE op(...)'.  SHAPE is extracted with a
# balanced-paren scan, not a depth-limited regex: tuple shapes nest (grouped
# async collectives carry tuples of buffers) and TPU layout annotations like
# {1,0:T(8,128)} add parens at arbitrary depth inside them.
_INSTR_RE = re.compile(r"=\s*")
_OP_RE = re.compile(
    r"\s*(all-reduce|all-gather|reduce-scatter|all-to-all|collective-permute)"
    r"(-start|-done)?\(")


def _scan_shape(line, start):
    """Return (shape_str, end_index) for the shape beginning at `start` —
    either a balanced parenthesized tuple or a single whitespace-free
    token."""
    if start < len(line) and line[start] == "(":
        depth = 0
        for i in range(start, len(line)):
            if line[i] == "(":
                depth += 1
            elif line[i] == ")":
                depth -= 1
                if depth == 0:
                    return line[start:i + 1], i + 1
        return line[start:], len(line)
    m = re.match(r"\S+", line[start:])
    if m is None:
        return "", start
    return m.group(0), start + m.end()


def shape_bytes(shape_str):
    """Total bytes of every 'dtype[dims]' shape in the string (tuples ok)."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(shape_str):
        width = _DTYPE_BYTES.get(dtype)
        if width is None:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * width
    return total


def _split_top_level(tuple_str):
    """Split '(a, (b, c), d)' into top-level elements ['a', '(b, c)', 'd']."""
    s = tuple_str.strip()
    if not (s.startswith("(") and s.endswith(")")):
        return [s]
    s = s[1:-1]
    parts, depth, start = [], 0, 0
    for i, ch in enumerate(s):
        if ch in "({[":
            depth += 1
        elif ch in ")}]":
            depth -= 1
        elif ch == "," and depth == 0:
            parts.append(s[start:i])
            start = i + 1
    parts.append(s[start:])
    return [p.strip() for p in parts if p.strip()]


def _start_bytes(op, shape_s):
    """Result payload of an async '-start' tuple shape.

    The tuple layout is op-specific (verified against compiled HLO):
    ``all-reduce-start`` has the SAME shape as the sync op — a flat tuple
    of results when XLA combined several all-reduces — so every buffer
    counts.  ``all-gather-start`` / ``reduce-scatter-start`` /
    ``collective-permute-start`` carry
    ``(operand(s), result(s), [u32 context scalars...])`` — count only
    the result element (itself possibly a tuple for grouped ops).
    Summing naively would double those (reduce-scatter-start used to fall
    into the generic fallback and did exactly that, inflating absolute
    KiB/step); taking the single largest buffer (the old rule)
    undercounts any grouped form.
    """
    parts = _split_top_level(shape_s)
    parts = [p for p in parts
             if not re.fullmatch(r"[su]32\[\]\S*", p)]  # context scalars
    if not parts:
        return 0
    if op == "all-reduce":
        return sum(shape_bytes(p) for p in parts)
    if op in ("all-gather", "reduce-scatter", "collective-permute") \
            and len(parts) >= 2:
        return shape_bytes(parts[1])
    # generic async wrapper: ((operands...), results, ctx) — a leading
    # tuple element marks the operand pack; otherwise flat results
    if len(parts) >= 2 and parts[0].startswith("("):
        return shape_bytes(parts[1])
    return sum(shape_bytes(p) for p in parts)


# stablehlo: '%3 = stablehlo.dot_general %1, %2, batching_dims = [0] x [0],
#   contracting_dims = [1] x [0] ... : (tensor<8x128xf32>, ...) -> tensor<...>'
_SH_DOT_RE = re.compile(
    r"dot_general\b.*?contracting_dims\s*=\s*\[([0-9,\s]*)\]\s*x\s*\[[0-9,\s]*\]"
    r".*?:\s*\(tensor<([^>]+)>.*?->\s*tensor<([^>]+)>")
# HLO: '%dot.3 = f32[8,512]{1,0} dot(f32[8,128]{1,0} %a, ...),
#   lhs_contracting_dims={1}, rhs_contracting_dims={0}'
_HLO_DOT_RE = re.compile(
    r"=\s*([a-z][a-z0-9]+\[[0-9,]*\])\S*\s+dot\(\s*([a-z][a-z0-9]+\[[0-9,]*\])"
    r".*?lhs_contracting_dims=\{([0-9,]*)\}")


def _tensor_dims(spec):
    """'2x4x64xf32' -> [2, 4, 64] (scalar 'f32' -> [])."""
    return [int(d) for d in spec.split("x")[:-1]]


def _bracket_dims(spec):
    """'f32[8,128]' -> [8, 128]."""
    inner = spec[spec.index("[") + 1:spec.index("]")]
    return [int(d) for d in inner.split(",") if d]


def dot_flops(program_text):
    """Total matmul FLOPs (2 * result elements * contraction size) of every
    dot in a lowered program — StableHLO ``dot_general`` and HLO ``dot(``
    lines both count, fusion bodies included.

    The decode benchmark's O(1)-in-prefix assertion rests on this: a
    KV-cached decode step's dot FLOPs are a constant while the
    recompute-the-prefix program's grow linearly with T.  Static counting
    (like :func:`collective_stats`) — no execution, backend-independent
    when fed ``jit(...).lower(...).as_text()``.
    """
    total = 0
    for line in program_text.splitlines():
        m = _SH_DOT_RE.search(line)
        if m is not None:
            cdims = [int(d) for d in m.group(1).replace(" ", "").split(",")
                     if d]
            lhs = _tensor_dims(m.group(2))
            out = _tensor_dims(m.group(3))
            contract = 1
            for d in cdims:
                contract *= lhs[d]
            n = 1
            for d in out:
                n *= d
            total += 2 * n * contract
            continue
        m = _HLO_DOT_RE.search(line)
        if m is not None:
            out = _bracket_dims(m.group(1))
            lhs = _bracket_dims(m.group(2))
            cdims = [int(d) for d in m.group(3).split(",") if d]
            contract = 1
            for d in cdims:
                contract *= lhs[d]
            n = 1
            for d in out:
                n *= d
            total += 2 * n * contract
    return total


def collective_stats(hlo_text):
    """Count collectives and sum their result payloads.

    Async start/done pairs count once (the -start carries the shape).
    Returns {op_name: {"count": int, "bytes": int}} plus two aggregate
    entries: "total" over every op, and "overlappable" — the count/bytes
    of collectives the backend emitted as async ``-start``/``-done``
    pairs, i.e. communication the scheduler can overlap with compute
    between the pair (the double-buffered ring's collective-permutes on
    TPU land here; backends that keep sync collectives report 0).
    """
    stats = {}
    overlappable = {"count": 0, "bytes": 0}
    matches = []
    for line in hlo_text.splitlines():
        em = _INSTR_RE.search(line)
        if em is None:
            continue
        shape_s, end = _scan_shape(line, em.end())
        om = _OP_RE.match(line, end)
        if om is None:
            continue
        matches.append((shape_s, om.group(1), om.group(2)))
    for shape_s, op, suffix in matches:
        if suffix == "-done":
            continue
        if suffix == "-start":
            nbytes = _start_bytes(op, shape_s)
            overlappable["count"] += 1
            overlappable["bytes"] += nbytes
        else:
            nbytes = shape_bytes(shape_s)
        entry = stats.setdefault(op, {"count": 0, "bytes": 0})
        entry["count"] += 1
        entry["bytes"] += nbytes
    total = {"count": sum(e["count"] for e in stats.values()),
             "bytes": sum(e["bytes"] for e in stats.values())}
    stats["total"] = total
    stats["overlappable"] = overlappable
    return stats
