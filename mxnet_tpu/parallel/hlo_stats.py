"""Collective-communication accounting from compiled HLO text.

The reference measures distributed communication empirically
(``tools/bandwidth/measure.py``); under XLA the collectives are explicit in
the optimized HLO, so the framework can *statically* count them and total
their payload bytes.  Used by tests/test_tensor_parallel.py (asserting the
Megatron plan emits fewer collectives than naive sharding) and
tools/bandwidth.py (comm volume per training step).
"""
from __future__ import annotations

import re

__all__ = ["collective_stats", "shape_bytes"]

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2, "f8e4m3fn": 1, "f8e5m2": 1,
    "s64": 8, "u64": 8, "s32": 4, "u32": 4, "s16": 2, "u16": 2,
    "s8": 1, "u8": 1, "pred": 1, "c64": 8, "c128": 16,
}

_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")

_COLLECTIVE_RE = re.compile(
    r"=\s*((?:\([^)]*\))|(?:[a-z][a-z0-9]*\[[0-9,]*\]\S*))\s+"
    r"(all-reduce|all-gather|reduce-scatter|all-to-all|collective-permute)"
    r"(-start|-done)?\(")


def shape_bytes(shape_str):
    """Total bytes of every 'dtype[dims]' shape in the string (tuples ok)."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(shape_str):
        width = _DTYPE_BYTES.get(dtype)
        if width is None:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * width
    return total


def collective_stats(hlo_text):
    """Count collectives and sum their result payloads.

    Async start/done pairs count once (the -start carries the shape).
    Returns {op_name: {"count": int, "bytes": int}} plus "total" entry.
    """
    stats = {}
    for m in _COLLECTIVE_RE.finditer(hlo_text):
        shape_s, op, suffix = m.group(1), m.group(2), m.group(3)
        if suffix == "-done":
            continue
        if suffix == "-start":
            # async start shapes are tuples holding operand-alias + result
            # buffers (+ u32 context scalars); counting the whole tuple
            # would double the payload — take the largest single buffer
            nbytes = max((shape_bytes(s.group(0))
                          for s in _SHAPE_RE.finditer(shape_s)), default=0)
        else:
            nbytes = shape_bytes(shape_s)
        entry = stats.setdefault(op, {"count": 0, "bytes": 0})
        entry["count"] += 1
        entry["bytes"] += nbytes
    total = {"count": sum(e["count"] for e in stats.values()),
             "bytes": sum(e["bytes"] for e in stats.values())}
    stats["total"] = total
    return stats
