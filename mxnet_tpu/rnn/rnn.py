"""RNN checkpoint helpers (reference: python/mxnet/rnn/rnn.py)."""
from __future__ import annotations

from .. import model as model_mod

__all__ = ["save_rnn_checkpoint", "load_rnn_checkpoint", "do_rnn_checkpoint"]


def _as_list(cells):
    return cells if isinstance(cells, (list, tuple)) else [cells]


def save_rnn_checkpoint(cells, prefix, epoch, symbol, arg_params, aux_params):
    """Save checkpoint with fused weights packed (reference: rnn.py:10)."""
    cells = _as_list(cells)
    for cell in cells:
        arg_params = cell.pack_weights(arg_params)
    model_mod.save_checkpoint(prefix, epoch, symbol, arg_params, aux_params)


def load_rnn_checkpoint(cells, prefix, epoch):
    """Load checkpoint, unpacking fused weights (reference: rnn.py:35)."""
    sym, arg, aux = model_mod.load_checkpoint(prefix, epoch)
    cells = _as_list(cells)
    for cell in cells:
        arg = cell.unpack_weights(arg)
    return sym, arg, aux


def do_rnn_checkpoint(cells, prefix, period=1):
    """Epoch-end callback (reference: rnn.py:61)."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            save_rnn_checkpoint(cells, prefix, iter_no + 1, sym, arg, aux)

    return _callback
