"""RNN-aware checkpointing.

Fused RNN cells keep their parameters as one packed device blob; on disk we
want the individual per-gate weights so checkpoints are portable between
fused and unfused graphs.  These helpers wrap the generic model checkpoint
path (``model.save_checkpoint``/``load_checkpoint``) with a pack step on
save and an unpack step on load.  Capability parity:
``python/mxnet/rnn/rnn.py``.
"""
from __future__ import annotations

from .. import model as model_mod

__all__ = ["save_rnn_checkpoint", "load_rnn_checkpoint", "do_rnn_checkpoint"]


def _through_cells(cells, method, params):
    """Thread ``params`` through ``cell.<method>`` for every cell."""
    if not isinstance(cells, (list, tuple)):
        cells = (cells,)
    for cell in cells:
        params = getattr(cell, method)(params)
    return params


def save_rnn_checkpoint(cells, prefix, epoch, symbol, arg_params, aux_params):
    """``model.save_checkpoint`` with fused-cell weights packed first."""
    model_mod.save_checkpoint(
        prefix, epoch, symbol,
        _through_cells(cells, "pack_weights", arg_params), aux_params)


def load_rnn_checkpoint(cells, prefix, epoch):
    """``model.load_checkpoint`` + unpack of fused-cell weights."""
    symbol, arg_params, aux_params = model_mod.load_checkpoint(prefix, epoch)
    return symbol, _through_cells(cells, "unpack_weights", arg_params), \
        aux_params


def do_rnn_checkpoint(cells, prefix, period=1):
    """Epoch-end callback variant of ``save_rnn_checkpoint`` (drop-in for
    ``callback.do_checkpoint`` when the net contains fused cells)."""
    period = max(1, int(period))

    def on_epoch_end(epoch, symbol=None, arg_params=None, aux_params=None):
        if (epoch + 1) % period == 0:
            save_rnn_checkpoint(cells, prefix, epoch + 1, symbol,
                                arg_params, aux_params)

    return on_epoch_end
