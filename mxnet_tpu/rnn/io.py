"""Bucketing data iterator for sequences.

Capability parity with the reference's ``BucketSentenceIter``
(``python/mxnet/rnn/io.py``), re-designed for array-at-once construction:
instead of binning sentences one by one into Python lists, all lengths are
bucketed in a single ``np.searchsorted`` and the padded token matrix is
materialized with one boolean-mask assignment.  On TPU each bucket length is
a distinct XLA compilation, so the bucket inventory doubles as the jit-cache
key set (see BucketingModule).
"""
from __future__ import annotations

import logging

import numpy as np

from ..io import DataIter, DataBatch, DataDesc
from .. import ndarray as nd

__all__ = ["BucketSentenceIter"]


def _auto_buckets(lengths, batch_size):
    """Pick bucket lengths: every distinct sentence length that occurs often
    enough to fill at least one batch becomes a bucket."""
    uniq, counts = np.unique(lengths, return_counts=True)
    chosen = uniq[counts >= batch_size].tolist()
    if not chosen:
        chosen = [int(uniq.max())]
    return chosen


def _pad_matrix(sentences, lengths, width, fill, dtype):
    """All sentences as one (n, width) matrix, tail-padded with ``fill``."""
    out = np.full((len(sentences), width), fill, dtype=dtype)
    mask = np.arange(width)[None, :] < lengths[:, None]
    out[mask] = np.concatenate([np.asarray(s, dtype=dtype)
                                for s in sentences]) if sentences else []
    return out


class BucketSentenceIter(DataIter):
    """Language-model iterator over variable-length token-id sequences.

    Sequences are assigned to the smallest bucket that fits (longer ones are
    dropped with a warning), padded with ``invalid_label``, and served as
    full batches whose ``bucket_key`` selects the matching unrolled graph.
    Labels are the inputs shifted one step left (next-token prediction).

    ``layout``: "NT" serves (batch, time); "TN" serves (time, batch).
    """

    def __init__(self, sentences, batch_size, buckets=None, invalid_label=-1,
                 data_name="data", label_name="softmax_label", dtype="float32",
                 layout="NT", seed=None):
        super().__init__(batch_size)
        lengths = np.array([len(s) for s in sentences], dtype=np.int64)
        buckets = sorted(buckets) if buckets else _auto_buckets(lengths,
                                                                batch_size)

        # vectorized binning: smallest bucket >= length, out-of-range -> drop
        which = np.searchsorted(buckets, lengths, side="left")
        keep = which < len(buckets)
        if not keep.all():
            logging.warning(
                "BucketSentenceIter: dropping %d sequence(s) longer than the "
                "largest bucket (%d)", int((~keep).sum()), buckets[-1])

        self.buckets = list(buckets)
        self.batch_size = batch_size
        self.invalid_label = invalid_label
        self.data_name = data_name
        self.label_name = label_name
        self.dtype = dtype
        self.default_bucket_key = max(buckets)

        if layout == "NT":
            self._batch_major = True
        elif layout == "TN":
            self._batch_major = False
        else:
            raise ValueError("layout must be 'NT' (batch major) or 'TN' "
                             "(time major), got %r" % layout)

        # one padded matrix per bucket, built in bulk
        self._tokens = []
        for b, width in enumerate(buckets):
            rows = np.nonzero(keep & (which == b))[0]
            group = [sentences[i] for i in rows]
            self._tokens.append(
                _pad_matrix(group, lengths[rows], width, invalid_label,
                            dtype))

        self._order = None      # per-bucket row permutations
        self._schedule = None   # shuffled (bucket, row-window) pairs
        self._cursor = 0
        self._rng = np.random.RandomState(seed)  # seed pins epoch order
        self.reset()

        shape = ((batch_size, self.default_bucket_key) if self._batch_major
                 else (self.default_bucket_key, batch_size))
        self.provide_data = [DataDesc(data_name, shape)]
        self.provide_label = [DataDesc(label_name, shape)]

    # -- epoch machinery -----------------------------------------------------
    def reset(self):
        self._cursor = 0
        self._order = [self._rng.permutation(len(t)) for t in self._tokens]
        schedule = [(b, start)
                    for b, tokens in enumerate(self._tokens)
                    for start in range(0,
                                       len(tokens) - self.batch_size + 1,
                                       self.batch_size)]
        self._rng.shuffle(schedule)
        self._schedule = schedule

    def next(self):
        if self._cursor >= len(self._schedule):
            raise StopIteration
        b, start = self._schedule[self._cursor]
        self._cursor += 1

        rows = self._order[b][start:start + self.batch_size]
        tokens = self._tokens[b][rows]
        # next-token labels: shift left, pad the final step
        labels = np.concatenate(
            [tokens[:, 1:],
             np.full((len(tokens), 1), self.invalid_label,
                     dtype=tokens.dtype)], axis=1)
        if not self._batch_major:
            tokens = tokens.T
            labels = labels.T
        data = nd.array(tokens, dtype=self.dtype)
        label = nd.array(labels, dtype=self.dtype)
        return DataBatch([data], [label], pad=0,
                         bucket_key=self.buckets[b],
                         provide_data=[DataDesc(self.data_name, data.shape)],
                         provide_label=[DataDesc(self.label_name,
                                                 label.shape)])
