"""RNN package (reference: python/mxnet/rnn/)."""
from . import rnn_cell
from .rnn_cell import (RNNParams, BaseRNNCell, RNNCell, LSTMCell, GRUCell,
                       FusedRNNCell, SequentialRNNCell, BidirectionalCell,
                       DropoutCell, ModifierCell, ZoneoutCell, ResidualCell)
from .rnn import save_rnn_checkpoint, load_rnn_checkpoint, do_rnn_checkpoint
from .io import BucketSentenceIter
