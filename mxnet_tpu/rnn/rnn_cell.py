"""RNN cell symbol library (reference: python/mxnet/rnn/rnn_cell.py, 962 LoC).

Cells compose Symbols step-by-step (`unroll`), or map onto the fused `RNN`
op (`FusedRNNCell`) which lowers to lax.scan — the reference's cuDNN path.
`unfuse()`/pack/unpack_weights convert between the fused flat parameter
vector (layout documented in ops/rnn_op.py) and per-cell FC weights, so
unrolled and fused nets interconvert exactly as in the reference
(tests/python/unittest/test_rnn.py consistency tests).
"""
from __future__ import annotations

from .. import symbol
from ..base import MXNetError
from ..ops.rnn_op import rnn_param_size, _layout, _gates

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "BidirectionalCell",
           "DropoutCell", "ModifierCell", "ZoneoutCell", "ResidualCell"]


class RNNParams:
    """Container for cell parameters (reference: rnn_cell.py:21)."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = symbol.Variable(name, **kwargs)
        return self._params[name]


class BaseRNNCell:
    """Abstract cell (reference: rnn_cell.py:42)."""

    def __init__(self, prefix="", params=None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    def __call__(self, inputs, states):
        raise NotImplementedError()

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def state_info(self):
        raise NotImplementedError()

    @property
    def state_shape(self):
        return [info["shape"] for info in self.state_info]

    @property
    def _gate_names(self):
        return ()

    def begin_state(self, func=None, _batch_ref=None, _ref_axis=0, **kwargs):
        """Initial states as symbols (reference: rnn_cell.py:129).

        With ``_batch_ref`` (set by unroll), states are zero tensors whose
        batch dimension follows the data symbol at bind time (the reference's
        ``func=sym.zeros``); otherwise they are plain Variables the caller
        must feed."""
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called directly."
        states = []
        for info in self.state_info:
            self._init_counter += 1
            name = "%sbegin_state_%d" % (self._prefix, self._init_counter)
            if func is not None:
                state = func(name=name, **kwargs)
            elif _batch_ref is not None:
                state = symbol._create(
                    "_rnn_begin_state", [_batch_ref],
                    {"shape": str(tuple(info["shape"])),
                     "batch_axis": str(_ref_axis)}, name=name)
            else:
                state = symbol.Variable(name)
            states.append(state)
        return states

    def unpack_weights(self, args):
        """Unpack fused weights (identity for unfused cells)."""
        args = dict(args)
        return args

    def pack_weights(self, args):
        args = dict(args)
        return args

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        """Unroll the cell `length` steps (reference: rnn_cell.py:254)."""
        self.reset()
        if inputs is None:
            inputs = [symbol.Variable("%st%d_data" % (input_prefix, i))
                      for i in range(length)]
        elif isinstance(inputs, symbol.Symbol):
            assert len(inputs) == 1
            axis = layout.find("T")
            inputs = getattr(symbol, "SliceChannel")(
                inputs, axis=axis, num_outputs=length, squeeze_axis=1)
            inputs = [inputs[i] for i in range(length)]
        if begin_state is None:
            begin_state = self.begin_state(_batch_ref=inputs[0], _ref_axis=0)
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        if merge_outputs:
            outputs = [symbol.expand_dims(o, axis=1) for o in outputs]
            outputs = symbol.Concat(*outputs, dim=1)
        return outputs, states

    def _get_activation(self, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return symbol.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)


class RNNCell(BaseRNNCell):
    """Vanilla RNN cell: h' = act(W x + R h + b) (reference: rnn_cell.py:325)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("",)

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW, bias=self._iB,
                                    num_hidden=self._num_hidden,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(data=states[0], weight=self._hW, bias=self._hB,
                                    num_hidden=self._num_hidden,
                                    name="%sh2h" % name)
        output = self._get_activation(i2h + h2h, self._activation,
                                      name="%sout" % name)
        return output, [output]


class LSTMCell(BaseRNNCell):
    """LSTM cell (reference: rnn_cell.py:365). Gate order i,f,g,o."""

    def __init__(self, num_hidden, prefix="lstm_", params=None, forget_bias=1.0):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._hW = self.params.get("h2h_weight")
        from ..initializer import LSTMBias

        self._iB = self.params.get("i2h_bias",
                                   init=LSTMBias(forget_bias=forget_bias))
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"},
                {"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW, bias=self._iB,
                                    num_hidden=self._num_hidden * 4,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(data=states[0], weight=self._hW, bias=self._hB,
                                    num_hidden=self._num_hidden * 4,
                                    name="%sh2h" % name)
        gates = i2h + h2h
        slice_gates = symbol.SliceChannel(gates, num_outputs=4, axis=1,
                                          name="%sslice" % name)
        in_gate = symbol.Activation(slice_gates[0], act_type="sigmoid")
        forget_gate = symbol.Activation(slice_gates[1], act_type="sigmoid")
        in_transform = symbol.Activation(slice_gates[2], act_type="tanh")
        out_gate = symbol.Activation(slice_gates[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * symbol.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """GRU cell (reference: rnn_cell.py:428). Gate order r,z,n."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_r", "_z", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        prev_state_h = states[0]
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW, bias=self._iB,
                                    num_hidden=self._num_hidden * 3,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(data=prev_state_h, weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden * 3,
                                    name="%sh2h" % name)
        i2h_r, i2h_z, i2h = symbol.SliceChannel(i2h, num_outputs=3,
                                                name="%si2h_slice" % name)
        h2h_r, h2h_z, h2h = symbol.SliceChannel(h2h, num_outputs=3,
                                                name="%sh2h_slice" % name)
        reset_gate = symbol.Activation(i2h_r + h2h_r, act_type="sigmoid",
                                       name="%sr_act" % name)
        update_gate = symbol.Activation(i2h_z + h2h_z, act_type="sigmoid",
                                        name="%sz_act" % name)
        next_h_tmp = symbol.Activation(i2h + reset_gate * h2h, act_type="tanh",
                                       name="%sh_act" % name)
        next_h = (1.0 - update_gate) * next_h_tmp + update_gate * prev_state_h
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Fused multi-layer RNN mapping onto the `RNN` op (reference: :497)."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm", bidirectional=False,
                 dropout=0.0, get_next_state=False, forget_bias=1.0,
                 prefix=None, params=None):
        if prefix is None:
            prefix = "%s_" % mode
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._forget_bias = forget_bias
        self._parameter = self.params.get("parameters")
        self._directions = 2 if bidirectional else 1

    @property
    def state_info(self):
        b = self._directions
        n = (self._mode == "lstm") + 1
        return [{"shape": (b * self._num_layers, 0, self._num_hidden),
                 "__layout__": "LNC"} for _ in range(n)]

    @property
    def _gate_names(self):
        return {"rnn_relu": [""], "rnn_tanh": [""],
                "lstm": ["_i", "_f", "_c", "_o"],
                "gru": ["_r", "_z", "_o"]}[self._mode]

    def _param_layout(self, input_size):
        return _layout(self._num_layers, self._num_hidden, self._mode,
                       self._bidirectional, input_size)

    def unpack_weights(self, args, input_size=None):
        """Split the flat `parameters` array into per-matrix numpy views."""
        import numpy as np

        args = dict(args)
        arr = args.pop(self._prefix + "parameters")
        if hasattr(arr, "asnumpy"):
            arr = arr.asnumpy()
        arr = np.asarray(arr)
        if input_size is None:
            input_size = self._infer_input_size(arr)
        for name, off, shape in self._param_layout(input_size):
            n = int(np.prod(shape))
            args[self._prefix + name] = arr[off:off + n].reshape(shape).copy()
        return args

    def pack_weights(self, args, input_size=None):
        import numpy as np

        args = dict(args)
        pieces = {}
        for key in list(args.keys()):
            if key.startswith(self._prefix) and ("_i2h_" in key or "_h2h_" in key):
                pieces[key[len(self._prefix):]] = args.pop(key)
        any_piece = next(iter(pieces.values()))
        first_w = pieces.get("l0_d0_i2h_weight")
        if input_size is None:
            input_size = np.asarray(first_w).shape[-1]
        total = rnn_param_size(self._num_layers, self._num_hidden, self._mode,
                               self._bidirectional, input_size)
        flat = np.zeros((total,), dtype=np.asarray(any_piece).dtype)
        for name, off, shape in self._param_layout(input_size):
            v = pieces[name]
            if hasattr(v, "asnumpy"):
                v = v.asnumpy()
            flat[off:off + int(np.prod(shape))] = np.asarray(v).reshape(-1)
        args[self._prefix + "parameters"] = flat
        return args

    def _infer_input_size(self, flat):
        """Solve for input_size from the flat parameter count."""
        g = _gates(self._mode)
        d = self._directions
        H = self._num_hidden
        L = self._num_layers
        total = flat.size
        # total = d*g*H*I + d*g*H*H + (L-1)*d*g*H*(H*d + H) + L*d*2*g*H
        rest = d * g * H * H + (L - 1) * d * g * H * (H * d + H) + L * d * 2 * g * H
        return (total - rest) // (d * g * H)

    def __call__(self, inputs, states):
        raise MXNetError("FusedRNNCell cannot be stepped; use unroll")

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        self.reset()
        axis = layout.find("T")
        if inputs is None:
            inputs = [symbol.Variable("%st%d_data" % (input_prefix, i))
                      for i in range(length)]
        if isinstance(inputs, list):
            inputs = [symbol.expand_dims(i, axis=0) for i in inputs]
            inputs = symbol.Concat(*inputs, dim=0)  # TNC
        else:
            if axis == 1:  # NTC -> TNC
                inputs = symbol.SwapAxis(inputs, dim1=0, dim2=1)
        if begin_state is None:
            begin_state = self.begin_state(_batch_ref=inputs, _ref_axis=1)
        states = list(begin_state)

        rnn_args = dict(state_size=self._num_hidden, num_layers=self._num_layers,
                        bidirectional=self._bidirectional, mode=self._mode,
                        p=self._dropout, state_outputs=self._get_next_state,
                        name="%srnn" % self._prefix)
        if self._mode == "lstm":
            rnn = symbol.RNN(inputs, self._parameter, states[0], states[1],
                             **rnn_args)
        else:
            rnn = symbol.RNN(inputs, self._parameter, states[0], **rnn_args)

        if self._get_next_state:
            outputs = rnn[0]
            next_states = [rnn[i] for i in range(1, len(self.state_info) + 1)]
        else:
            outputs = rnn if len(rnn) == 1 else rnn[0]
            next_states = []

        if axis == 1:
            outputs = symbol.SwapAxis(outputs, dim1=0, dim2=1)
        if not merge_outputs:
            outputs = symbol.SliceChannel(outputs, axis=axis, num_outputs=length,
                                          squeeze_axis=1)
            outputs = [outputs[i] for i in range(length)]
        return outputs, next_states

    def unfuse(self):
        """Equivalent unfused SequentialRNNCell (reference: rnn_cell.py:604)."""
        stack = SequentialRNNCell()
        get_cell = {
            "rnn_relu": lambda p: RNNCell(self._num_hidden, activation="relu",
                                          prefix=p),
            "rnn_tanh": lambda p: RNNCell(self._num_hidden, activation="tanh",
                                          prefix=p),
            "lstm": lambda p: LSTMCell(self._num_hidden, prefix=p),
            "gru": lambda p: GRUCell(self._num_hidden, prefix=p),
        }[self._mode]
        for i in range(self._num_layers):
            if self._bidirectional:
                stack.add(BidirectionalCell(
                    get_cell("%sl%d_d0_" % (self._prefix, i)),
                    get_cell("%sl%d_d1_" % (self._prefix, i)),
                    output_prefix="%sbi_l%d_" % (self._prefix, i)))
            else:
                stack.add(get_cell("%sl%d_d0_" % (self._prefix, i)))
            if self._dropout > 0 and i != self._num_layers - 1:
                stack.add(DropoutCell(self._dropout,
                                      prefix="%s_dropout%d_" % (self._prefix, i)))
        return stack


class SequentialRNNCell(BaseRNNCell):
    """Stack of cells (reference: rnn_cell.py:685)."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._override_cell_params = params is not None
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)
        if self._override_cell_params:
            assert cell._own_params, \
                "Either specify params for SequentialRNNCell or child cells, not both."
            cell.params._params.update(self.params._params)
        self.params._params.update(cell.params._params)

    @property
    def state_info(self):
        return sum([c.state_info for c in self._cells], [])

    def begin_state(self, **kwargs):
        assert not self._modified
        return sum([c.begin_state(**kwargs) for c in self._cells], [])

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._cells:
            assert not isinstance(cell, BidirectionalCell)
            n = len(cell.state_info)
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.append(state)
        return inputs, sum(next_states, [])

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        # unroll layer by layer so Bidirectional/Fused children work
        self.reset()
        num_cells = len(self._cells)
        p = 0
        next_states = []
        outputs = inputs
        for i, cell in enumerate(self._cells):
            n = len(cell.state_info)
            states = begin_state[p:p + n] if begin_state is not None else None
            p += n
            outputs, states = cell.unroll(
                length, inputs=outputs, begin_state=states,
                input_prefix=input_prefix, layout=layout,
                merge_outputs=None if i < num_cells - 1 else merge_outputs)
            next_states.extend(states)
        return outputs, next_states


class DropoutCell(BaseRNNCell):
    """Dropout between layers (reference: rnn_cell.py:763)."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix=prefix, params=params)
        self.dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self.dropout > 0:
            inputs = symbol.Dropout(data=inputs, p=self.dropout)
        return inputs, states

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        self.reset()
        if isinstance(inputs, symbol.Symbol) and merge_outputs is not False:
            output, _ = self(inputs, [])
            return output, []
        return super().unroll(length, inputs, begin_state, input_prefix, layout,
                              merge_outputs)


class ModifierCell(BaseRNNCell):
    """Base for cells wrapping another cell (reference: rnn_cell.py:797)."""

    def __init__(self, base_cell):
        super().__init__()
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        self._own_params = False
        return self.base_cell.params

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, init_sym=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(**kwargs)
        self.base_cell._modified = True
        return begin

    def unpack_weights(self, args):
        return self.base_cell.unpack_weights(args)

    def pack_weights(self, args):
        return self.base_cell.pack_weights(args)

    def __call__(self, inputs, states):
        raise NotImplementedError()


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (reference: rnn_cell.py:839)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, FusedRNNCell), \
            "FusedRNNCell does not support zoneout; unfuse() first."
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self.prev_output = None

    def reset(self):
        super().reset()
        self.prev_output = None

    def __call__(self, inputs, states):
        cell, p_outputs, p_states = (self.base_cell, self.zoneout_outputs,
                                     self.zoneout_states)
        next_output, next_states = cell(inputs, states)
        mask = lambda p, like: symbol.Dropout(
            symbol.ones_like(like), p=p)

        prev_output = self.prev_output if self.prev_output is not None \
            else symbol.zeros_like(next_output)
        output = (symbol.where(mask(p_outputs, next_output), next_output,
                               prev_output)
                  if p_outputs != 0.0 else next_output)
        states = ([symbol.where(mask(p_states, new_s), new_s, old_s)
                   for new_s, old_s in zip(next_states, states)]
                  if p_states != 0.0 else next_states)
        self.prev_output = output
        return output, states


class ResidualCell(ModifierCell):
    """Residual connection around a cell."""

    def __call__(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states


class BidirectionalCell(BaseRNNCell):
    """Bidirectional wrapper (reference: rnn_cell.py:881)."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__("", params=params)
        self._output_prefix = output_prefix
        self._override_cell_params = params is not None
        if self._override_cell_params:
            assert l_cell._own_params and r_cell._own_params
            l_cell.params._params.update(self.params._params)
            r_cell.params._params.update(self.params._params)
        self.params._params.update(l_cell.params._params)
        self.params._params.update(r_cell.params._params)
        self._cells = [l_cell, r_cell]

    def unpack_weights(self, args):
        return _cells_unpack_weights(self._cells, args)

    def pack_weights(self, args):
        return _cells_pack_weights(self._cells, args)

    def __call__(self, inputs, states):
        raise MXNetError("Bidirectional cannot be stepped. Please use unroll")

    @property
    def state_info(self):
        return sum([c.state_info for c in self._cells], [])

    def begin_state(self, **kwargs):
        assert not self._modified
        return sum([c.begin_state(**kwargs) for c in self._cells], [])

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        self.reset()
        if inputs is None:
            inputs = [symbol.Variable("%st%d_data" % (input_prefix, i))
                      for i in range(length)]
        elif isinstance(inputs, symbol.Symbol):
            axis = layout.find("T")
            inputs = symbol.SliceChannel(inputs, axis=axis, num_outputs=length,
                                         squeeze_axis=1)
            inputs = [inputs[i] for i in range(length)]
        l_cell, r_cell = self._cells
        if begin_state is None:
            l_begin = r_begin = None
        else:
            l_begin = begin_state[:len(l_cell.state_info)]
            r_begin = begin_state[len(l_cell.state_info):]
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs, begin_state=l_begin,
            layout=layout, merge_outputs=False)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=list(reversed(inputs)),
            begin_state=r_begin,
            layout=layout, merge_outputs=False)
        outputs = [symbol.Concat(l_o, r_o, dim=1,
                                 name="%st%d" % (self._output_prefix, i))
                   for i, (l_o, r_o) in enumerate(zip(l_outputs,
                                                      reversed(r_outputs)))]
        if merge_outputs:
            outputs = [symbol.expand_dims(o, axis=1) for o in outputs]
            outputs = symbol.Concat(*outputs, dim=1)
        states = l_states + r_states
        return outputs, states


def _cells_unpack_weights(cells, args):
    for cell in cells:
        args = cell.unpack_weights(args)
    return args


def _cells_pack_weights(cells, args):
    for cell in cells:
        args = cell.pack_weights(args)
    return args
