"""RNN cell symbol library.

Capability parity with the reference's ``python/mxnet/rnn/rnn_cell.py``:
step-composable cells (``__call__``), graph unrolling (``unroll``), the
fused multi-layer ``FusedRNNCell`` (lowers to the lax.scan-backed ``RNN``
op — the cuDNN-path analog), and exact pack/unpack interconversion between
the fused flat parameter blob and per-cell FC weights (layout documented in
ops/rnn_op.py).

Structure here differs from the reference: sequence marshalling lives in
two module-level helpers (``_as_step_list`` / ``_stack_steps``) shared by
every cell, gate projections go through one ``_linear`` helper, and the
two container cells (Sequential, Bidirectional) share a ``_MultiCell`` base
that owns parameter merging and state fan-out.
"""
from __future__ import annotations

from .. import symbol
from ..base import MXNetError
from ..ops.rnn_op import rnn_param_size, _layout, _gates

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "BidirectionalCell",
           "DropoutCell", "ModifierCell", "ZoneoutCell", "ResidualCell"]


# -- sequence marshalling ----------------------------------------------------


def _as_step_list(inputs, length, layout, prefix=""):
    """Normalize ``inputs`` into a list of per-step (N, C) symbols.

    Accepts None (fresh Variables), a single merged symbol (split along the
    time axis of ``layout``), or an existing list (returned as-is).
    """
    if inputs is None:
        return [symbol.Variable("%st%d_data" % (prefix, t))
                for t in range(length)]
    if isinstance(inputs, symbol.Symbol):
        if len(inputs) != 1:
            raise MXNetError("unroll expects a single-output symbol")
        steps = symbol.SliceChannel(inputs, axis=layout.find("T"),
                                    num_outputs=length, squeeze_axis=1)
        return [steps[t] for t in range(length)]
    return list(inputs)


def _stack_steps(outputs, time_axis):
    """Merge a list of per-step symbols into one along a new time axis."""
    expanded = [symbol.expand_dims(o, axis=time_axis) for o in outputs]
    return symbol.Concat(*expanded, dim=time_axis)


def _linear(data, weight, bias, n_out, name):
    return symbol.FullyConnected(data=data, weight=weight, bias=bias,
                                 num_hidden=n_out, name=name)


# -- parameter container -----------------------------------------------------


class RNNParams:
    """Lazily-created, prefix-namespaced Variable pool shared across steps."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        full = self._prefix + name
        if full not in self._params:
            self._params[full] = symbol.Variable(full, **kwargs)
        return self._params[full]


# -- base cell ---------------------------------------------------------------


class BaseRNNCell:
    """Contract: ``__call__(input, states) -> (output, new_states)`` plus
    ``state_info``/``begin_state`` for state bootstrapping and
    pack/unpack_weights for fused interop."""

    def __init__(self, prefix="", params=None):
        self._own_params = params is None
        self._params = params if params is not None else RNNParams(prefix)
        self._prefix = prefix
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    def __call__(self, inputs, states):
        raise NotImplementedError()

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def state_info(self):
        raise NotImplementedError()

    @property
    def state_shape(self):
        return [info["shape"] for info in self.state_info]

    @property
    def _gate_names(self):
        return ()

    def _step_name(self):
        self._counter += 1
        return "%st%d_" % (self._prefix, self._counter)

    def begin_state(self, func=None, _batch_ref=None, _ref_axis=0, **kwargs):
        """Initial-state symbols.

        ``_batch_ref`` (set by unroll) produces zeros whose batch dim tracks
        a data symbol at bind time; ``func`` delegates construction; the
        default is plain Variables the caller feeds.
        """
        if self._modified:
            raise MXNetError("cell was wrapped by a modifier; use the "
                             "modifier's begin_state")
        states = []
        for info in self.state_info:
            self._init_counter += 1
            name = "%sbegin_state_%d" % (self._prefix, self._init_counter)
            if func is not None:
                states.append(func(name=name, **kwargs))
            elif _batch_ref is not None:
                states.append(symbol._create(
                    "_rnn_begin_state", [_batch_ref],
                    {"shape": str(tuple(info["shape"])),
                     "batch_axis": str(_ref_axis)}, name=name))
            else:
                states.append(symbol.Variable(name))
        return states

    # fused interop: identity for plain cells
    def unpack_weights(self, args):
        return dict(args)

    def pack_weights(self, args):
        return dict(args)

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        """Step the cell ``length`` times over the time axis of ``layout``.

        Returns (outputs, final_states); outputs are a per-step list unless
        ``merge_outputs`` requests one stacked symbol.
        """
        self.reset()
        steps = _as_step_list(inputs, length, layout, input_prefix)
        states = begin_state if begin_state is not None else \
            self.begin_state(_batch_ref=steps[0], _ref_axis=0)
        outputs = []
        for step in steps:
            out, states = self(step, states)
            outputs.append(out)
        if merge_outputs:
            outputs = _stack_steps(outputs, 1)
        return outputs, states

    def _get_activation(self, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return symbol.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)


# -- elementary cells --------------------------------------------------------


class RNNCell(BaseRNNCell):
    """Elman cell: h' = act(W_x x + W_h h + b_x + b_h)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._w = {k: self.params.get("%s_weight" % k) for k in ("i2h", "h2h")}
        self._b = {k: self.params.get("%s_bias" % k) for k in ("i2h", "h2h")}

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("",)

    def __call__(self, inputs, states):
        name = self._step_name()
        pre = _linear(inputs, self._w["i2h"], self._b["i2h"],
                      self._num_hidden, name + "i2h") \
            + _linear(states[0], self._w["h2h"], self._b["h2h"],
                      self._num_hidden, name + "h2h")
        out = self._get_activation(pre, self._activation, name=name + "out")
        return out, [out]


class LSTMCell(BaseRNNCell):
    """LSTM with gate order i, f, c, o (matches the fused layout)."""

    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(prefix=prefix, params=params)
        from ..initializer import LSTMBias

        self._num_hidden = num_hidden
        self._w = {k: self.params.get("%s_weight" % k) for k in ("i2h", "h2h")}
        self._b = {"i2h": self.params.get(
                       "i2h_bias", init=LSTMBias(forget_bias=forget_bias)),
                   "h2h": self.params.get("h2h_bias")}

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"},
                {"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def __call__(self, inputs, states):
        name = self._step_name()
        h_prev, c_prev = states
        width = self._num_hidden * 4
        pre = _linear(inputs, self._w["i2h"], self._b["i2h"], width,
                      name + "i2h") \
            + _linear(h_prev, self._w["h2h"], self._b["h2h"], width,
                      name + "h2h")
        gate = symbol.SliceChannel(pre, num_outputs=4, axis=1,
                                   name=name + "slice")
        sigm = lambda s: symbol.Activation(s, act_type="sigmoid")
        tanh = lambda s: symbol.Activation(s, act_type="tanh")
        c_next = sigm(gate[1]) * c_prev + sigm(gate[0]) * tanh(gate[2])
        h_next = sigm(gate[3]) * tanh(c_next)
        return h_next, [h_next, c_next]


class GRUCell(BaseRNNCell):
    """GRU with gate order r, z, n (matches the fused layout)."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._w = {k: self.params.get("%s_weight" % k) for k in ("i2h", "h2h")}
        self._b = {k: self.params.get("%s_bias" % k) for k in ("i2h", "h2h")}

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_r", "_z", "_o")

    def __call__(self, inputs, states):
        name = self._step_name()
        h_prev = states[0]
        width = self._num_hidden * 3
        from_x = symbol.SliceChannel(
            _linear(inputs, self._w["i2h"], self._b["i2h"], width,
                    name + "i2h"),
            num_outputs=3, name=name + "i2h_slice")
        from_h = symbol.SliceChannel(
            _linear(h_prev, self._w["h2h"], self._b["h2h"], width,
                    name + "h2h"),
            num_outputs=3, name=name + "h2h_slice")
        reset = symbol.Activation(from_x[0] + from_h[0], act_type="sigmoid",
                                  name=name + "r_act")
        update = symbol.Activation(from_x[1] + from_h[1], act_type="sigmoid",
                                   name=name + "z_act")
        cand = symbol.Activation(from_x[2] + reset * from_h[2],
                                 act_type="tanh", name=name + "h_act")
        h_next = update * h_prev + (1.0 - update) * cand
        return h_next, [h_next]


# -- fused cell --------------------------------------------------------------


class FusedRNNCell(BaseRNNCell):
    """Multi-layer (optionally bidirectional) RNN backed by the fused ``RNN``
    op.  Cannot be stepped — only unrolled whole."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0.0, get_next_state=False,
                 forget_bias=1.0, prefix=None, params=None):
        super().__init__(prefix="%s_" % mode if prefix is None else prefix,
                         params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._forget_bias = forget_bias
        from ..initializer import FusedRNN

        # unpack->init->repack aware initializer rides on the Variable so
        # Module.init_params initializes the packed blob correctly
        self._parameter = self.params.get(
            "parameters", init=FusedRNN(None, num_hidden, num_layers, mode,
                                        bidirectional, forget_bias))
        self._directions = 2 if bidirectional else 1

    @property
    def state_info(self):
        layers = self._directions * self._num_layers
        n_states = 2 if self._mode == "lstm" else 1
        return [{"shape": (layers, 0, self._num_hidden),
                 "__layout__": "LNC"}] * n_states

    @property
    def _gate_names(self):
        return {"rnn_relu": [""], "rnn_tanh": [""],
                "lstm": ["_i", "_f", "_c", "_o"],
                "gru": ["_r", "_z", "_o"]}[self._mode]

    def __call__(self, inputs, states):
        raise MXNetError("FusedRNNCell cannot be stepped; use unroll()")

    # -- packed-parameter interop -------------------------------------------
    def _param_layout(self, input_size):
        return _layout(self._num_layers, self._num_hidden, self._mode,
                       self._bidirectional, input_size)

    def _infer_input_size(self, flat):
        """Invert the parameter-count formula for the input width."""
        import numpy as np

        g, d, H, L = (_gates(self._mode), self._directions,
                      self._num_hidden, self._num_layers)
        # flat.size = d*g*H*input + [first-layer h2h + upper layers + biases]
        fixed = d * g * H * H \
            + (L - 1) * d * g * H * (H * d + H) \
            + L * d * 2 * g * H
        return (int(flat.size) - fixed) // (d * g * H)

    def unpack_weights(self, args, input_size=None):
        """Flat ``parameters`` blob -> individual lX_dY_{i2h,h2h}_* arrays."""
        import numpy as np

        out = dict(args)
        flat = out.pop(self._prefix + "parameters")
        flat = np.asarray(flat.asnumpy() if hasattr(flat, "asnumpy")
                          else flat)
        if input_size is None:
            input_size = self._infer_input_size(flat)
        for name, offset, shape in self._param_layout(input_size):
            count = int(np.prod(shape))
            out[self._prefix + name] = \
                flat[offset:offset + count].reshape(shape).copy()
        return out

    def pack_weights(self, args, input_size=None):
        """Individual per-gate arrays -> flat ``parameters`` blob."""
        import numpy as np

        out = dict(args)
        pieces = {k[len(self._prefix):]: out.pop(k)
                  for k in list(out)
                  if k.startswith(self._prefix)
                  and ("_i2h_" in k or "_h2h_" in k)}

        def host(v):
            return np.asarray(v.asnumpy() if hasattr(v, "asnumpy") else v)

        if input_size is None:
            input_size = host(pieces["l0_d0_i2h_weight"]).shape[-1]
        flat = np.zeros(rnn_param_size(self._num_layers, self._num_hidden,
                                       self._mode, self._bidirectional,
                                       input_size),
                        dtype=host(next(iter(pieces.values()))).dtype)
        for name, offset, shape in self._param_layout(input_size):
            count = int(np.prod(shape))
            flat[offset:offset + count] = host(pieces[name]).reshape(-1)
        out[self._prefix + "parameters"] = flat
        return out

    # -- graph construction ---------------------------------------------------
    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        self.reset()
        time_axis = layout.find("T")
        # the RNN op wants TNC; merge lists ourselves along axis 0
        if inputs is None or isinstance(inputs, list):
            steps = _as_step_list(inputs, length, layout, input_prefix)
            seq = _stack_steps(steps, 0)
        elif time_axis == 1:
            seq = symbol.SwapAxis(inputs, dim1=0, dim2=1)
        else:
            seq = inputs
        states = begin_state if begin_state is not None else \
            self.begin_state(_batch_ref=seq, _ref_axis=1)

        rnn = symbol.RNN(seq, self._parameter, *states,
                         state_size=self._num_hidden,
                         num_layers=self._num_layers,
                         bidirectional=self._bidirectional, mode=self._mode,
                         p=self._dropout, state_outputs=self._get_next_state,
                         name="%srnn" % self._prefix)

        if self._get_next_state:
            outputs = rnn[0]
            next_states = [rnn[i + 1]
                           for i in range(len(self.state_info))]
        else:
            outputs = rnn if len(rnn) == 1 else rnn[0]
            next_states = []

        if time_axis == 1:
            outputs = symbol.SwapAxis(outputs, dim1=0, dim2=1)
        if not merge_outputs:
            split = symbol.SliceChannel(outputs, axis=time_axis,
                                        num_outputs=length, squeeze_axis=1)
            outputs = [split[t] for t in range(length)]
        return outputs, next_states

    def unfuse(self):
        """Equivalent stack of unfused cells (prefixes line up with the
        packed layout, so weights transfer via pack/unpack)."""
        factories = {
            "rnn_relu": lambda p: RNNCell(self._num_hidden,
                                          activation="relu", prefix=p),
            "rnn_tanh": lambda p: RNNCell(self._num_hidden,
                                          activation="tanh", prefix=p),
            "lstm": lambda p: LSTMCell(self._num_hidden, prefix=p),
            "gru": lambda p: GRUCell(self._num_hidden, prefix=p),
        }
        make = factories[self._mode]
        stack = SequentialRNNCell()
        for layer in range(self._num_layers):
            if self._bidirectional:
                stack.add(BidirectionalCell(
                    make("%sl%d_d0_" % (self._prefix, layer)),
                    make("%sl%d_d1_" % (self._prefix, layer)),
                    output_prefix="%sbi_l%d_" % (self._prefix, layer)))
            else:
                stack.add(make("%sl%d_d0_" % (self._prefix, layer)))
            if self._dropout > 0 and layer + 1 < self._num_layers:
                stack.add(DropoutCell(
                    self._dropout,
                    prefix="%s_dropout%d_" % (self._prefix, layer)))
        return stack


# -- container cells ---------------------------------------------------------


class _MultiCell(BaseRNNCell):
    """Shared machinery for cells made of child cells: parameter merging,
    state fan-out, and pack/unpack delegation."""

    def __init__(self, params=None, prefix=""):
        super().__init__(prefix=prefix, params=params)
        self._override_cell_params = params is not None
        self._cells = []

    def _adopt(self, cell):
        if self._override_cell_params:
            if not cell._own_params:
                raise MXNetError("give params to the container or to the "
                                 "child cells, not both")
            cell.params._params.update(self.params._params)
        self.params._params.update(cell.params._params)
        self._cells.append(cell)

    @property
    def state_info(self):
        return [info for c in self._cells for info in c.state_info]

    def begin_state(self, **kwargs):
        if self._modified:
            raise MXNetError("cell was wrapped by a modifier; use the "
                             "modifier's begin_state")
        return [s for c in self._cells for s in c.begin_state(**kwargs)]

    def _split_states(self, states):
        """Slice a flat state list into per-child chunks."""
        chunks, pos = [], 0
        for cell in self._cells:
            width = len(cell.state_info)
            chunks.append(states[pos:pos + width] if states is not None
                          else None)
            pos += width
        return chunks

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args


class SequentialRNNCell(_MultiCell):
    """Vertical stack: each child consumes the previous child's output."""

    def __init__(self, params=None):
        super().__init__(params=params)

    def add(self, cell):
        self._adopt(cell)

    def __call__(self, inputs, states):
        self._counter += 1
        new_states = []
        for cell, chunk in zip(self._cells, self._split_states(list(states))):
            if isinstance(cell, BidirectionalCell):
                raise MXNetError("BidirectionalCell cannot be stepped inside "
                                 "SequentialRNNCell; unroll instead")
            inputs, out_states = cell(inputs, chunk)
            new_states.extend(out_states)
        return inputs, new_states

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        # layer-wise unroll so Fused/Bidirectional children work
        self.reset()
        outputs = inputs
        final_states = []
        chunks = self._split_states(begin_state)
        last = len(self._cells) - 1
        for i, (cell, chunk) in enumerate(zip(self._cells, chunks)):
            outputs, states = cell.unroll(
                length, inputs=outputs, begin_state=chunk,
                input_prefix=input_prefix, layout=layout,
                merge_outputs=merge_outputs if i == last else None)
            final_states.extend(states)
        return outputs, final_states


class BidirectionalCell(_MultiCell):
    """Runs one child forward and one backward over time, concatenating the
    per-step outputs on the feature axis."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__(params=params)
        self._output_prefix = output_prefix
        self._adopt(l_cell)
        self._adopt(r_cell)

    def __call__(self, inputs, states):
        raise MXNetError("BidirectionalCell cannot be stepped; use unroll()")

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        self.reset()
        steps = _as_step_list(inputs, length, layout, input_prefix)
        fwd_cell, bwd_cell = self._cells
        fwd_begin, bwd_begin = self._split_states(begin_state)
        fwd_out, fwd_states = fwd_cell.unroll(
            length, inputs=steps, begin_state=fwd_begin, layout=layout,
            merge_outputs=False)
        bwd_out, bwd_states = bwd_cell.unroll(
            length, inputs=steps[::-1], begin_state=bwd_begin, layout=layout,
            merge_outputs=False)
        outputs = [symbol.Concat(f, b, dim=1,
                                 name="%st%d" % (self._output_prefix, t))
                   for t, (f, b) in enumerate(zip(fwd_out, bwd_out[::-1]))]
        if merge_outputs:
            outputs = _stack_steps(outputs, 1)
        return outputs, fwd_states + bwd_states


# -- pass-through / wrapper cells ---------------------------------------------


class DropoutCell(BaseRNNCell):
    """Stateless dropout between stacked layers."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix=prefix, params=params)
        self.dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self.dropout > 0:
            inputs = symbol.Dropout(data=inputs, p=self.dropout)
        return inputs, states

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        self.reset()
        # a merged symbol can be masked in one shot
        if isinstance(inputs, symbol.Symbol) and merge_outputs is not False:
            out, _ = self(inputs, [])
            return out, []
        return super().unroll(length, inputs, begin_state, input_prefix,
                              layout, merge_outputs)


class ModifierCell(BaseRNNCell):
    """Wraps a base cell, borrowing its params/states; subclasses override
    ``__call__`` to decorate the step function."""

    def __init__(self, base_cell):
        super().__init__()
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        self._own_params = False
        return self.base_cell.params

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, init_sym=None, **kwargs):
        if self._modified:
            raise MXNetError("doubly-modified cell; unwrap first")
        self.base_cell._modified = False
        try:
            return self.base_cell.begin_state(**kwargs)
        finally:
            self.base_cell._modified = True

    def unpack_weights(self, args):
        return self.base_cell.unpack_weights(args)

    def pack_weights(self, args):
        return self.base_cell.pack_weights(args)

    def __call__(self, inputs, states):
        raise NotImplementedError()


class ZoneoutCell(ModifierCell):
    """Zoneout (Krueger et al.): randomly carry previous outputs/states
    through instead of the new values."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        if isinstance(base_cell, FusedRNNCell):
            raise MXNetError("zoneout needs per-step access; unfuse() the "
                             "FusedRNNCell first")
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self.prev_output = None

    def reset(self):
        super().reset()
        self.prev_output = None

    @staticmethod
    def _carry(p, new, old):
        """new where a Bernoulli(1-p) mask fires, else old."""
        keep_mask = symbol.Dropout(symbol.ones_like(new), p=p)
        return symbol.where(keep_mask, new, old)

    def __call__(self, inputs, states):
        new_output, new_states = self.base_cell(inputs, states)
        prev = self.prev_output if self.prev_output is not None \
            else symbol.zeros_like(new_output)
        output = self._carry(self.zoneout_outputs, new_output, prev) \
            if self.zoneout_outputs else new_output
        if self.zoneout_states:
            new_states = [self._carry(self.zoneout_states, s_new, s_old)
                          for s_new, s_old in zip(new_states, states)]
        self.prev_output = output
        return output, new_states


class ResidualCell(ModifierCell):
    """Adds the cell input to its output (He-style skip over the step)."""

    def __call__(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        return output + inputs, states
