"""Profiler facade.

Reference: `src/engine/profiler.{h,cc}` + `python/mxnet/profiler.py` — per-op
engine timestamps dumped as Chrome trace-event JSON.  TPU-native: wraps the
JAX/XLA profiler (`jax.profiler`), whose traces open in TensorBoard/XProf
(strictly more detail than the reference's op spans: XLA HLO cost, TPU step
time, HBM usage).  The reference's chrome-trace file contract is kept:
``dump_profile()`` writes a chrome-trace JSON with whatever op spans were
recorded through the python-side span API.
"""
from __future__ import annotations

import json
import os
import time
import threading

__all__ = ["profiler_set_config", "profiler_set_state", "dump_profile",
           "Scope", "start", "stop", "record_host_wait", "record_input_wait",
           "record_step", "bump_metric_d2h", "bump_metric_sync",
           "record_request", "record_ckpt_stall", "record_ckpt_write",
           "bump_recovery", "step_stats", "reset_step_stats"]

_state = {"mode": "symbolic", "filename": "profile.json", "running": False,
          "events": [], "jax_trace_dir": None}
_lock = threading.Lock()

# ---------------------------------------------------------------------------
# Training-loop step accounting (always on — counters only; span events are
# recorded only while the profiler runs).  The async fit loop reports where
# the host thread's time went: blocked on device results (host_wait), blocked
# on the input pipeline (input_wait), or free to run ahead.  metric_d2h
# counts device->host array materializations on behalf of metrics — the
# transfers MXNET_METRIC_SYNC_PERIOD exists to eliminate.
# ---------------------------------------------------------------------------
_STEP_KEYS = ("steps", "host_wait_s", "input_wait_s", "metric_d2h",
              "metric_syncs", "ckpt_stall_s", "ckpt_writes", "last_ckpt_ms",
              "recoveries")
_FLOAT_STEP_KEYS = ("host_wait_s", "input_wait_s", "ckpt_stall_s",
                    "last_ckpt_ms")
_step = dict.fromkeys(_STEP_KEYS, 0)
for _k in _FLOAT_STEP_KEYS:
    _step[_k] = 0.0
_step["t0"] = time.time()

# Per-request serving records (decode.DecodeServer retirements): each is a
# dict with queue_wait_s (submit -> admission), ttft_s (submit -> first
# token), tokens, decode_tokens_per_sec.  Bounded so a long-lived server
# cannot grow the profiler without bound; step_stats() reports p50/p95 over
# whatever is retained.
_REQ_CAP = 4096
_requests = []


def _percentile(values, q):
    """Nearest-rank percentile of a non-empty sorted list."""
    idx = min(len(values) - 1, max(0, int(round(q * (len(values) - 1)))))
    return values[idx]


def _span(name, t0, dur):
    if _state["running"]:
        _state["events"].append({
            "name": name, "cat": "loop", "ph": "X", "ts": int(t0 * 1e6),
            "dur": int(dur * 1e6), "pid": os.getpid(),
            "tid": threading.get_ident()})


def record_host_wait(seconds):
    """Time the loop spent blocked on a device result (fence/metric sync)."""
    with _lock:
        _step["host_wait_s"] += seconds
        _span("host_wait", time.time() - seconds, seconds)


def record_input_wait(seconds):
    """Time the loop spent waiting for the input pipeline's next batch."""
    with _lock:
        _step["input_wait_s"] += seconds
        _span("input_wait", time.time() - seconds, seconds)


def record_step(n=1):
    """One (or n) training steps dispatched."""
    with _lock:
        _step["steps"] += n


def bump_metric_d2h(n=1):
    """n device->host transfers performed on behalf of a metric."""
    with _lock:
        _step["metric_d2h"] += n


def bump_metric_sync(n=1):
    """n device-accumulator drains (each moves the whole accumulator)."""
    with _lock:
        _step["metric_syncs"] += n


def record_ckpt_stall(seconds):
    """Time the training loop's host thread spent on checkpointing work
    (elastic fence snapshot + write submission; the ENTIRE save when
    MXNET_CKPT_ASYNC=0).  Feeds ``checkpoint_stall_fraction`` in
    ``step_stats`` — the number async fenced checkpointing exists to
    drive toward zero."""
    with _lock:
        _step["ckpt_stall_s"] += seconds
        _span("ckpt_stall", time.time() - seconds, seconds)


def record_ckpt_write(ms):
    """One committed fence checkpoint written (by the writer thread or
    inline): duration in milliseconds."""
    with _lock:
        _step["ckpt_writes"] += 1
        _step["last_ckpt_ms"] = float(ms)
        _span("ckpt_write", time.time() - ms / 1e3, ms / 1e3)


def bump_recovery(n=1):
    """n elastic recovery events (resume-from-checkpoint at startup, or a
    mid-fit mesh shrink/regrow reconfiguration)."""
    with _lock:
        _step["recoveries"] += n


def record_request(queue_wait_s, ttft_s, tokens, decode_s):
    """One served request retired (decode.DecodeServer): time queued
    before admission, time to first token (from submit), tokens
    delivered, and the wall time its post-first-token decode took."""
    rec = {"queue_wait_s": float(queue_wait_s), "ttft_s": float(ttft_s),
           "tokens": int(tokens),
           "decode_tokens_per_sec":
               (int(tokens) - 1) / max(float(decode_s), 1e-9)
               if tokens > 1 else 0.0}
    with _lock:
        _requests.append(rec)
        if len(_requests) > _REQ_CAP:
            del _requests[:len(_requests) - _REQ_CAP]
        _span("request", time.time() - max(float(ttft_s), 0.0),
              max(float(ttft_s), 0.0))


def reset_step_stats():
    with _lock:
        for k in _STEP_KEYS:
            _step[k] = 0
        for k in _FLOAT_STEP_KEYS:
            _step[k] = 0.0
        _step["t0"] = time.time()
        del _requests[:]


def step_stats():
    """Snapshot of loop accounting plus the derived bench-contract ratios:
    ``input_stall_fraction`` (share of wall time blocked on input) and
    ``host_syncs_per_step`` (metric-driven d2h transfers per step)."""
    with _lock:
        out = {k: _step[k] for k in _STEP_KEYS}
        wall = max(time.time() - _step["t0"], 1e-9)
        reqs = list(_requests)
    out["wall_s"] = wall
    if reqs:
        qw = sorted(r["queue_wait_s"] for r in reqs)
        tf = sorted(r["ttft_s"] for r in reqs)
        ts = sorted(r["decode_tokens_per_sec"] for r in reqs)
        out["requests"] = {
            "count": len(reqs),
            "tokens": sum(r["tokens"] for r in reqs),
            "queue_wait_p50_s": _percentile(qw, 0.50),
            "queue_wait_p95_s": _percentile(qw, 0.95),
            "ttft_p50_s": _percentile(tf, 0.50),
            "ttft_p95_s": _percentile(tf, 0.95),
            "decode_tokens_per_sec_p50": _percentile(ts, 0.50),
        }
    out["input_stall_fraction"] = min(out["input_wait_s"] / wall, 1.0)
    out["host_wait_fraction"] = min(out["host_wait_s"] / wall, 1.0)
    out["checkpoint_stall_fraction"] = min(out["ckpt_stall_s"] / wall, 1.0)
    steps = max(out["steps"], 1)
    out["host_syncs_per_step"] = out["metric_d2h"] / steps
    return out


def profiler_set_config(mode="symbolic", filename="profile.json"):
    """Set up the profiler (reference: python/mxnet/profiler.py:10)."""
    _state["mode"] = mode
    _state["filename"] = filename


def profiler_set_state(state="stop"):
    """'run' or 'stop' (reference: profiler.py:30)."""
    import jax

    if state == "run" and not _state["running"]:
        _state["running"] = True
        _state["t0"] = time.time()
        trace_dir = os.path.splitext(_state["filename"])[0] + "_xla"
        try:
            jax.profiler.start_trace(trace_dir)
            _state["jax_trace_dir"] = trace_dir
        except Exception:  # profiling backend may be unavailable (CPU tests)
            _state["jax_trace_dir"] = None
    elif state == "stop" and _state["running"]:
        _state["running"] = False
        if _state["jax_trace_dir"]:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass


def start():
    profiler_set_state("run")


def stop():
    profiler_set_state("stop")


def is_running():
    return _state["running"]


class Scope:
    """Record one named span into the chrome trace (engine OprExecStat analog)."""

    def __init__(self, name, category="operator"):
        self.name = name
        self.category = category

    def __enter__(self):
        self._t0 = time.time()
        return self

    def __exit__(self, *exc):
        if _state["running"]:
            with _lock:
                _state["events"].append({
                    "name": self.name, "cat": self.category, "ph": "X",
                    "ts": int(self._t0 * 1e6),
                    "dur": int((time.time() - self._t0) * 1e6),
                    "pid": os.getpid(), "tid": threading.get_ident(),
                })


def dump_profile():
    """Write chrome-trace JSON (reference: profiler.py:46 dump_profile)."""
    with _lock:
        payload = {"traceEvents": list(_state["events"]), "displayTimeUnit": "ms"}
        with open(_state["filename"], "w") as f:
            json.dump(payload, f)


# reference env_var.md:71-79 — start profiling at library load
from . import config as _config

if _config.get("MXNET_PROFILER_AUTOSTART"):
    start()
