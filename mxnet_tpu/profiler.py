"""Profiler facade over the unified telemetry subsystem (mxnet_tpu.obs).

Reference: `src/engine/profiler.{h,cc}` + `python/mxnet/profiler.py` — per-op
engine timestamps dumped as Chrome trace-event JSON.  TPU-native: the span
store is now ``obs.timeline`` (an always-on bounded ring buffer), the loop
counters live in ``obs.registry`` (typed metrics with JSON-lines and
Prometheus exporters), and this module keeps the reference's API as a thin
compatibility facade: ``dump_profile()`` still writes a chrome-trace JSON
of whatever spans were recorded — now merged with the ``jax.profiler``
trace directory when one was captured, so host spans and the XLA device
timeline open as ONE Perfetto view.

Thread-safety contract (this module's historical holes, now closed):
``profiler_set_state`` and ``dump_profile`` mutate/read shared state under
the module lock; ``start()`` clears stale events from any prior run; the
span store is bounded the same way the request store always was.
"""
from __future__ import annotations

import os
import threading
import time

from . import obs as _obs
from .obs.metrics import percentile as _nearest_rank

__all__ = ["profiler_set_config", "profiler_set_state", "dump_profile",
           "Scope", "start", "stop", "record_host_wait", "record_input_wait",
           "record_step", "bump_metric_d2h", "bump_metric_sync",
           "record_request", "record_ckpt_stall", "record_ckpt_write",
           "bump_recovery", "step_stats", "reset_step_stats"]

_state = {"mode": "symbolic", "filename": "profile.json", "running": False,
          "jax_trace_dir": None}
_lock = threading.Lock()

# ---------------------------------------------------------------------------
# Training-loop step accounting (always on — counters only; op-granularity
# span events are recorded only while the profiler runs).  The async fit
# loop reports where the host thread's time went: blocked on device results
# (host_wait), blocked on the input pipeline (input_wait), or free to run
# ahead.  metric_d2h counts device->host array materializations on behalf
# of metrics — the transfers MXNET_METRIC_SYNC_PERIOD exists to eliminate.
# Storage is the obs registry, so the same numbers are scrapeable over
# /metrics and exportable as JSON lines without a second bookkeeping path.
# ---------------------------------------------------------------------------
_R = _obs.registry
_c_steps = _R.counter("mx_steps", "training steps dispatched")
_c_host_wait = _R.counter("mx_host_wait_seconds",
                          "host time blocked on device results")
_c_input_wait = _R.counter("mx_input_wait_seconds",
                           "host time blocked on the input pipeline")
_c_metric_d2h = _R.counter("mx_metric_d2h",
                           "device->host transfers on behalf of metrics")
_c_metric_syncs = _R.counter("mx_metric_syncs",
                             "device metric-accumulator drains")
_c_ckpt_stall = _R.counter("mx_ckpt_stall_seconds",
                           "loop-thread time spent on checkpoint work")
_c_ckpt_writes = _R.counter("mx_ckpt_writes",
                            "committed fence checkpoints")
_g_last_ckpt_ms = _R.gauge("mx_last_ckpt_ms",
                           "duration of the last committed checkpoint write")
_c_recoveries = _R.counter("mx_recoveries",
                           "elastic recovery events (resume/shrink/regrow)")
# per-request serving SLOs (decode.DecodeServer retirements); histograms
# keep a bounded sample reservoir — the cap the old _requests list had
_c_requests = _R.counter("mx_requests", "served requests retired")
_c_req_tokens = _R.counter("mx_request_tokens",
                           "tokens delivered to retired requests")
_h_queue_wait = _R.histogram("mx_request_queue_wait_seconds",
                             "submit -> admission wait per request")
_h_ttft = _R.histogram("mx_request_ttft_seconds",
                       "submit -> first token per request")
_h_decode_rate = _R.histogram(
    "mx_request_decode_tokens_per_sec",
    "post-first-token decode rate per request",
    buckets=(1, 2, 5, 10, 25, 50, 100, 250, 500, 1000, 5000, 10000))

_t0 = time.time()

# the families this facade OWNS (and may therefore zero): other
# subsystems' registry series (serve-loop mirrors, liveness gauges, user
# metrics) are not this module's to reset
_OWNED_METRICS = (_c_steps, _c_host_wait, _c_input_wait, _c_metric_d2h,
                  _c_metric_syncs, _c_ckpt_stall, _c_ckpt_writes,
                  _g_last_ckpt_ms, _c_recoveries, _c_requests,
                  _c_req_tokens, _h_queue_wait, _h_ttft, _h_decode_rate)


def _percentile(values, q):
    """Nearest-rank percentile of a sorted list — ``None`` on empty input
    (callers must guard; the historical version raised IndexError)."""
    return _nearest_rank(values, q)


def _loop_span(name, t0, dur):
    """Always-on loop span (host_wait/input_wait/ckpt_*/request) into the
    bounded timeline; gated only by MXNET_TELEMETRY."""
    if _obs.enabled():
        _obs.timeline.add_span(name, t0, dur, cat="loop")


def record_host_wait(seconds):
    """Time the loop spent blocked on a device result (fence/metric sync)."""
    _c_host_wait.inc(seconds)
    _loop_span("host_wait", time.time() - seconds, seconds)


def record_input_wait(seconds):
    """Time the loop spent waiting for the input pipeline's next batch."""
    _c_input_wait.inc(seconds)
    _loop_span("input_wait", time.time() - seconds, seconds)


def record_step(n=1):
    """One (or n) training steps dispatched."""
    _c_steps.inc(n)


def bump_metric_d2h(n=1):
    """n device->host transfers performed on behalf of a metric."""
    _c_metric_d2h.inc(n)


def bump_metric_sync(n=1):
    """n device-accumulator drains (each moves the whole accumulator)."""
    _c_metric_syncs.inc(n)


def record_ckpt_stall(seconds):
    """Time the training loop's host thread spent on checkpointing work
    (elastic fence snapshot + write submission; the ENTIRE save when
    MXNET_CKPT_ASYNC=0).  Feeds ``checkpoint_stall_fraction`` in
    ``step_stats`` — the number async fenced checkpointing exists to
    drive toward zero."""
    _c_ckpt_stall.inc(seconds)
    _loop_span("ckpt_stall", time.time() - seconds, seconds)


def record_ckpt_write(ms):
    """One committed fence checkpoint written (by the writer thread or
    inline): duration in milliseconds."""
    _c_ckpt_writes.inc()
    _g_last_ckpt_ms.set(float(ms))
    _loop_span("ckpt_write", time.time() - ms / 1e3, ms / 1e3)


def bump_recovery(n=1):
    """n elastic recovery events (resume-from-checkpoint at startup, or a
    mid-fit mesh shrink/regrow reconfiguration)."""
    _c_recoveries.inc(n)


def record_request(queue_wait_s, ttft_s, tokens, decode_s):
    """One served request retired (decode.DecodeServer): time queued
    before admission, time to first token (from submit), tokens
    delivered, and the wall time its post-first-token decode took."""
    tokens = int(tokens)
    _c_requests.inc()
    _c_req_tokens.inc(tokens)
    _h_queue_wait.observe(float(queue_wait_s))
    _h_ttft.observe(float(ttft_s))
    if tokens > 1:
        _h_decode_rate.observe((tokens - 1) / max(float(decode_s), 1e-9))
    _loop_span("request", time.time() - max(float(ttft_s), 0.0),
               max(float(ttft_s), 0.0))


def reset_step_stats():
    """Zero the loop counters, request histograms and the per-program
    roofline timings — a bench's measurement window starts here.  Only
    the facade-owned series reset; other subsystems' registry metrics
    (serve-loop mirrors, liveness gauges, user counters) are untouched."""
    global _t0
    with _lock:
        for m in _OWNED_METRICS:
            m.reset()
        _obs.programs.reset()
        _t0 = time.time()


def step_stats():
    """Snapshot of loop accounting plus the derived bench-contract ratios:
    ``input_stall_fraction`` (share of wall time blocked on input) and
    ``host_syncs_per_step`` (metric-driven d2h transfers per step)."""
    with _lock:
        t0 = _t0
    out = {
        "steps": int(_c_steps.get()),
        "host_wait_s": _c_host_wait.get(),
        "input_wait_s": _c_input_wait.get(),
        "metric_d2h": int(_c_metric_d2h.get()),
        "metric_syncs": int(_c_metric_syncs.get()),
        "ckpt_stall_s": _c_ckpt_stall.get(),
        "ckpt_writes": int(_c_ckpt_writes.get()),
        "last_ckpt_ms": _g_last_ckpt_ms.get(),
        "recoveries": int(_c_recoveries.get()),
    }
    wall = max(time.time() - t0, 1e-9)
    out["wall_s"] = wall
    nreq = int(_c_requests.get())
    if nreq:
        out["requests"] = {
            "count": nreq,
            "tokens": int(_c_req_tokens.get()),
            "queue_wait_p50_s": _h_queue_wait.percentile(0.50),
            "queue_wait_p95_s": _h_queue_wait.percentile(0.95),
            "ttft_p50_s": _h_ttft.percentile(0.50),
            "ttft_p95_s": _h_ttft.percentile(0.95),
        }
        if _h_decode_rate.count:
            out["requests"]["decode_tokens_per_sec_p50"] = \
                _h_decode_rate.percentile(0.50)
            out["requests"]["decode_tokens_per_sec_p95"] = \
                _h_decode_rate.percentile(0.95)
    out["input_stall_fraction"] = min(out["input_wait_s"] / wall, 1.0)
    out["host_wait_fraction"] = min(out["host_wait_s"] / wall, 1.0)
    out["checkpoint_stall_fraction"] = min(out["ckpt_stall_s"] / wall, 1.0)
    steps = max(out["steps"], 1)
    out["host_syncs_per_step"] = out["metric_d2h"] / steps
    return out


def profiler_set_config(mode="symbolic", filename="profile.json"):
    """Set up the profiler (reference: python/mxnet/profiler.py:10)."""
    with _lock:
        _state["mode"] = mode
        _state["filename"] = filename


def profiler_set_state(state="stop"):
    """'run' or 'stop' (reference: profiler.py:30).  Serialized under the
    module lock — concurrent callers can no longer interleave the
    running-flag flip with the jax trace start/stop."""
    import jax

    with _lock:
        if state == "run" and not _state["running"]:
            # a fresh profile window: stale span events from a prior run
            # must not leak into this run's dump
            _obs.timeline.clear()
            _state["running"] = True
            trace_dir = os.path.splitext(_state["filename"])[0] + "_xla"
            try:
                jax.profiler.start_trace(trace_dir)
                _state["jax_trace_dir"] = trace_dir
            except Exception:  # profiling backend unavailable (CPU tests)
                _state["jax_trace_dir"] = None
        elif state == "stop" and _state["running"]:
            _state["running"] = False
            if _state["jax_trace_dir"]:
                try:
                    jax.profiler.stop_trace()
                except Exception:
                    pass


def start():
    profiler_set_state("run")


def stop():
    profiler_set_state("stop")


def is_running():
    return _state["running"]


class Scope:
    """Record one named span into the trace (engine OprExecStat analog).

    Op-granularity spans (imperative dispatch, eager per-node walks) are
    recorded only while the profiler runs — they are high-frequency and
    would otherwise churn the always-on ring; the loop-accounting spans
    above are always on."""

    def __init__(self, name, category="operator"):
        self.name = name
        self.category = category

    def __enter__(self):
        self._t0 = time.time()
        return self

    def __exit__(self, *exc):
        if _state["running"] and _obs.enabled():
            _obs.timeline.add_span(self.name, self._t0,
                                   time.time() - self._t0,
                                   cat=self.category)
        return False


def dump_profile():
    """Write chrome-trace JSON (reference: profiler.py:46 dump_profile):
    the current timeline ring contents, merged with any Chrome-format
    traces the ``jax.profiler`` capture left in its trace directory."""
    with _lock:
        _obs.timeline.export(_state["filename"],
                             jax_trace_dir=_state["jax_trace_dir"])


# reference env_var.md:71-79 — start profiling at library load
from . import config as _config

if _config.get("MXNET_PROFILER_AUTOSTART"):
    start()
