"""Profiler facade.

Reference: `src/engine/profiler.{h,cc}` + `python/mxnet/profiler.py` — per-op
engine timestamps dumped as Chrome trace-event JSON.  TPU-native: wraps the
JAX/XLA profiler (`jax.profiler`), whose traces open in TensorBoard/XProf
(strictly more detail than the reference's op spans: XLA HLO cost, TPU step
time, HBM usage).  The reference's chrome-trace file contract is kept:
``dump_profile()`` writes a chrome-trace JSON with whatever op spans were
recorded through the python-side span API.
"""
from __future__ import annotations

import json
import os
import time
import threading

__all__ = ["profiler_set_config", "profiler_set_state", "dump_profile",
           "Scope", "start", "stop"]

_state = {"mode": "symbolic", "filename": "profile.json", "running": False,
          "events": [], "jax_trace_dir": None}
_lock = threading.Lock()


def profiler_set_config(mode="symbolic", filename="profile.json"):
    """Set up the profiler (reference: python/mxnet/profiler.py:10)."""
    _state["mode"] = mode
    _state["filename"] = filename


def profiler_set_state(state="stop"):
    """'run' or 'stop' (reference: profiler.py:30)."""
    import jax

    if state == "run" and not _state["running"]:
        _state["running"] = True
        _state["t0"] = time.time()
        trace_dir = os.path.splitext(_state["filename"])[0] + "_xla"
        try:
            jax.profiler.start_trace(trace_dir)
            _state["jax_trace_dir"] = trace_dir
        except Exception:  # profiling backend may be unavailable (CPU tests)
            _state["jax_trace_dir"] = None
    elif state == "stop" and _state["running"]:
        _state["running"] = False
        if _state["jax_trace_dir"]:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass


def start():
    profiler_set_state("run")


def stop():
    profiler_set_state("stop")


def is_running():
    return _state["running"]


class Scope:
    """Record one named span into the chrome trace (engine OprExecStat analog)."""

    def __init__(self, name, category="operator"):
        self.name = name
        self.category = category

    def __enter__(self):
        self._t0 = time.time()
        return self

    def __exit__(self, *exc):
        if _state["running"]:
            with _lock:
                _state["events"].append({
                    "name": self.name, "cat": self.category, "ph": "X",
                    "ts": int(self._t0 * 1e6),
                    "dur": int((time.time() - self._t0) * 1e6),
                    "pid": os.getpid(), "tid": threading.get_ident(),
                })


def dump_profile():
    """Write chrome-trace JSON (reference: profiler.py:46 dump_profile)."""
    with _lock:
        payload = {"traceEvents": list(_state["events"]), "displayTimeUnit": "ms"}
        with open(_state["filename"], "w") as f:
            json.dump(payload, f)


# reference env_var.md:71-79 — start profiling at library load
from . import config as _config

if _config.get("MXNET_PROFILER_AUTOSTART"):
    start()
