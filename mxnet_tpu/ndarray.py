"""NDArray — the imperative array type.

TPU-native re-design of the reference NDArray
(`include/mxnet/ndarray.h:376-433`, `python/mxnet/ndarray.py`).  Instead of a
ref-counted Chunk over Storage + an engine variable, an NDArray owns an
immutable ``jax.Array``; XLA's async dispatch plays the role of the
dependency engine (every op returns immediately with a future-backed array;
``wait_to_read`` == ``block_until_ready``).  Mutation (`+=`, ``x[:] = v``,
aux-state updates) rebinds the underlying buffer — the ownership protocol
that replaces in-place writes (SURVEY §7 hard part (a)).

Operator functions (``mxnet_tpu.ndarray.relu`` etc.) are generated from the
op registry at import, mirroring `_init_ndarray_module`
(`python/mxnet/ndarray.py:2120+`).
"""
from __future__ import annotations

import functools
import struct
from collections import deque

import numpy as np

from .base import MXNetError, numeric_types
from .context import Context, cpu, current_context
from . import registry as _reg

__all__ = ["NDArray", "array", "empty", "zeros", "ones", "full", "arange",
           "save", "load", "concatenate", "imperative_invoke", "waitall"]

_DTYPE_ALIASES = {
    "float16": np.float16, "float32": np.float32, "float64": np.float64,
    "uint8": np.uint8, "int32": np.int32, "int8": np.int8, "int64": np.int64,
    "bool": np.bool_, "bfloat16": "bfloat16",
}

# ring buffer of recently produced arrays, so waitall() has something to block on
_RECENT = deque(maxlen=128)

# generated op functions (slice, abs, sum, ...) shadow builtins at module
# level, exactly as in the reference's mx.nd namespace — keep real ones here
_py_slice = slice
_py_abs = abs


def _np_dtype(dtype):
    if dtype is None:
        return np.float32
    if isinstance(dtype, str):
        if dtype == "bfloat16":
            import jax.numpy as jnp
            return jnp.bfloat16
        return np.dtype(dtype).type
    return dtype


def _jax_put(value, ctx):
    import jax

    return jax.device_put(value, ctx.jax_device)


class NDArray:
    """Multi-dimensional array on a device context."""

    __slots__ = ("_data", "_ctx", "_base", "_idx", "writable")

    def __init__(self, data, ctx=None, base=None, idx=None, writable=True):
        self._ctx = ctx if ctx is not None else current_context()
        self._data = data
        self._base = base   # parent NDArray when this is a write-through view
        self._idx = idx
        self.writable = writable

    # -- data access -------------------------------------------------------
    @property
    def data(self):
        """The underlying jax.Array (re-sliced from base for views)."""
        if self._base is not None:
            return self._base.data[self._idx]
        return self._data

    def _set_data(self, new_data):
        # commit host arrays to this context's device immediately: leaving
        # numpy in _data would re-upload it on EVERY jitted call that takes
        # it as an argument (through a remote-device tunnel that is seconds
        # per step, not microseconds)
        if isinstance(new_data, np.ndarray):
            new_data = _jax_put(new_data, self._ctx)
        if self._base is not None:
            self._base._set_data(self._base.data.at[self._idx].set(new_data))
        else:
            self._data = new_data
        _RECENT.append(new_data)

    @property
    def handle(self):
        return self  # ctypes-handle compat shim

    # -- basic properties --------------------------------------------------
    @property
    def shape(self):
        return tuple(self.data.shape)

    @property
    def dtype(self):
        dt = self.data.dtype
        try:
            return np.dtype(dt).type
        except TypeError:
            return dt

    @property
    def size(self):
        n = 1
        for s in self.shape:
            n *= s
        return n

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def context(self):
        return self._ctx

    @property
    def T(self):
        from . import ndarray as nd
        return nd.transpose(self)

    # -- synchronization (engine facade) -----------------------------------
    def wait_to_read(self):
        """Block until the value is computed (reference: ndarray.h:153)."""
        import jax
        jax.block_until_ready(self.data)

    wait_to_write = wait_to_read

    # -- conversions -------------------------------------------------------
    def asnumpy(self):
        return np.asarray(self.data)

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def astype(self, dtype):
        import jax.numpy as jnp
        return NDArray(jnp.asarray(self.data, dtype=_np_dtype(dtype)), self._ctx)

    def copy(self):
        # jax buffers are immutable and mutation rebinds, so aliasing is a
        # correct copy: later writes to either NDArray cannot affect the other
        return NDArray(self.data, self._ctx)

    def copyto(self, other):
        """Copy to another NDArray or a context (reference: ndarray.py:533)."""
        if isinstance(other, NDArray):
            other._set_data(_jax_put(self.data, other._ctx))
            return other
        elif isinstance(other, Context):
            return NDArray(_jax_put(self.data, other), other)
        raise TypeError("copyto expects NDArray or Context")

    def as_in_context(self, context):
        if context == self._ctx:
            return self
        return self.copyto(context)

    def reshape(self, shape, **kwargs):
        import jax.numpy as jnp
        if isinstance(shape, int):
            shape = (shape,)
        # support -1 and 0 (copy-dim) semantics of mxnet Reshape
        shape = tuple(self.shape[i] if s == 0 else s for i, s in enumerate(shape)) \
            if 0 in shape else tuple(shape)
        return NDArray(jnp.reshape(self.data, shape), self._ctx)

    def broadcast_to(self, shape):
        import jax.numpy as jnp
        return NDArray(jnp.broadcast_to(self.data, tuple(shape)), self._ctx)

    # -- indexing ----------------------------------------------------------
    def __getitem__(self, key):
        if isinstance(key, NDArray):
            key = key.asnumpy()
        if isinstance(key, _py_slice) and key.step is not None and key.step != 1:
            raise ValueError("slice step cannot be non-unit")
        # base is self (not the root): chained views write through recursively
        # with each key kept relative to its own parent
        return NDArray(self.data[key], self._ctx, base=self, idx=key)

    def __setitem__(self, key, value):
        if not self.writable:
            raise MXNetError("trying to write to an immutable NDArray")
        import jax.numpy as jnp
        if isinstance(value, NDArray):
            value = value.data
        elif isinstance(value, (np.ndarray, list, tuple)) or np.isscalar(value):
            value = jnp.asarray(value, dtype=self.data.dtype)
        if isinstance(key, _py_slice) and key == _py_slice(None):
            value = jnp.broadcast_to(value, self.shape).astype(self.data.dtype)
            self._set_data(jnp.asarray(value))
        else:
            if isinstance(key, NDArray):
                key = key.asnumpy()
            self._set_data(self.data.at[key].set(value))

    # -- arithmetic (dispatches through the op registry so autograd sees it)
    def _binary(self, other, op, scalar_op, rop=False):
        from . import ndarray as nd
        if isinstance(other, NDArray):
            lhs, rhs = (other, self) if rop else (self, other)
            return getattr(nd, op)(lhs, rhs)
        elif isinstance(other, numeric_types):
            return getattr(nd, scalar_op)(self, scalar=float(other))
        raise TypeError("unsupported operand type %s" % type(other))

    def __add__(self, other):
        return self._binary(other, "broadcast_plus", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, other):
        return self._binary(other, "broadcast_minus", "_minus_scalar")

    def __rsub__(self, other):
        return self._binary(other, "broadcast_minus", "_rminus_scalar", rop=True)

    def __mul__(self, other):
        return self._binary(other, "broadcast_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __div__(self, other):
        return self._binary(other, "broadcast_div", "_div_scalar")

    __truediv__ = __div__

    def __rdiv__(self, other):
        return self._binary(other, "broadcast_div", "_rdiv_scalar", rop=True)

    __rtruediv__ = __rdiv__

    def __pow__(self, other):
        return self._binary(other, "broadcast_power", "_power_scalar")

    def __rpow__(self, other):
        return self._binary(other, "broadcast_power", "_rpower_scalar", rop=True)

    def __mod__(self, other):
        return self._binary(other, "broadcast_mod", "_mod_scalar")

    def __neg__(self):
        from . import ndarray as nd
        return nd.negative(self)

    def __eq__(self, other):
        if isinstance(other, (NDArray,) + numeric_types):
            return self._binary(other, "broadcast_equal", "_equal_scalar")
        return NotImplemented

    def __ne__(self, other):
        if isinstance(other, (NDArray,) + numeric_types):
            return self._binary(other, "broadcast_not_equal", "_not_equal_scalar")
        return NotImplemented

    def __gt__(self, other):
        return self._binary(other, "broadcast_greater", "_greater_scalar")

    def __ge__(self, other):
        return self._binary(other, "broadcast_greater_equal", "_greater_equal_scalar")

    def __lt__(self, other):
        return self._binary(other, "broadcast_lesser", "_lesser_scalar")

    def __le__(self, other):
        return self._binary(other, "broadcast_lesser_equal", "_lesser_equal_scalar")

    def __hash__(self):
        return id(self)

    def __bool__(self):
        if self.size == 1:
            return bool(self.asscalar())
        raise ValueError("The truth value of an NDArray with multiple elements is ambiguous")

    # in-place: rebind buffer (ownership protocol; engine would track WAR here)
    def __iadd__(self, other):
        self._set_data((self + other).data.astype(self.data.dtype))
        return self

    def __isub__(self, other):
        self._set_data((self - other).data.astype(self.data.dtype))
        return self

    def __imul__(self, other):
        self._set_data((self * other).data.astype(self.data.dtype))
        return self

    def __idiv__(self, other):
        self._set_data((self / other).data.astype(self.data.dtype))
        return self

    __itruediv__ = __idiv__

    def __len__(self):
        return self.shape[0]

    def __repr__(self):
        return "<NDArray %s @%s>\n%s" % (
            "x".join(str(s) for s in self.shape), self._ctx, self.asnumpy())

    # -- serialization helpers (see save/load below) -----------------------


# ---------------------------------------------------------------------------
# Creation
# ---------------------------------------------------------------------------

def array(source_array, ctx=None, dtype=None):
    ctx = ctx or current_context()
    if isinstance(source_array, NDArray):
        src = source_array.asnumpy()
    else:
        src = np.asarray(source_array)
    if dtype is None:
        dtype = src.dtype if src.dtype != np.float64 else np.float32
    src = src.astype(_np_dtype(dtype) if not isinstance(dtype, str) or dtype != "bfloat16"
                     else _np_dtype(dtype), copy=False)
    return NDArray(_jax_put(src, ctx), ctx)


def empty(shape, ctx=None, dtype=None):
    return zeros(shape, ctx, dtype)


def zeros(shape, ctx=None, dtype=None):
    import jax.numpy as jnp
    ctx = ctx or current_context()
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray(_jax_put(jnp.zeros(shape, dtype=_np_dtype(dtype)), ctx), ctx)


def ones(shape, ctx=None, dtype=None):
    import jax.numpy as jnp
    ctx = ctx or current_context()
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray(_jax_put(jnp.ones(shape, dtype=_np_dtype(dtype)), ctx), ctx)


def full(shape, val, ctx=None, dtype=None):
    import jax.numpy as jnp
    ctx = ctx or current_context()
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray(_jax_put(jnp.full(shape, val, dtype=_np_dtype(dtype)), ctx), ctx)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None):
    import jax.numpy as jnp
    ctx = ctx or current_context()
    arr = np.arange(start, stop, step, dtype=_np_dtype(dtype) or np.float32)
    if repeat != 1:
        arr = np.repeat(arr, repeat)
    return NDArray(_jax_put(jnp.asarray(arr, dtype=_np_dtype(dtype)), ctx), ctx)


def concatenate(arrays, axis=0, always_copy=True):
    import jax.numpy as jnp
    assert arrays
    return NDArray(jnp.concatenate([a.data for a in arrays], axis=axis), arrays[0]._ctx)


@functools.lru_cache(maxsize=None)
def _fence_fn():
    import jax

    return jax.jit(lambda v: v + 1)


def waitall():
    """Block on ALL dispatched work (reference: Engine::WaitForAll).

    Two layers: drain the ring of recently produced arrays, then push a
    trivial fence computation onto every local device and block on it —
    XLA's per-device execution streams are FIFO, so the fence completing
    means everything enqueued before it has completed, including work whose
    result arrays fell out of the ring.
    """
    import jax
    import jax.numpy as jnp

    while _RECENT:
        jax.block_until_ready(_RECENT.popleft())
    fence = _fence_fn()
    for dev in jax.local_devices():
        x = jax.device_put(jnp.zeros((), jnp.float32), dev)
        jax.block_until_ready(fence(x))


# ---------------------------------------------------------------------------
# Serialization — .params format: magic, count, names, dtype/shape headers,
# raw little-endian bytes.  (API-compatible with reference save/load,
# src/ndarray/ndarray.cc:605-700; byte format is our own.)
# ---------------------------------------------------------------------------

_MAGIC = b"MXTPU001"


def save(fname, data):
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, dict):
        names, arrays = list(data.keys()), list(data.values())
    else:
        names, arrays = [""] * len(data), list(data)
    with open(fname, "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<q", len(arrays)))
        for name, arr in zip(names, arrays):
            nb = name.encode()
            npy = arr.asnumpy()
            dt = str(npy.dtype).encode()
            f.write(struct.pack("<i", len(nb)))
            f.write(nb)
            f.write(struct.pack("<i", len(dt)))
            f.write(dt)
            f.write(struct.pack("<i", npy.ndim))
            f.write(struct.pack("<%dq" % npy.ndim, *npy.shape))
            raw = np.ascontiguousarray(npy).tobytes()
            f.write(struct.pack("<q", len(raw)))
            f.write(raw)


def load(fname):
    """Load from a .params path or an in-memory ``bytes`` blob (the latter
    serves the predict API, reference c_predict_api.h:59-77)."""
    import io as _io

    if isinstance(fname, (bytes, bytearray, memoryview)):
        return _load_stream(_io.BytesIO(bytes(fname)), "<bytes>")
    with open(fname, "rb") as f:
        return _load_stream(f, fname)


def _load_stream(f, fname):
    magic = f.read(8)
    if magic != _MAGIC:
        raise MXNetError("Invalid NDArray file format: %s" % fname)
    (count,) = struct.unpack("<q", f.read(8))
    names, arrays = [], []
    for _ in range(count):
        (nlen,) = struct.unpack("<i", f.read(4))
        name = f.read(nlen).decode()
        (dlen,) = struct.unpack("<i", f.read(4))
        dt = np.dtype(f.read(dlen).decode())
        (ndim,) = struct.unpack("<i", f.read(4))
        shape = struct.unpack("<%dq" % ndim, f.read(8 * ndim)) if ndim else ()
        (rawlen,) = struct.unpack("<q", f.read(8))
        buf = np.frombuffer(f.read(rawlen), dtype=dt).reshape(shape)
        names.append(name)
        arrays.append(array(buf, dtype=dt.type))
    if any(names):
        return dict(zip(names, arrays))
    return arrays


# ---------------------------------------------------------------------------
# Imperative dispatch — generated op functions
# ---------------------------------------------------------------------------

def imperative_invoke(opdef, nd_inputs, raw_attrs, out=None, is_train=None):
    """The single imperative dispatch path (MXImperativeInvoke analog)."""
    from . import autograd

    if opdef.key_var_num_args and opdef.key_var_num_args not in raw_attrs:
        raw_attrs = dict(raw_attrs)
        raw_attrs[opdef.key_var_num_args] = str(len(nd_inputs))
    attrs = opdef.parse_attrs(raw_attrs)
    n_aux = len(opdef.list_aux(attrs))
    if n_aux and len(nd_inputs) == opdef.n_inputs(attrs) + n_aux:
        nd_aux = nd_inputs[-n_aux:]
        nd_inputs = nd_inputs[:-n_aux]
    else:
        nd_aux = []
    if is_train is None:
        is_train = autograd.is_training()
    rng = None
    if opdef.needs_rng:
        from . import random as _rnd

        rng = _rnd.split_key()
    outs, new_aux = _reg.invoke(
        opdef,
        [a.data for a in nd_inputs],
        attrs,
        is_train=is_train,
        rng=rng,
        aux=[a.data for a in nd_aux],
    )
    recorded_aux = list(nd_aux)
    for nd_a, new_a in zip(nd_aux, new_aux):
        nd_a._set_data(new_a)
    ctx = nd_inputs[0]._ctx if nd_inputs else current_context()
    out_nds = [NDArray(o, ctx) for o in outs]
    # hide internal outputs (Dropout mask, BatchNorm mean/var) as the
    # reference's num_visible_outputs does
    n_vis = opdef.n_visible_outputs(attrs)
    out_nds = out_nds[:n_vis]
    for o in out_nds:
        _RECENT.append(o.data)
    if out is not None:
        # write into the destination arrays and record THOSE on the tape, so
        # downstream ops consuming `out` stay connected in autograd replay
        outs_req = out if isinstance(out, (list, tuple)) else [out]
        for dst, src in zip(outs_req, out_nds):
            dst._set_data(src.data)
        if autograd.is_recording():
            autograd.record_op(opdef, attrs, nd_inputs, list(outs_req), rng,
                               aux=recorded_aux)
        return out
    if autograd.is_recording():
        autograd.record_op(opdef, attrs, nd_inputs, out_nds, rng,
                           aux=recorded_aux)
    if len(out_nds) == 1:
        return out_nds[0]
    return out_nds


def _make_op_func(opdef):
    def op_func(*args, **kwargs):
        out = kwargs.pop("out", None)
        kwargs.pop("name", None)
        nd_args = list(args)
        # accept NDArray kwargs by argument name (e.g. data=, weight=)
        if any(isinstance(v, NDArray) for v in kwargs.values()):
            probe = {k: v for k, v in kwargs.items() if not isinstance(v, NDArray)}
            attrs0 = opdef.parse_attrs(probe)
            names = opdef.list_arguments(attrs0) + opdef.list_aux(attrs0)
            for n in names:
                if n in kwargs and isinstance(kwargs[n], NDArray):
                    nd_args.append(kwargs.pop(n))
        return imperative_invoke(opdef, nd_args, kwargs, out)

    op_func.__name__ = opdef.name
    op_func.__doc__ = opdef.doc + "\n\nParameters\n----------\n" + opdef.schema.doc()
    return op_func


def _init_ndarray_module():
    """Generate module-level functions for every registered op."""
    import sys

    mod = sys.modules[__name__]
    for name in _reg.list_ops():
        opdef = _reg.get_op(name)
        setattr(mod, name, _make_op_func(opdef))


def onehot_encode(indices, out):
    """Legacy one-hot into `out` (reference: ndarray.py:986)."""
    from . import ndarray as nd
    depth = out.shape[1]
    res = nd.one_hot(indices, depth=depth)
    out._set_data(res.data)
    return out
