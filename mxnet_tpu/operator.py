"""User-defined operators (``mx.operator``).

Capability parity with the reference's Custom op stack
(`python/mxnet/operator.py` + `src/operator/custom/custom.cc`): users
subclass :class:`CustomOp` (imperative ``forward``/``backward`` over
NDArrays) and :class:`CustomOpProp` (shape/type inference + operator
construction), register the prop under a name, and use the op as
``mx.sym.Custom(..., op_type=name)`` or ``mx.nd.Custom(...)``.

TPU-native execution: the user's Python runs on the host through
``jax.pure_callback`` — the analog of the reference routing Custom through
``FnProperty::kAsync`` engine ops so arbitrary Python can block without
stalling the device — and a ``jax.custom_vjp`` pairs the user's backward
with XLA's autodiff, so Custom nodes compose with jit/vjp exactly like
built-in ops.

**Purity contract (deviation from the reference).**  The reference's
Custom is an effectful engine op; under XLA, ``pure_callback``'s contract
lets the runtime elide the call when outputs are unused, cache it across
identical invocations, and re-execute it (e.g. under remat).  CustomOp
``forward``/``backward`` must therefore be *pure functions of their
inputs*: no counters, no internal state carried across calls, no side
effects the program depends on.  Ops that need mutable state belong in
:class:`~mxnet_tpu.module.PythonModule` (host-side module computation),
which runs outside jit.
"""
from __future__ import annotations

import numpy as np

from .base import MXNetError
from .registry import OpDef, register_op

__all__ = ["CustomOp", "CustomOpProp", "register", "get_prop"]

_CUSTOM_PROPS = {}

# attrs handled by the framework, never forwarded to the user's prop
_SYSTEM_KEYS = ("op_type", "ctx_group")


class CustomOp:
    """Base for user ops.  Subclasses implement ``forward`` and (when the
    op participates in training) ``backward``; both receive NDArray lists
    and write results with :meth:`assign`.

    Both methods MUST be pure functions of their inputs (see the module
    docstring): the XLA runtime may skip, cache, or replay them."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise MXNetError("%s does not implement backward"
                         % type(self).__name__)

    @staticmethod
    def assign(dst, req, src):
        """Write ``src`` into ``dst`` honoring the grad request."""
        if req in ("null", 0):
            return
        if req in ("add", "add_to", 3):
            dst[:] = dst + src
        else:  # write / inplace
            dst[:] = src


class CustomOpProp:
    """Declares a custom op's signature: argument/output names, shape and
    dtype inference, and the operator factory."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), []

    def need_top_grad(self):
        return self.need_top_grad_

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return CustomOp()


def register(reg_name):
    """Class decorator registering a CustomOpProp under ``reg_name``."""

    def deco(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError("register expects a CustomOpProp subclass")
        _CUSTOM_PROPS[reg_name] = prop_cls
        return prop_cls

    return deco


def get_prop(attrs):
    """Instantiate the registered prop from a Custom node's attrs."""
    op_type = attrs.get("op_type")
    if not op_type:
        raise MXNetError("Custom requires op_type=<registered name>")
    prop_cls = _CUSTOM_PROPS.get(op_type)
    if prop_cls is None:
        raise MXNetError("Custom op %r is not registered (have: %s)"
                         % (op_type, sorted(_CUSTOM_PROPS)))
    kwargs = {k: v for k, v in
              (attrs.items() if hasattr(attrs, "items") else [])
              if k not in _SYSTEM_KEYS and not k.startswith("__")}
    return prop_cls(**kwargs)


# ---------------------------------------------------------------------------
# the Custom OpDef: host callbacks under custom_vjp
# ---------------------------------------------------------------------------

def _wrap(host_arrays):
    """numpy -> NDArray views for the user's imperative code."""
    from . import ndarray as nd

    return [nd.array(a) for a in host_arrays]


def _custom_fcompute(attrs, inputs, aux, octx):
    import jax
    import jax.numpy as jnp

    prop = get_prop(attrs)
    if prop.list_auxiliary_states():
        raise MXNetError(
            "Custom aux states are not supported on the jit path; Custom "
            "forward/backward must be pure functions of their inputs "
            "(pure_callback may elide/cache/replay them) — stateful "
            "computation belongs in PythonModule")
    in_shapes = [tuple(v.shape) for v in inputs]
    _, out_shapes, _ = prop.infer_shape([list(s) for s in in_shapes])
    in_types = [np.dtype(v.dtype) for v in inputs]
    _, out_types, _ = prop.infer_type(list(in_types))
    out_struct = tuple(jax.ShapeDtypeStruct(tuple(s), np.dtype(t))
                       for s, t in zip(out_shapes, out_types))
    in_struct = tuple(jax.ShapeDtypeStruct(s, t)
                      for s, t in zip(in_shapes, in_types))
    op = prop.create_operator("cpu", [list(s) for s in in_shapes], in_types)
    is_train = bool(octx.is_train)
    n_out = len(out_struct)

    def host_forward(*host_ins):
        in_data = _wrap(host_ins)
        out_data = _wrap([np.zeros(s.shape, s.dtype) for s in out_struct])
        op.forward(is_train, ["write"] * n_out, in_data, out_data, [])
        return tuple(o.asnumpy() for o in out_data)

    def host_backward(*host_args):
        k = len(inputs)
        ins = list(host_args[:k])
        outs = list(host_args[k:k + n_out])
        cts = list(host_args[k + n_out:])
        in_data = _wrap(ins)
        out_data = _wrap(outs)
        out_grad = _wrap(cts)
        in_grad = _wrap([np.zeros_like(a) for a in ins])
        op.backward(["write"] * k, out_grad, in_data, out_data, in_grad, [])
        return tuple(g.asnumpy() for g in in_grad)

    @jax.custom_vjp
    def run(*ins):
        return jax.pure_callback(host_forward, out_struct, *ins)

    def run_fwd(*ins):
        outs = jax.pure_callback(host_forward, out_struct, *ins)
        return outs, (ins, outs)

    def run_bwd(residual, cts):
        ins, outs = residual
        grads = jax.pure_callback(host_backward, in_struct,
                                  *(tuple(ins) + tuple(outs) + tuple(cts)))
        return tuple(grads)

    run.defvjp(run_fwd, run_bwd)
    return list(run(*inputs)), list(aux)


def _custom_infer_shape(attrs, in_shapes, aux_shapes):
    prop = get_prop(attrs)
    ins, outs, aux = prop.infer_shape([list(s) if s else s
                                       for s in in_shapes])
    return [tuple(s) for s in ins], [tuple(s) for s in outs], \
        [tuple(s) for s in (aux or [])]


def _custom_infer_type(attrs, in_types, aux_types):
    prop = get_prop(attrs)
    seed = [t if t is not None else np.dtype(np.float32) for t in in_types]
    ins, outs, aux = prop.infer_type(seed)
    return list(ins), list(outs), list(aux or aux_types)


def _custom_n_inputs(attrs):
    return len(get_prop(attrs).list_arguments())


def _custom_n_outputs(attrs):
    return len(get_prop(attrs).list_outputs())


register_op(OpDef(
    "Custom", _custom_fcompute,
    num_inputs=_custom_n_inputs, num_outputs=_custom_n_outputs,
    arguments=lambda a: get_prop(a).list_arguments(),
    outputs=lambda a: get_prop(a).list_outputs(),
    infer_shape=_custom_infer_shape, infer_type=_custom_infer_type,
    needs_train=True, hint="custom",
    doc="User-defined Python operator; forward/backward run on the host "
        "via pure_callback under a custom_vjp "
        "(ref: src/operator/custom/custom.cc, python/mxnet/operator.py)."))
