"""Learning-rate schedules.

API surface of the reference's ``python/mxnet/lr_scheduler.py`` (names,
``__call__(num_update)`` protocol, optimizer sets ``base_lr``), re-designed
stateless: each schedule is a closed-form function of ``num_update`` rather
than a stateful counter loop.  That matters here because the fused train
step evaluates the schedule host-side every step — a pure function stays
correct under replay, checkpoint resume, and out-of-order queries, none of
which the mutate-in-place formulation tolerates.

Extra TPU-era schedules (cosine, polynomial, linear warmup wrapper) are
provided beyond the reference pair.
"""
from __future__ import annotations

import bisect
import logging
import math

__all__ = ["LRScheduler", "FactorScheduler", "MultiFactorScheduler",
           "PolyScheduler", "CosineScheduler", "WarmupScheduler"]


class LRScheduler:
    """Maps the optimizer's global update count to a learning rate."""

    def __init__(self, base_lr=0.01):
        self.base_lr = base_lr
        self._last_announced = None

    def _rate(self, num_update):
        raise NotImplementedError()

    def __call__(self, num_update):
        lr = self._rate(max(int(num_update), 0))
        if lr != self._last_announced:
            if self._last_announced is not None:
                logging.info("Update[%d]: learning rate is now %0.5e",
                             num_update, lr)
            self._last_announced = lr
        return lr


class FactorScheduler(LRScheduler):
    """Geometric decay: one ``factor`` multiplication every ``step``
    updates, floored at ``stop_factor_lr``."""

    def __init__(self, step, factor=1, stop_factor_lr=1e-8):
        super().__init__()
        if step < 1:
            raise ValueError("schedule step must be >= 1")
        if factor > 1.0:
            raise ValueError("factor must be <= 1 so the lr decays")
        self.step = step
        self.factor = factor
        self.stop_factor_lr = stop_factor_lr

    def _rate(self, num_update):
        drops = max(num_update - 1, 0) // self.step
        return max(self.base_lr * self.factor ** drops, self.stop_factor_lr)


class MultiFactorScheduler(LRScheduler):
    """Multiply by ``factor`` as each milestone in ``step`` is passed."""

    def __init__(self, step, factor=1):
        super().__init__()
        if not isinstance(step, list) or not step:
            raise ValueError("step must be a non-empty list of milestones")
        if any(s < 1 for s in step):
            raise ValueError("schedule step must be >= 1")
        if any(b <= a for a, b in zip(step, step[1:])):
            raise ValueError("milestones must be strictly increasing")
        if factor > 1.0:
            raise ValueError("factor must be <= 1 so the lr decays")
        self.step = step
        self.factor = factor

    def _rate(self, num_update):
        # milestones passed = how many entries are < num_update
        drops = bisect.bisect_left(self.step, num_update)
        return self.base_lr * self.factor ** drops


class PolyScheduler(LRScheduler):
    """Polynomial decay to ``final_lr`` over ``max_update`` steps."""

    def __init__(self, max_update, power=2.0, final_lr=0.0):
        super().__init__()
        if max_update < 1:
            raise ValueError("max_update must be >= 1")
        self.max_update = max_update
        self.power = power
        self.final_lr = final_lr

    def _rate(self, num_update):
        frac = min(num_update / self.max_update, 1.0)
        return self.final_lr + (self.base_lr - self.final_lr) \
            * (1.0 - frac) ** self.power


class CosineScheduler(LRScheduler):
    """Cosine decay to ``final_lr`` over ``max_update`` steps."""

    def __init__(self, max_update, final_lr=0.0):
        super().__init__()
        if max_update < 1:
            raise ValueError("max_update must be >= 1")
        self.max_update = max_update
        self.final_lr = final_lr

    def _rate(self, num_update):
        frac = min(num_update / self.max_update, 1.0)
        return self.final_lr + 0.5 * (self.base_lr - self.final_lr) \
            * (1.0 + math.cos(math.pi * frac))


class WarmupScheduler(LRScheduler):
    """Linear ramp from ``start_lr`` for ``warmup_steps``, then delegate to
    the wrapped schedule (which sees the post-warmup update count)."""

    def __init__(self, child, warmup_steps, start_lr=0.0):
        super().__init__()
        if warmup_steps < 1:
            raise ValueError("warmup_steps must be >= 1")
        self.child = child
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr

    @property
    def base_lr(self):
        return self.child.base_lr

    @base_lr.setter
    def base_lr(self, v):
        # the optimizer assigns base_lr before the child exists (object
        # construction order) — tolerate that window
        if hasattr(self, "child"):
            self.child.base_lr = v

    def _rate(self, num_update):
        if num_update < self.warmup_steps:
            frac = num_update / self.warmup_steps
            return self.start_lr + (self.base_lr - self.start_lr) * frac
        return self.child._rate(num_update - self.warmup_steps)
