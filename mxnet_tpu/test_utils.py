"""Numerics-testing toolkit.

Capability parity with the reference's ``python/mxnet/test_utils.py``
(SURVEY §4): finite-difference gradient checks, forward/backward checks
against numpy references, and cross-context consistency.  The TPU twist:
"interpret-mode vs compiled-XLA" and "1-chip vs N-chip" stand in for the
reference's "CPU vs GPU" oracle pair.

Design differences from the reference implementation:

* ``numeric_grad`` is built around a single ``objective()`` closure and a
  central-difference probe loop over flattened coordinates — state
  save/restore happens once per argument, not once per element.
* ``check_numeric_gradient`` projects multi-output symbols to a scalar with
  an explicit random-projection head composed via the symbol API.
* consistency checking compares every context against an explicit oracle
  (highest-precision context) with per-dtype tolerances.
"""
from __future__ import annotations

import logging
import time

import numpy as np

from . import ndarray as nd
from . import symbol as sym_mod
from .context import current_context

_rng = np.random.RandomState(1234)

# -- basic helpers ----------------------------------------------------------


def default_context():
    return current_context()


def default_dtype():
    return np.float32


def random_arrays(*shapes):
    """Random float32 arrays (a scalar np.float32 for 0-d shapes)."""
    out = [_rng.standard_normal(s).astype(default_dtype()) if s
           else np.float32(_rng.standard_normal()) for s in shapes]
    return out[0] if len(out) == 1 else out


def rand_ndarray(shape, dtype=np.float32):
    return nd.array(_rng.standard_normal(shape).astype(dtype))


def rand_shape_2d(dim0=10, dim1=10):
    return tuple(_rng.randint(1, d + 1) for d in (dim0, dim1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return tuple(_rng.randint(1, d + 1) for d in (dim0, dim1, dim2))


def np_reduce(dat, axis, keepdims, numpy_reduce_func):
    """Apply a numpy reduction with mxnet-style axis/keepdims semantics."""
    axes = ((axis,) if isinstance(axis, int)
            else tuple(axis) if axis is not None
            else tuple(range(dat.ndim)))
    out = numpy_reduce_func(dat, axis=axes)
    if keepdims:
        shape = tuple(1 if i in axes else s for i, s in enumerate(dat.shape))
        out = np.asarray(out).reshape(shape)
    return out


def same(a, b):
    return np.array_equal(a, b)


def reldiff(a, b):
    """L1 relative difference in [0, 1]."""
    num = np.abs(a - b).sum()
    den = np.abs(a).sum() + np.abs(b).sum()
    return 0.0 if num == 0 else float(num / den)


def _to_numpy(x):
    return x.asnumpy() if isinstance(x, nd.NDArray) else np.asarray(x)


def almost_equal(a, b, rtol=1e-5, atol=1e-20):
    return np.allclose(_to_numpy(a), _to_numpy(b), rtol=rtol, atol=atol)


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-20, names=("a", "b")):
    """np.allclose with an error report locating the worst element."""
    a, b = _to_numpy(a), _to_numpy(b)
    if np.allclose(a, b, rtol=rtol, atol=atol):
        return
    err = np.abs(a - b)
    worst = np.unravel_index(int(np.argmax(err)), err.shape) if err.ndim \
        else ()
    raise AssertionError(
        "%s and %s differ beyond rtol=%g atol=%g: max |diff| = %g at %s "
        "(%s=%s, %s=%s)" % (names[0], names[1], rtol, atol, err.max(),
                            worst, names[0], a[worst], names[1], b[worst]))


# -- argument marshalling ---------------------------------------------------


def _named_arrays(names, values, ctx, what):
    """Normalize a dict-or-sequence of inputs into {name: NDArray}."""
    if values is None:
        return None
    if isinstance(values, dict):
        if set(values) != set(names):
            raise ValueError("%s mismatch: symbol wants %s, got %s"
                             % (what, sorted(names), sorted(values)))
        pairs = values.items()
    else:
        pairs = zip(names, values)
    return {k: v if isinstance(v, nd.NDArray) else nd.array(v, ctx=ctx)
            for k, v in pairs}


def simple_forward(sym, ctx=None, is_train=False, **inputs):
    """One forward pass on numpy inputs; numpy output(s)."""
    ctx = ctx or default_context()
    args = {k: nd.array(v, ctx=ctx) for k, v in inputs.items()}
    outs = [o.asnumpy()
            for o in sym.bind(ctx, args=args,
                              grad_req="null").forward(is_train=is_train)]
    return outs[0] if len(outs) == 1 else outs


# -- finite differences -----------------------------------------------------


def numeric_grad(executor, location, aux_states=None, eps=1e-4,
                 use_forward_train=True):
    """Central-difference gradient of ``sum(outputs[0])`` w.r.t. each float
    input.

    Evaluates the executor as a black-box objective; each coordinate gets a
    symmetric probe (±eps/2), and the argument buffer is restored once after
    its coordinate sweep.
    """
    aux_states = aux_states or {}

    def objective(name, perturbed):
        executor.arg_dict[name][:] = perturbed
        for aux_name, aux_val in aux_states.items():
            executor.aux_dict[aux_name][:] = aux_val
        executor.forward(is_train=use_forward_train)
        return float(executor.outputs[0].asnumpy().sum())

    # seed all buffers with the base point first
    for name, value in location.items():
        executor.arg_dict[name][:] = value

    grads = {}
    for name, value in location.items():
        base = np.asarray(value, dtype=np.float64).reshape(-1)
        grads[name] = np.zeros(np.shape(value), np.float32)
        if np.asarray(value).dtype.kind != "f":
            continue
        flat_grad = grads[name].reshape(-1)
        shape = np.shape(value)
        for i in range(base.size):
            probe = base.copy()
            probe[i] += eps / 2.0
            hi = objective(name, probe.reshape(shape))
            probe[i] -= eps
            lo = objective(name, probe.reshape(shape))
            flat_grad[i] = (hi - lo) / eps
        executor.arg_dict[name][:] = value  # restore the base point
    return grads


def check_numeric_gradient(sym, location, aux_states=None, numeric_eps=1e-3,
                           rtol=1e-2, atol=None, grad_nodes=None,
                           use_forward_train=True, ctx=None):
    """Assert symbolic backward matches central differences.

    The symbol's (possibly tensor-valued) output is reduced to a scalar by
    an elementwise product with a fixed random projection, so every output
    element influences the objective.
    """
    ctx = ctx or default_context()
    atol = atol if atol is not None else 1e-4

    location = _named_arrays(sym.list_arguments(), location, ctx, "location")
    aux_states = _named_arrays(sym.list_auxiliary_states(), aux_states, ctx,
                               "aux_states")
    host_location = {k: v.asnumpy() for k, v in location.items()}
    host_aux = {k: v.asnumpy() for k, v in aux_states.items()} \
        if aux_states else None

    if grad_nodes is None:
        grad_req = {k: "write" for k in sym.list_arguments()}
    elif isinstance(grad_nodes, dict):
        grad_req = dict(grad_nodes)
    else:
        grad_req = {k: "write" for k in grad_nodes}

    # scalar objective: sum(output * random_projection).  The projection and
    # seed grads draw from a per-call generator so results do not depend on
    # which tests ran earlier in the session (global-RNG order flakiness).
    _, out_shapes, _ = sym.infer_shape(
        **{k: v.shape for k, v in location.items()})
    call_rng = np.random.RandomState(1234)
    proj_value = call_rng.uniform(0.1, 1.1, out_shapes[0])
    scalar = sym_mod.MakeLoss(
        sym_mod.sum(sym * sym_mod.Variable("__random_proj")))

    bind_args = dict(location)
    bind_args["__random_proj"] = nd.array(proj_value, ctx=ctx)
    seed_grads = {k: call_rng.normal(0, 0.01, bind_args[k].shape)
                  for k in list(grad_req) + ["__random_proj"]}
    exe = scalar.bind(ctx, args=bind_args,
                      args_grad={k: nd.array(v, ctx=ctx)
                                 for k, v in seed_grads.items()},
                      grad_req=grad_req, aux_states=aux_states)
    exe.forward(is_train=True)
    exe.backward()

    fd = numeric_grad(exe, host_location, host_aux, eps=numeric_eps,
                      use_forward_train=use_forward_train)
    for name, req in grad_req.items():
        got = exe.grad_dict[name].asnumpy()
        if req == "null":
            assert_almost_equal(seed_grads[name], got, rtol, atol)
        elif req == "add":
            assert_almost_equal(fd[name], got - seed_grads[name], rtol, atol,
                                ("NUMERIC_%s" % name, "SYMBOLIC_%s" % name))
        elif req == "write":
            assert_almost_equal(fd[name], got, rtol, atol,
                                ("NUMERIC_%s" % name, "SYMBOLIC_%s" % name))
        else:
            raise ValueError("unknown grad_req %r for %s" % (req, name))


# -- numpy-reference checks -------------------------------------------------


def check_symbolic_forward(sym, location, expected, rtol=1e-4, atol=None,
                           aux_states=None, ctx=None):
    """Assert forward outputs match expected numpy arrays."""
    ctx = ctx or default_context()
    location = _named_arrays(sym.list_arguments(), location, ctx, "location")
    aux_states = _named_arrays(sym.list_auxiliary_states(), aux_states, ctx,
                               "aux_states")
    if isinstance(expected, dict):
        expected = [expected[k] for k in sym.list_outputs()]

    exe = sym.bind(ctx, args=location,
                   args_grad={k: nd.zeros(v.shape, ctx=ctx)
                              for k, v in location.items()},
                   aux_states=aux_states)
    exe.forward()
    for name, want, got in zip(sym.list_outputs(), expected, exe.outputs):
        assert_almost_equal(want, got, rtol, atol if atol is not None
                            else 1e-5,
                            ("EXPECTED_%s" % name, "FORWARD_%s" % name))
    return exe.outputs


def check_symbolic_backward(sym, location, out_grads, expected, rtol=1e-5,
                            atol=None, aux_states=None, grad_req="write",
                            ctx=None):
    """Assert backward gradients match expected numpy arrays."""
    ctx = ctx or default_context()
    atol = atol if atol is not None else 1e-8
    location = _named_arrays(sym.list_arguments(), location, ctx, "location")
    aux_states = _named_arrays(sym.list_auxiliary_states(), aux_states, ctx,
                               "aux_states")
    if not isinstance(expected, dict):
        expected = dict(zip(sym.list_arguments(), expected))
    if isinstance(grad_req, str):
        grad_req = {k: grad_req for k in location}
    elif not isinstance(grad_req, dict):
        grad_req = dict(zip(location, grad_req))

    seed = {k: _rng.standard_normal(location[k].shape) for k in expected}
    exe = sym.bind(ctx, args=location,
                   args_grad={k: nd.array(v, ctx=ctx)
                              for k, v in seed.items()},
                   aux_states=aux_states, grad_req=grad_req)
    exe.forward(is_train=True)
    if isinstance(out_grads, dict):
        out_grads = [out_grads[k] for k in sym.list_outputs()]
    if isinstance(out_grads, (list, tuple)):
        out_grads = [g if isinstance(g, nd.NDArray) else nd.array(g, ctx=ctx)
                     for g in out_grads]
    exe.backward(out_grads)

    for name, want in expected.items():
        got = exe.grad_dict[name].asnumpy()
        req = grad_req[name]
        if req == "null":
            assert_almost_equal(seed[name], got, rtol, atol)
        elif req == "add":
            assert_almost_equal(want, got - seed[name], rtol, atol,
                                ("EXPECTED_%s" % name, "BACKWARD_%s" % name))
        elif req == "write":
            assert_almost_equal(want, got, rtol, atol,
                                ("EXPECTED_%s" % name, "BACKWARD_%s" % name))
        else:
            raise ValueError("unknown grad_req %r for %s" % (req, name))
    return exe.grad_arrays


# -- timing + cross-context oracle ------------------------------------------


def check_speed(sym, location=None, ctx=None, N=20, grad_req="write",
                typ="whole", **kwargs):
    """Mean seconds per forward (+backward when typ='whole') over N runs,
    after one warmup (compilation) pass."""
    ctx = ctx or default_context()
    shapes = kwargs if location is None \
        else {k: v.shape for k, v in location.items()}
    exe = sym.simple_bind(ctx=ctx, grad_req=grad_req, **shapes)
    if location is None:
        location = {k: _rng.standard_normal(arr.shape)
                    for k, arr in exe.arg_dict.items()}
    for name, value in location.items():
        exe.arg_dict[name][:] = np.asarray(value).astype(
            exe.arg_dict[name].dtype)

    train = typ == "whole"
    if typ not in ("whole", "forward"):
        raise ValueError("typ must be 'whole' or 'forward'")

    def one_pass():
        exe.forward(is_train=train)
        if train:
            exe.backward()

    one_pass()          # warmup: jit compile
    nd.waitall()
    start = time.time()
    for _ in range(N):
        one_pass()
    nd.waitall()
    return (time.time() - start) / N


def _consistency_tol():
    tol = {np.dtype(np.float16): 1e-1, np.dtype(np.float32): 1e-3,
           np.dtype(np.float64): 1e-5, np.dtype(np.uint8): 0,
           np.dtype(np.int32): 0}
    try:
        import ml_dtypes

        # bf16: 7-bit mantissa (coarser than fp16's 10); 1e-1 is generous
        tol[np.dtype(ml_dtypes.bfloat16)] = 1e-1
    except ImportError:
        pass
    return tol


_CONSISTENCY_TOL = _consistency_tol()


def check_consistency(sym, ctx_list, scale=1.0, grad_req="write",
                      arg_params=None, aux_params=None, tol=None,
                      raise_on_err=True, ground_truth=None):
    """Run the same symbol in several context/dtype configurations and
    compare every output and gradient against the highest-precision run.

    Each element of ``ctx_list`` is a simple_bind kwargs dict (``ctx`` plus
    input shapes, optionally ``type_dict``).  The oracle is whichever
    configuration produced the widest output dtype, or ``ground_truth``.
    """
    if tol is None:
        tol = dict(_CONSISTENCY_TOL)
    elif isinstance(tol, float):
        tol = {dt: tol for dt in _CONSISTENCY_TOL}

    syms = list(sym) if isinstance(sym, (list, tuple)) \
        else [sym] * len(ctx_list)
    assert len(syms) == len(ctx_list) >= 2
    out_names = syms[0].list_outputs()
    arg_names = syms[0].list_arguments()

    exes = [s.simple_bind(grad_req=grad_req, **cfg)
            for s, cfg in zip(syms, ctx_list)]

    # one shared random parameter set, cast per-executor
    arg_params = dict(arg_params or {})
    for name, arr in exes[0].arg_dict.items():
        arg_params.setdefault(name,
                              _rng.normal(size=arr.shape, scale=scale))
    aux_params = dict(aux_params or {})
    for name in exes[0].aux_dict:
        aux_params.setdefault(name, 0)
    for exe in exes:
        for name, arr in exe.arg_dict.items():
            val = arg_params[name]
            arr[:] = val.astype(arr.dtype) if isinstance(val, np.ndarray) \
                else val
        for name, arr in exe.aux_dict.items():
            arr[:] = aux_params[name]

    def compare(collect, oracle):
        for i, exe in enumerate(exes):
            if i == oracle_idx and ground_truth is None:
                continue
            bound = tol[dtypes[i]]
            for name, got in collect(exe).items():
                if name not in oracle:
                    continue
                try:
                    assert_almost_equal(got, oracle[name].astype(dtypes[i]),
                                        rtol=bound, atol=bound,
                                        names=("ctx%d_%s" % (i, name),
                                               "oracle_%s" % name))
                except AssertionError:
                    if raise_on_err:
                        raise
                    import traceback

                    logging.warning("check_consistency mismatch (ctx %d, "
                                    "%s):\n%s", i, name,
                                    traceback.format_exc())

    def collect_outputs(exe):
        return {n: o.asnumpy() for n, o in zip(out_names, exe.outputs)}

    def collect_all(exe):
        named = dict(zip(out_names, exe.outputs))
        named.update({n: g for n, g in zip(arg_names, exe.grad_arrays)
                      if g is not None})
        return {k: v.asnumpy() for k, v in named.items()}

    # phase 1: eval-mode forward — catches inference-path divergence and
    # keeps train-only randomness (dropout masks) out of the comparison
    for exe in exes:
        exe.forward(is_train=False)
    dtypes = [np.dtype(exe.outputs[0].dtype) for exe in exes]
    oracle_idx = int(np.argmax(dtypes))
    oracle = ground_truth or collect_outputs(exes[oracle_idx])
    compare(collect_outputs, oracle)

    # phase 2: train-mode forward+backward — outputs and gradients
    if grad_req != "null":
        for exe in exes:
            exe.forward(is_train=True)
            exe.backward()
        oracle = ground_truth or collect_all(exes[oracle_idx])
        compare(collect_all, oracle)
    return oracle


# -- telemetry helpers ------------------------------------------------------


def assert_chrome_trace(payload, required_names=()):
    """Validate a Chrome-trace export (``obs.timeline.export`` /
    ``profiler.dump_profile`` payload): the ``traceEvents`` schema every
    viewer (chrome://tracing, Perfetto) relies on, plus presence of
    ``required_names`` — so tests can pin that a real fit / serve /
    elastic run actually landed its spans and instant events."""
    assert isinstance(payload, dict) and "traceEvents" in payload, payload
    events = payload["traceEvents"]
    assert isinstance(events, list) and events
    for e in events:
        # real jax.profiler captures carry float-microsecond timestamps
        # and phases beyond our own (flow s/t/f, B/E pairs, counters) —
        # require only what every viewer requires, and the full contract
        # on the phases this framework emits itself
        assert isinstance(e, dict)
        ph = e.get("ph")
        assert isinstance(ph, str) and ph, e
        assert isinstance(e.get("ts", 0), (int, float)), e
        if ph == "X":
            assert isinstance(e.get("name"), str) and "pid" in e \
                and "tid" in e, e
            assert e.get("dur", 0) >= 0, e
        if ph == "i":
            assert isinstance(e.get("name"), str), e
            assert e.get("s") in ("t", "p", "g"), e
    names = {e.get("name") for e in events}
    missing = set(required_names) - names
    assert not missing, ("missing trace events %s (have %d events)"
                        % (sorted(missing), len(events)))
    return names
