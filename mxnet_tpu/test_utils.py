"""Test utilities (reference: python/mxnet/test_utils.py, 905 LoC).

The numerics trio the reference's operator tests are built on
(SURVEY §4): finite-difference gradient checks, forward/backward checks
against numpy references, and cross-backend consistency — here
"interpret-mode vs compiled-XLA" and "1-chip vs N-chip" replace
"CPU vs GPU".
"""
from __future__ import annotations

import numpy as np

from . import ndarray as nd
from . import symbol as sym_mod
from .context import Context, cpu, current_context

_rng = np.random.RandomState(1234)


def default_context():
    return current_context()


def default_dtype():
    return np.float32


def random_arrays(*shapes):
    """Generate random numpy arrays."""
    arrays = [np.array(_rng.randn(), dtype=default_dtype()) if len(s) == 0
              else _rng.randn(*s).astype(default_dtype()) for s in shapes]
    if len(arrays) == 1:
        return arrays[0]
    return arrays


def np_reduce(dat, axis, keepdims, numpy_reduce_func):
    """Numpy reduce with mxnet axis semantics (reference: test_utils.py:56)."""
    if isinstance(axis, int):
        axis = [axis]
    else:
        axis = list(axis) if axis is not None else range(len(dat.shape))
    ret = dat
    for i in reversed(sorted(axis)):
        ret = numpy_reduce_func(ret, axis=i)
    if keepdims:
        keepdims_shape = list(dat.shape)
        for i in axis:
            keepdims_shape[i] = 1
        ret = ret.reshape(tuple(keepdims_shape))
    return ret


def same(a, b):
    return np.array_equal(a, b)


def reldiff(a, b):
    diff = np.sum(np.abs(a - b))
    norm = np.sum(np.abs(a)) + np.sum(np.abs(b))
    if diff == 0:
        return 0
    return diff / norm


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-20, names=("a", "b")):
    """Assert arrays equal within tolerance (reference: test_utils.py:128)."""
    a = a.asnumpy() if isinstance(a, nd.NDArray) else np.asarray(a)
    b = b.asnumpy() if isinstance(b, nd.NDArray) else np.asarray(b)
    if not np.allclose(a, b, rtol=rtol, atol=atol):
        index = np.unravel_index(np.argmax(np.abs(a - b)), a.shape)
        raise AssertionError(
            "Items are not equal:\nError %f exceeds tolerance rtol=%f, atol=%f."
            "  Location of maximum error: %s, %s=%f, %s=%f"
            % (np.max(np.abs(a - b)), rtol, atol, str(index),
               names[0], a[index], names[1], b[index]))


def almost_equal(a, b, rtol=1e-5, atol=1e-20):
    return np.allclose(a, b, rtol=rtol, atol=atol)


def simple_forward(sym, ctx=None, is_train=False, **inputs):
    """Forward a symbol on numpy inputs, returning numpy outputs."""
    ctx = ctx or default_context()
    inputs = {k: nd.array(v) for k, v in inputs.items()}
    exe = sym.bind(ctx, args=inputs, grad_req="null")
    outputs = [o.asnumpy() for o in exe.forward(is_train=is_train)]
    if len(outputs) == 1:
        outputs = outputs[0]
    return outputs


def _parse_location(sym, location, ctx):
    assert isinstance(location, (dict, list, tuple))
    if isinstance(location, dict):
        if set(location.keys()) != set(sym.list_arguments()):
            raise ValueError("Symbol arguments and keys of the given location do "
                             "not match. symbol args:%s, location.keys():%s"
                             % (str(set(sym.list_arguments())),
                                str(set(location.keys()))))
    else:
        location = {k: v for k, v in zip(sym.list_arguments(), location)}
    return {k: nd.array(v, ctx=ctx) if not isinstance(v, nd.NDArray) else v
            for k, v in location.items()}


def _parse_aux_states(sym, aux_states, ctx):
    if aux_states is not None:
        if isinstance(aux_states, dict):
            if set(aux_states.keys()) != set(sym.list_auxiliary_states()):
                raise ValueError("Symbol aux_states names and given aux_states "
                                 "do not match.")
        elif isinstance(aux_states, (list, tuple)):
            aux_names = sym.list_auxiliary_states()
            aux_states = {k: v for k, v in zip(aux_names, aux_states)}
        aux_states = {k: nd.array(v, ctx=ctx) if not isinstance(v, nd.NDArray)
                      else v for k, v in aux_states.items()}
    return aux_states


def numeric_grad(executor, location, aux_states=None, eps=1e-4,
                 use_forward_train=True):
    """Finite-difference gradients (reference: test_utils.py:297)."""
    approx_grads = {k: np.zeros(v.shape, dtype=np.float32)
                    for k, v in location.items()}
    for k, v in location.items():
        executor.arg_dict[k][:] = v
    for k in location:
        location[k] = np.array(location[k], order="C", copy=True)  # writable
    for k, v in location.items():
        if v.dtype.kind != "f":
            continue
        old_value = v.copy()
        for i in range(int(np.prod(v.shape))):
            # inplace update
            v.ravel()[i] += eps / 2.0
            executor.arg_dict[k][:] = v
            if aux_states is not None:
                for key, val in aux_states.items():
                    executor.aux_dict[key][:] = val
            executor.forward(is_train=use_forward_train)
            f_peps = executor.outputs[0].asnumpy().sum()

            v.ravel()[i] -= eps
            executor.arg_dict[k][:] = v
            if aux_states is not None:
                for key, val in aux_states.items():
                    executor.aux_dict[key][:] = val
            executor.forward(is_train=use_forward_train)
            f_neps = executor.outputs[0].asnumpy().sum()

            approx_grads[k].ravel()[i] = (f_peps - f_neps) / eps
            v.ravel()[i] = old_value.ravel()[i]
        # copy back
        executor.arg_dict[k][:] = old_value
    return approx_grads


def check_numeric_gradient(sym, location, aux_states=None, numeric_eps=1e-3,
                           rtol=1e-2, atol=None, grad_nodes=None,
                           use_forward_train=True, ctx=None):
    """Finite-difference vs symbolic backward (reference: test_utils.py:360)."""
    ctx = ctx or default_context()

    def random_projection(shape):
        plain = _rng.rand(*shape) + 0.1
        return plain

    location = _parse_location(sym=sym, location=location, ctx=ctx)
    location_npy = {k: v.asnumpy() for k, v in location.items()}
    aux_states = _parse_aux_states(sym=sym, aux_states=aux_states, ctx=ctx)
    if aux_states is not None:
        aux_states_npy = {k: v.asnumpy() for k, v in aux_states.items()}
    else:
        aux_states_npy = None
    if grad_nodes is None:
        grad_nodes = sym.list_arguments()
        grad_req = {k: "write" for k in grad_nodes}
    elif isinstance(grad_nodes, (list, tuple)):
        grad_nodes = list(grad_nodes)
        grad_req = {k: "write" for k in grad_nodes}
    elif isinstance(grad_nodes, dict):
        grad_req = grad_nodes.copy()
        grad_nodes = grad_nodes.keys()
    else:
        raise ValueError

    input_shape = {k: v.shape for k, v in location.items()}
    _, out_shape, _ = sym.infer_shape(**input_shape)
    proj = sym_mod.Variable("__random_proj")
    out = sym_mod.sum(sym * proj)
    out = sym_mod.MakeLoss(out)

    location = dict(list(location.items()) +
                    [("__random_proj", nd.array(random_projection(out_shape[0]),
                                                ctx=ctx))])
    args_grad_npy = dict([(k, _rng.normal(0, 0.01, size=location[k].shape))
                          for k in grad_nodes] +
                         [("__random_proj", _rng.normal(0, 0.01, size=out_shape[0]))])
    args_grad = {k: nd.array(v, ctx=ctx) for k, v in args_grad_npy.items()}

    executor = out.bind(ctx, grad_req=grad_req, args=location,
                        args_grad=args_grad, aux_states=aux_states)

    inps = executor.arg_arrays
    executor.forward(is_train=True)
    executor.backward()
    symbolic_grads = {k: executor.grad_dict[k].asnumpy() for k in grad_nodes}

    numeric_gradients = numeric_grad(
        executor, location_npy, aux_states_npy, eps=numeric_eps,
        use_forward_train=use_forward_train)

    for name in grad_nodes:
        fd_grad = numeric_gradients[name]
        orig_grad = args_grad_npy[name]
        sym_grad = symbolic_grads[name]
        if grad_req[name] == "write":
            assert_almost_equal(fd_grad, sym_grad, rtol, atol or 1e-4,
                                ("NUMERICAL_%s" % name, "BACKWARD_%s" % name))
        elif grad_req[name] == "add":
            assert_almost_equal(fd_grad, sym_grad - orig_grad, rtol, atol or 1e-4,
                                ("NUMERICAL_%s" % name, "BACKWARD_%s" % name))
        elif grad_req[name] == "null":
            assert_almost_equal(orig_grad, sym_grad, rtol, atol or 1e-4)
        else:
            raise ValueError


def check_symbolic_forward(sym, location, expected, rtol=1e-4, atol=None,
                           aux_states=None, ctx=None):
    """Forward vs expected numpy outputs (reference: test_utils.py:473)."""
    ctx = ctx or default_context()
    location = _parse_location(sym=sym, location=location, ctx=ctx)
    aux_states = _parse_aux_states(sym=sym, aux_states=aux_states, ctx=ctx)
    if isinstance(expected, dict):
        expected = [expected[k] for k in sym.list_outputs()]
    args_grad_data = {k: nd.zeros(v.shape, ctx=ctx) for k, v in location.items()}

    executor = sym.bind(ctx, args=location, args_grad=args_grad_data,
                        aux_states=aux_states)
    outputs = [o.asnumpy() for o in executor.forward()]
    for output_name, expect, output in zip(sym.list_outputs(), expected, outputs):
        assert_almost_equal(expect, output, rtol, atol or 1e-5,
                            ("EXPECTED_%s" % output_name, "FORWARD_%s" % output_name))
    return executor.outputs


def check_symbolic_backward(sym, location, out_grads, expected, rtol=1e-5,
                            atol=None, aux_states=None, grad_req="write",
                            ctx=None):
    """Backward vs expected numpy gradients (reference: test_utils.py:526)."""
    ctx = ctx or default_context()
    location = _parse_location(sym=sym, location=location, ctx=ctx)
    aux_states = _parse_aux_states(sym=sym, aux_states=aux_states, ctx=ctx)
    if isinstance(expected, (list, tuple)):
        expected = {k: v for k, v in zip(sym.list_arguments(), expected)}
    args_grad_npy = {k: _rng.normal(size=location[k].shape) for k in expected}
    args_grad_data = {k: nd.array(v, ctx=ctx) for k, v in args_grad_npy.items()}
    if isinstance(grad_req, str):
        grad_req = {k: grad_req for k in location}
    elif isinstance(grad_req, (list, tuple)):
        grad_req = {k: v for k, v in zip(location, grad_req)}

    executor = sym.bind(ctx, args=location, args_grad=args_grad_data,
                        aux_states=aux_states, grad_req=grad_req)
    executor.forward(is_train=True)
    if isinstance(out_grads, (tuple, list)):
        out_grads = [nd.array(v, ctx=ctx) if not isinstance(v, nd.NDArray) else v
                     for v in out_grads]
    elif isinstance(out_grads, (dict)):
        out_grads = [nd.array(out_grads[k], ctx=ctx)
                     for k in sym.list_outputs()]
    executor.backward(out_grads)

    grads = {k: v.asnumpy() for k, v in executor.grad_dict.items()}
    for name in expected:
        if grad_req[name] == "write":
            assert_almost_equal(expected[name], grads[name], rtol, atol or 1e-8,
                                ("EXPECTED_%s" % name, "BACKWARD_%s" % name))
        elif grad_req[name] == "add":
            assert_almost_equal(expected[name], grads[name] - args_grad_npy[name],
                                rtol, atol or 1e-8,
                                ("EXPECTED_%s" % name, "BACKWARD_%s" % name))
        elif grad_req[name] == "null":
            assert_almost_equal(args_grad_npy[name], grads[name], rtol,
                                atol or 1e-8)
        else:
            raise ValueError
    return executor.grad_arrays


def check_speed(sym, location=None, ctx=None, N=20, grad_req=None,
                typ="whole", **kwargs):
    """Time forward(+backward) (reference: test_utils.py:620)."""
    import time

    ctx = ctx or default_context()
    if grad_req is None:
        grad_req = "write"
    if location is None:
        exe = sym.simple_bind(grad_req=grad_req, ctx=ctx, **kwargs)
        location = {k: _rng.normal(size=arr.shape, scale=1.0)
                    for k, arr in exe.arg_dict.items()}
    else:
        assert isinstance(location, dict)
        exe = sym.simple_bind(grad_req=grad_req, ctx=ctx,
                              **{k: v.shape for k, v in location.items()})
    for name, iarr in location.items():
        exe.arg_dict[name][:] = iarr.astype(exe.arg_dict[name].dtype)

    if typ == "whole":
        exe.forward(is_train=True)
        exe.backward()
        for output in exe.outputs:
            output.wait_to_read()
        tic = time.time()
        for _ in range(N):
            exe.forward(is_train=True)
            exe.backward()
        for output in exe.outputs:
            output.wait_to_read()
        nd.waitall()
        toc = time.time()
        return (toc - tic) / N
    elif typ == "forward":
        exe.forward(is_train=False)
        for output in exe.outputs:
            output.wait_to_read()
        tic = time.time()
        for _ in range(N):
            exe.forward(is_train=False)
        for output in exe.outputs:
            output.wait_to_read()
        nd.waitall()
        toc = time.time()
        return (toc - tic) / N
    else:
        raise ValueError("typ can only be 'whole' or 'forward'")


def check_consistency(sym, ctx_list, scale=1.0, grad_req="write",
                      arg_params=None, aux_params=None, tol=None,
                      raise_on_err=True, ground_truth=None):
    """Same symbol across contexts/dtypes, compare outputs+grads pairwise
    (reference: test_utils.py:676 — the de-facto kernel oracle)."""
    if tol is None:
        tol = {np.dtype(np.float16): 1e-1, np.dtype(np.float32): 1e-3,
               np.dtype(np.float64): 1e-5, np.dtype(np.uint8): 0,
               np.dtype(np.int32): 0}
    elif isinstance(tol, float):
        tol = {np.dtype(np.float16): tol, np.dtype(np.float32): tol,
               np.dtype(np.float64): tol, np.dtype(np.uint8): tol,
               np.dtype(np.int32): tol}

    assert len(ctx_list) > 1
    if isinstance(sym, sym_mod.Symbol):
        sym = [sym] * len(ctx_list)
    else:
        assert len(sym) == len(ctx_list)

    output_names = sym[0].list_outputs()
    arg_names = sym[0].list_arguments()
    exe_list = []
    for s, ctx in zip(sym, ctx_list):
        assert s.list_arguments() == arg_names
        assert s.list_outputs() == output_names
        exe_list.append(s.simple_bind(grad_req=grad_req, **ctx))

    arg_params = {} if arg_params is None else arg_params
    aux_params = {} if aux_params is None else aux_params
    for n, arr in exe_list[0].arg_dict.items():
        if n not in arg_params:
            arg_params[n] = np.random.normal(size=arr.shape, scale=scale)
    for n, arr in exe_list[0].aux_dict.items():
        if n not in aux_params:
            aux_params[n] = 0
    for exe in exe_list:
        for name, arr in exe.arg_dict.items():
            arr[:] = arg_params[name].astype(arr.dtype) \
                if isinstance(arg_params[name], np.ndarray) else arg_params[name]
        for name, arr in exe.aux_dict.items():
            arr[:] = aux_params[name]

    dtypes = [np.dtype(exe.outputs[0].dtype) if exe._outputs else np.dtype(np.float32)
              for exe in exe_list]
    # forward
    for exe in exe_list:
        exe.forward(is_train=False)
    dtypes = [np.dtype(exe.outputs[0].dtype) for exe in exe_list]
    max_idx = np.argmax(dtypes)
    gt = ground_truth
    if gt is None:
        gt = {name: arr.asnumpy() for name, arr in
              zip(output_names, exe_list[max_idx].outputs)}
    for i, exe in enumerate(exe_list):
        if i == max_idx:
            continue
        rtol = tol[dtypes[i]]
        atol = rtol
        for name, arr in zip(output_names, exe.outputs):
            assert_almost_equal(arr.asnumpy(), gt[name].astype(dtypes[i]),
                                rtol=rtol, atol=atol)

    # train (forward + backward)
    if grad_req != "null":
        for exe in exe_list:
            exe.forward(is_train=True)
            exe.backward()
        if ground_truth is None:
            gt = {name: arr.asnumpy() for name, arr in
                  zip(output_names + arg_names,
                      exe_list[max_idx].outputs + exe_list[max_idx].grad_arrays)
                  if arr is not None}
        for i, exe in enumerate(exe_list):
            if i == max_idx:
                continue
            rtol = tol[dtypes[i]]
            atol = rtol
            curr = zip(output_names + arg_names, exe.outputs + exe.grad_arrays)
            for name, arr in curr:
                if arr is None or name not in gt:
                    continue
                assert_almost_equal(arr.asnumpy(), gt[name].astype(dtypes[i]),
                                    rtol=rtol, atol=atol)
    return gt


def rand_ndarray(shape, dtype=np.float32):
    return nd.array(_rng.randn(*shape).astype(dtype))


def rand_shape_2d(dim0=10, dim1=10):
    return (_rng.randint(1, dim0 + 1), _rng.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (_rng.randint(1, dim0 + 1), _rng.randint(1, dim1 + 1),
            _rng.randint(1, dim2 + 1))
