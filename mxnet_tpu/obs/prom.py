"""Tiny HTTP exporter for the metrics registry + trace timeline.

Heritage: ``kvstore_server.py``'s process contract — a sidecar loop the
serving process runs so operators can scrape state — rebuilt on the
standard-library HTTP server instead of a bespoke socket protocol.
:class:`~mxnet_tpu.decode.DecodeServer` starts one when
``MXNET_METRICS_PORT`` (or its ``metrics_port`` argument) is set.

Endpoints:

* ``/metrics``       — Prometheus text exposition
  (:meth:`MetricsRegistry.prometheus_text`);
* ``/metrics.json``  — the registry snapshot as JSON, merged with any
  registered JSON providers (:meth:`MetricsServer.add_json`) — e.g. the
  serving loop's per-host ``mx_serve_summary:<host>`` routing views
  (prefix-cache chain digest + free-page/queue-depth signals) the
  fleet router polls;
* ``/trace``         — the current trace-timeline ring as Chrome-trace
  JSON (save it, open in Perfetto);
* ``/healthz``       — liveness probe (``ok``).

The server runs on a daemon thread and binds ``127.0.0.1`` by default —
expose it deliberately (a reverse proxy, ``host="0.0.0.0"``), not by
accident.  ``port=0`` binds an ephemeral port (tests); read it back from
:attr:`MetricsServer.port` after :meth:`start`.
"""
from __future__ import annotations

import json
import threading

__all__ = ["MetricsServer"]


class MetricsServer:
    """Serve one registry (+ optional timeline) over HTTP."""

    def __init__(self, registry=None, timeline=None, port=0,
                 host="127.0.0.1"):
        if registry is None or timeline is None:
            from . import registry as default_registry
            from . import timeline as default_timeline

            registry = registry or default_registry
            timeline = timeline if timeline is not None \
                else default_timeline
        self.registry = registry
        self.timeline = timeline
        self._host = host
        self._port = int(port)
        self._httpd = None
        self._thread = None
        self._json = {}     # extra /metrics.json sections: name -> fn

    def add_json(self, name, provider):
        """Merge ``provider()`` (a JSON-serializable dict) into the
        ``/metrics.json`` payload under ``name`` — how the serving loop
        exposes non-scalar state (the prefix-cache chain summary) next
        to the registry snapshot.  Re-registering a name replaces it;
        servers sharing one port therefore register DISTINCT names
        (``mx_serve_summary:<host>``)."""
        self._json[str(name)] = provider
        return self

    def remove_json(self, name):
        """Drop a registered ``/metrics.json`` section (a renamed host
        re-registers under its new label)."""
        self._json.pop(str(name), None)
        return self

    @property
    def port(self):
        """The bound port (resolves ``port=0`` after :meth:`start`)."""
        if self._httpd is not None:
            return self._httpd.server_address[1]
        return self._port

    def start(self):
        if self._httpd is not None:
            return self
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        registry, timeline = self.registry, self.timeline
        extra_json = self._json

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib API)
                path = self.path.split("?", 1)[0]
                if path in ("/metrics", "/"):
                    body = registry.prometheus_text().encode()
                    ctype = "text/plain; version=0.0.4"
                elif path == "/metrics.json":
                    payload = registry.snapshot()
                    for name, fn in list(extra_json.items()):
                        try:
                            payload[name] = fn()
                        except Exception as exc:  # a dead provider must
                            payload[name] = {"error": str(exc)}  # not 500
                    body = json.dumps(payload).encode()
                    ctype = "application/json"
                elif path == "/trace" and timeline is not None:
                    body = json.dumps(timeline.export()).encode()
                    ctype = "application/json"
                elif path == "/healthz":
                    body, ctype = b"ok\n", "text/plain"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # scrapes must not spam stderr
                pass

        self._httpd = ThreadingHTTPServer((self._host, self._port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="mxtpu-metrics-http")
        self._thread.start()
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
