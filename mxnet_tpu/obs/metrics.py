"""Typed, labeled metrics behind one thread-safe registry.

The telemetry subsystem's first layer (docs/observability.md): every
counter the framework used to keep in ad-hoc module state (the old
``profiler._step`` dict, ``profiler._requests`` list, the serving loop's
bare ints) becomes a declared metric in a :class:`MetricsRegistry` —
named, typed (counter / gauge / histogram), optionally labeled, and
mutated only under the registry lock, so concurrent writers (the fit
loop, the checkpoint writer thread, prefetch workers, a serving loop)
can never tear an update.

Export paths:

* :meth:`MetricsRegistry.snapshot` — a plain dict (the programmatic
  read ``profiler.step_stats`` is built on);
* :meth:`MetricsRegistry.export_jsonl` — append ONE JSON line per call
  (``{"ts": ..., "metrics": {...}}``), the periodic-flush format
  (:class:`PeriodicExporter`, ``MXNET_METRICS_EXPORT`` /
  ``MXNET_METRICS_EXPORT_PERIOD``);
* :meth:`MetricsRegistry.prometheus_text` — the Prometheus text
  exposition format, served over HTTP by
  :class:`~mxnet_tpu.obs.prom.MetricsServer` (``MXNET_METRICS_PORT`` on
  :class:`~mxnet_tpu.decode.DecodeServer`).

Histograms keep (a) running count/sum, (b) cumulative bucket counts for
Prometheus, and (c) a bounded reservoir of recent samples (the same cap
discipline the old ``_requests`` list had) from which
:meth:`Histogram.percentile` computes numpy-exact percentiles.
"""
from __future__ import annotations

import json
import threading
import time

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "PeriodicExporter", "percentile", "DEFAULT_BUCKETS",
           "DEFAULT_SAMPLE_CAP"]

# prometheus-client's defaults: latencies from 1ms to 10s
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0)
# retained samples per histogram series (the old profiler._requests cap)
DEFAULT_SAMPLE_CAP = 4096


def percentile(values, q):
    """Nearest-rank percentile of a sorted list; ``None`` when empty (the
    old ``profiler._percentile`` indexed into an empty list and raised)."""
    if not values:
        return None
    idx = min(len(values) - 1, max(0, int(round(q * (len(values) - 1)))))
    return values[idx]


def _escape_label(v):
    return str(v).replace("\\", r"\\").replace('"', r"\"") \
        .replace("\n", r"\n")


def _fmt_labels(label_names, label_values, extra=None):
    pairs = ["%s=\"%s\"" % (n, _escape_label(v))
             for n, v in zip(label_names, label_values)]
    if extra:
        pairs.extend("%s=\"%s\"" % (n, _escape_label(v))
                     for n, v in extra)
    return "{%s}" % ",".join(pairs) if pairs else ""


class _Metric:
    """One metric family: a name, a type, declared label names, and one
    child series per distinct label-value tuple.  With no labels the
    family IS its single series — ``inc``/``set``/``observe`` work
    directly on it."""

    kind = None

    def __init__(self, name, help, label_names, lock):
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._lock = lock
        self._series = {}
        if not self.label_names:
            self._series[()] = self._new_series()

    def _new_series(self):
        raise NotImplementedError

    def labels(self, *values, **kv):
        """A bound child for one label-value combination (created on
        first use) exposing the family's mutators.  Accepts positional
        values in declared order or keyword form."""
        if kv:
            if values:
                raise ValueError("pass labels positionally or by keyword, "
                                 "not both")
            values = tuple(kv[n] for n in self.label_names)
        values = tuple(str(v) for v in values)
        if len(values) != len(self.label_names):
            raise ValueError("%s expects labels %s, got %r"
                             % (self.name, self.label_names, values))
        with self._lock:
            child = self._series.get(values)
            if child is None:
                child = self._series[values] = self._new_series()
            return self._bind(child)

    def _bind(self, series):
        raise NotImplementedError

    def _default(self):
        if self.label_names:
            raise ValueError("%s is labeled (%s); call .labels(...) first"
                             % (self.name, self.label_names))
        return self._series[()]

    def reset(self):
        with self._lock:
            if self.label_names:
                self._series.clear()
            else:
                self._series[()] = self._new_series()

    def series(self):
        with self._lock:
            return list(self._series.items())

    def reset_series(self, *values, **kv):
        """Zero ONE labeled child series (other labels untouched) — how
        a fleet router cold-starts its own hosts' TTFT samples between
        timed drains without clearing other hosts' history.  No-op when
        the series does not exist yet."""
        if kv:
            if values:
                raise ValueError("pass labels positionally or by keyword, "
                                 "not both")
            values = tuple(kv[n] for n in self.label_names)
        values = tuple(str(v) for v in values)
        with self._lock:
            old = self._series.get(values)
            if old is None:
                return
            # zero IN PLACE: bound children (DecodeServer holds one per
            # host label) must keep recording into the same series
            fresh = self._new_series()
            for slot in old.__slots__:
                setattr(old, slot, getattr(fresh, slot))


class _CounterSeries:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0


class _BoundCounter:
    __slots__ = ("_family", "_series")

    def __init__(self, family, series):
        self._family = family
        self._series = series

    def inc(self, n=1.0):
        self._family._inc(self._series, n)

    def get(self):
        with self._family._lock:
            return self._series.value


class Counter(_Metric):
    """Monotonically increasing value (``inc`` rejects negatives)."""

    kind = "counter"

    def _new_series(self):
        return _CounterSeries()

    def _bind(self, series):
        return _BoundCounter(self, series)

    def inc(self, n=1.0):
        self._inc(self._default(), n)

    def _inc(self, series, n):
        if n < 0:
            raise ValueError("counter %s cannot decrease" % self.name)
        with self._lock:
            series.value += n

    def get(self):
        with self._lock:
            return self._default().value


class _GaugeSeries:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0


class _BoundGauge:
    __slots__ = ("_family", "_series")

    def __init__(self, family, series):
        self._family = family
        self._series = series

    def set(self, v):
        with self._family._lock:
            self._series.value = float(v)

    def inc(self, n=1.0):
        with self._family._lock:
            self._series.value += n

    def get(self):
        with self._family._lock:
            return self._series.value


class Gauge(_Metric):
    """A value that can go anywhere (pool utilization, last-write ms)."""

    kind = "gauge"

    def _new_series(self):
        return _GaugeSeries()

    def _bind(self, series):
        return _BoundGauge(self, series)

    def set(self, v):
        with self._lock:
            self._default().value = float(v)

    def inc(self, n=1.0):
        with self._lock:
            self._default().value += n

    def get(self):
        with self._lock:
            return self._default().value


class _HistogramSeries:
    __slots__ = ("count", "sum", "buckets", "samples", "cap")

    def __init__(self, nbuckets, cap):
        self.count = 0
        self.sum = 0.0
        self.buckets = [0] * nbuckets     # cumulative at export time? no:
        self.samples = []                 # bounded reservoir (recent)
        self.cap = cap


class Histogram(_Metric):
    """Distribution: running count/sum, per-bucket counts (Prometheus
    cumulative form is assembled at export), and a bounded buffer of the
    most recent samples for numpy-exact percentiles."""

    kind = "histogram"

    def __init__(self, name, help, label_names, lock, buckets=None,
                 sample_cap=DEFAULT_SAMPLE_CAP):
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))
        self.sample_cap = int(sample_cap)
        super().__init__(name, help, label_names, lock)

    def _new_series(self):
        return _HistogramSeries(len(self.buckets) + 1, self.sample_cap)

    def _bind(self, series):
        return _BoundHistogram(self, series)

    def observe(self, v):
        self._observe(self._default(), v)

    def _observe(self, series, v):
        v = float(v)
        with self._lock:
            series.count += 1
            series.sum += v
            i = 0
            for i, b in enumerate(self.buckets):
                if v <= b:
                    break
            else:
                i = len(self.buckets)
            series.buckets[i] += 1
            series.samples.append(v)
            if len(series.samples) > series.cap:
                del series.samples[:len(series.samples) - series.cap]

    @property
    def count(self):
        with self._lock:
            return self._default().count

    @property
    def sum(self):
        with self._lock:
            return self._default().sum

    def percentile(self, q):
        """The q-quantile (q in [0, 1]) over the retained samples,
        computed by ``numpy.percentile`` (linear interpolation — exactly
        what a numpy cross-check of the same samples yields); ``None``
        when nothing has been observed."""
        import numpy as np

        with self._lock:
            samples = list(self._default().samples)
        if not samples:
            return None
        return float(np.percentile(samples, q * 100.0))

    def sorted_samples(self):
        with self._lock:
            return sorted(self._default().samples)


class _BoundHistogram:
    __slots__ = ("_family", "_series")

    def __init__(self, family, series):
        self._family = family
        self._series = series

    def observe(self, v):
        self._family._observe(self._series, v)

    @property
    def count(self):
        with self._family._lock:
            return self._series.count

    @property
    def sum(self):
        with self._family._lock:
            return self._series.sum

    def percentile(self, q):
        import numpy as np

        with self._family._lock:
            samples = list(self._series.samples)
        if not samples:
            return None
        return float(np.percentile(samples, q * 100.0))


class MetricsRegistry:
    """Get-or-create metric families by name; one lock guards every
    mutation and every read, so snapshots are internally consistent."""

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics = {}

    # ------------------------------------------------------------------
    def _declare(self, cls, name, help, labels, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise ValueError(
                        "metric %r already registered as %s, not %s"
                        % (name, m.kind, cls.kind))
                return m
            m = cls(name, help, tuple(labels), self._lock, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name, help="", labels=()):
        return self._declare(Counter, name, help, labels)

    def gauge(self, name, help="", labels=()):
        return self._declare(Gauge, name, help, labels)

    def histogram(self, name, help="", labels=(), buckets=None,
                  sample_cap=DEFAULT_SAMPLE_CAP):
        return self._declare(Histogram, name, help, labels,
                             buckets=buckets, sample_cap=sample_cap)

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    def reset(self):
        """Zero every series (the ``profiler.reset_step_stats`` path —
        declared families survive, values restart)."""
        with self._lock:
            for m in self._metrics.values():
                m.reset()

    # ------------------------------------------------------------------
    # exporters
    # ------------------------------------------------------------------
    def snapshot(self):
        """``{name: {type, help, label_names, series: [...]}}`` with each
        series ``{"labels": {...}, "value": v}`` (histograms: a dict of
        count/sum/min/max/p50/p95/p99)."""
        import numpy as np

        with self._lock:
            metrics = list(self._metrics.items())
        out = {}
        for name, m in metrics:
            rows = []
            for label_values, s in m.series():
                labels = dict(zip(m.label_names, label_values))
                if m.kind == "histogram":
                    with m._lock:
                        samples = list(s.samples)
                        count, total = s.count, s.sum
                    val = {"count": count, "sum": total}
                    if samples:
                        val.update({
                            "min": float(min(samples)),
                            "max": float(max(samples)),
                            "p50": float(np.percentile(samples, 50)),
                            "p95": float(np.percentile(samples, 95)),
                            "p99": float(np.percentile(samples, 99)),
                        })
                else:
                    with m._lock:
                        val = s.value
                rows.append({"labels": labels, "value": val})
            out[name] = {"type": m.kind, "help": m.help,
                         "label_names": list(m.label_names),
                         "series": rows}
        return out

    def export_jsonl(self, path):
        """Append one ``{"ts", "metrics"}`` JSON line to ``path``."""
        line = json.dumps({"ts": time.time(), "metrics": self.snapshot()})
        with open(path, "a") as f:
            f.write(line + "\n")
        return line

    def prometheus_text(self):
        """The Prometheus text exposition format (served by
        :class:`~mxnet_tpu.obs.prom.MetricsServer`)."""
        lines = []
        with self._lock:
            metrics = list(self._metrics.items())
        for name, m in sorted(metrics):
            if m.help:
                lines.append("# HELP %s %s" % (name, m.help))
            lines.append("# TYPE %s %s" % (name, m.kind))
            for label_values, s in m.series():
                lab = _fmt_labels(m.label_names, label_values)
                if m.kind == "histogram":
                    with m._lock:
                        buckets = list(s.buckets)
                        count, total = s.count, s.sum
                    cum = 0
                    for bound, n in zip(m.buckets, buckets):
                        cum += n
                        lines.append("%s_bucket%s %d" % (
                            name, _fmt_labels(m.label_names, label_values,
                                              [("le", "%g" % bound)]),
                            cum))
                    lines.append("%s_bucket%s %d" % (
                        name, _fmt_labels(m.label_names, label_values,
                                          [("le", "+Inf")]), count))
                    lines.append("%s_sum%s %g" % (name, lab, total))
                    lines.append("%s_count%s %d" % (name, lab, count))
                else:
                    with m._lock:
                        v = s.value
                    lines.append("%s%s %g" % (name, lab, v))
        return "\n".join(lines) + "\n"


class PeriodicExporter:
    """Background JSON-lines flusher: one snapshot line every ``period``
    seconds (armed by ``MXNET_METRICS_EXPORT`` +
    ``MXNET_METRICS_EXPORT_PERIOD``).  Daemon thread; :meth:`stop`
    flushes once more on the way out."""

    def __init__(self, registry, path, period):
        self.registry = registry
        self.path = path
        self.period = float(period)
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="mxtpu-metrics-export")
            self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.period):
            try:
                self.registry.export_jsonl(self.path)
            except OSError:
                pass  # disk hiccup; next period retries

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.period + 1)
            self._thread = None
        try:
            self.registry.export_jsonl(self.path)
        except OSError:
            pass
