"""Always-on trace timeline — bounded ring buffer, Chrome-trace export.

The telemetry subsystem's second layer (docs/observability.md): a span /
instant-event API whose storage is a fixed-capacity ring buffer
(``MXNET_TRACE_BUFFER`` events, oldest evicted first), so leaving it
armed in production costs one deque append per event and bounded memory
— the always-on property the old profiler's unbounded ``events`` list
could not offer.

Events are thread-aware (every record carries the writing thread's id,
so the fit loop, the checkpoint writer, prefetch workers and a serving
loop interleave legibly) and nest naturally: complete ("X") events with
overlapping [ts, ts+dur) on one thread render as a flame stack in any
Chrome-trace viewer.  :meth:`TraceTimeline.export` writes the standard
``{"traceEvents": [...]}`` JSON — open it at ``chrome://tracing`` or
https://ui.perfetto.dev — and merges any Chrome-format traces found in a
``jax.profiler`` trace directory when one is given, so host spans and
the XLA device timeline land in one file.
"""
from __future__ import annotations

import glob
import gzip
import json
import os
import threading
import time
from collections import deque

__all__ = ["TraceTimeline", "DEFAULT_CAPACITY"]

DEFAULT_CAPACITY = 65536


class TraceTimeline:
    """Bounded, thread-safe event ring buffer in Chrome-trace form."""

    def __init__(self, capacity=DEFAULT_CAPACITY):
        self._lock = threading.Lock()
        self._buf = deque(maxlen=int(capacity))
        self._total = 0  # events ever added (dropped = total - len)

    @property
    def capacity(self):
        return self._buf.maxlen

    @property
    def dropped(self):
        """Events evicted by the ring bound since the last clear."""
        with self._lock:
            return max(0, self._total - len(self._buf))

    def __len__(self):
        with self._lock:
            return len(self._buf)

    # ------------------------------------------------------------------
    # recording
    # ------------------------------------------------------------------
    def _push(self, ev):
        with self._lock:
            self._buf.append(ev)
            self._total += 1

    def add_span(self, name, t0, dur, cat="host", tid=None, args=None):
        """One complete ("X") event: ``t0`` epoch seconds, ``dur``
        seconds.  Used both live (the :meth:`span` context manager) and
        retroactively (``profiler.record_host_wait`` knows the duration
        only after the wait)."""
        ev = {"name": name, "cat": cat, "ph": "X",
              "ts": int(t0 * 1e6), "dur": max(int(dur * 1e6), 0),
              "pid": os.getpid(),
              "tid": tid if tid is not None else threading.get_ident()}
        if args:
            ev["args"] = dict(args)
        self._push(ev)

    def instant(self, name, cat="event", args=None, scope="t"):
        """One instant ("i") event — elastic shrink/regrow, checkpoint
        commits, COW forks, admissions/retirements, prefill-chunk
        windows.  ``scope`` "t"=thread, "p"=process, "g"=global."""
        ev = {"name": name, "cat": cat, "ph": "i", "s": scope,
              "ts": int(time.time() * 1e6), "pid": os.getpid(),
              "tid": threading.get_ident()}
        if args:
            ev["args"] = dict(args)
        self._push(ev)

    def span(self, name, cat="host", args=None):
        """Context manager recording one complete event around the body
        (nests: inner spans on the same thread stack in the viewer)."""
        return _LiveSpan(self, name, cat, args)

    # ------------------------------------------------------------------
    def events(self):
        """A consistent copy of the current ring contents."""
        with self._lock:
            return list(self._buf)

    def clear(self):
        with self._lock:
            self._buf.clear()
            self._total = 0

    # ------------------------------------------------------------------
    # export
    # ------------------------------------------------------------------
    def export(self, path=None, jax_trace_dir=None, extra_events=None):
        """The Chrome-trace payload dict; written as JSON to ``path``
        when given.  ``jax_trace_dir`` (the ``jax.profiler`` output
        directory) is scanned for ``*.trace.json[.gz]`` files whose
        ``traceEvents`` are merged in — host spans and the XLA device
        timeline open as one Perfetto view."""
        events = self.events()
        if extra_events:
            events.extend(extra_events)
        if jax_trace_dir:
            events.extend(_jax_trace_events(jax_trace_dir))
        payload = {"traceEvents": events, "displayTimeUnit": "ms"}
        if path is not None:
            with open(path, "w") as f:
                json.dump(payload, f)
        return payload


class _LiveSpan:
    __slots__ = ("_tl", "_name", "_cat", "_args", "_t0")

    def __init__(self, timeline, name, cat, args):
        self._tl = timeline
        self._name = name
        self._cat = cat
        self._args = args

    def __enter__(self):
        self._t0 = time.time()
        return self

    def __exit__(self, *exc):
        self._tl.add_span(self._name, self._t0, time.time() - self._t0,
                          cat=self._cat, args=self._args)
        return False


def _jax_trace_events(trace_dir):
    """Best-effort: Chrome-format trace events under a ``jax.profiler``
    trace dir (TensorBoard layout writes ``*.trace.json.gz`` per host
    alongside the xplane protobuf).  Unreadable files are skipped — the
    merge must never break an export."""
    events = []
    for pattern in ("**/*.trace.json", "**/*.trace.json.gz"):
        for fname in glob.glob(os.path.join(trace_dir, pattern),
                               recursive=True):
            try:
                opener = gzip.open if fname.endswith(".gz") else open
                with opener(fname, "rt") as f:
                    payload = json.load(f)
                found = payload.get("traceEvents") \
                    if isinstance(payload, dict) else None
                if found:
                    # real events only: jax's writers pad with empty
                    # objects, which downstream consumers index into
                    events.extend(
                        e for e in found
                        if isinstance(e, dict) and e.get("ph")
                        and ("name" in e or e["ph"] == "M"))
            except (OSError, ValueError):
                continue
    return events
