"""mxnet_tpu.obs — the unified telemetry subsystem.

Three layers (docs/observability.md), shared process-wide singletons:

* :data:`registry` — the typed/labeled metrics registry
  (:mod:`~mxnet_tpu.obs.metrics`): counters, gauges, histograms behind
  one lock, with JSON-lines and Prometheus exporters;
* :data:`timeline` — the always-on trace timeline
  (:mod:`~mxnet_tpu.obs.trace`): a bounded ring buffer of thread-aware
  spans and instant events, exported as Chrome-trace JSON (Perfetto);
* :data:`programs` — per-program roofline accounting
  (:mod:`~mxnet_tpu.obs.roofline`): measured dispatch wall per compiled
  program joined against static FLOPs/bytes into the MFU table.

``profiler`` (the historical module) is a thin compatibility facade over
these; new code records here directly.  Instrumentation is HOST-side
only: nothing in this package runs inside a traced program, so compiled
HLO is byte-identical with telemetry on or off (``MXNET_TELEMETRY``),
and the zero-overhead tripwire in ``tests/test_obs.py`` plus the
analysis ``host-sync`` pass keep it that way.
"""
from __future__ import annotations

import time

from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,
                      PeriodicExporter, percentile)
from .prom import MetricsServer
from .roofline import (PEAK_FLOPS, ProgramAccounting, auto_peak,
                       peak_flops_for, render_mfu_table)
from .trace import TraceTimeline

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "MetricsServer",
    "PEAK_FLOPS", "PeriodicExporter", "ProgramAccounting", "TraceTimeline",
    "auto_peak", "enabled", "mfu_table", "peak_flops_for", "percentile",
    "program_span", "programs", "registry", "render_mfu_table",
    "serve_metrics", "span", "timeline",
]

from .. import config as _config

# ---------------------------------------------------------------------------
# process-wide singletons
# ---------------------------------------------------------------------------
registry = MetricsRegistry()
timeline = TraceTimeline(capacity=max(int(_config.get("MXNET_TRACE_BUFFER")),
                                      1))
programs = ProgramAccounting()


def enabled():
    """Whether telemetry recording is armed (``MXNET_TELEMETRY``).
    Counters predating the subsystem (``profiler.step_stats``'s loop
    accounting) stay on regardless; this gates the timeline spans /
    instant events and the per-program dispatch timing."""
    return bool(_config.get("MXNET_TELEMETRY"))


def mfu_table(peak_flops=None):
    """The per-program MFU/roofline table (see
    :meth:`~mxnet_tpu.obs.roofline.ProgramAccounting.table`); the peak
    defaults to ``MXNET_PEAK_FLOPS`` or the device spec sheet."""
    return programs.table(auto_peak() if peak_flops is None else peak_flops)


# ---------------------------------------------------------------------------
# no-op-when-disabled recording helpers (the instrumentation surface the
# rest of the framework calls — one isinstance-free fast path each)
# ---------------------------------------------------------------------------
class _NullCtx:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullCtx()


class _ProgramSpan:
    """Times one compiled-program dispatch: feeds the roofline
    accounting AND drops a span on the timeline (cat="program")."""

    __slots__ = ("_name", "_t0", "_w0")

    def __init__(self, name):
        self._name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        self._w0 = time.time()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        programs.note(self._name, dt)
        timeline.add_span(self._name, self._w0, dt, cat="program")
        return False


def program_span(name):
    """Context manager timing one dispatch of program ``name`` (no-op
    when telemetry is off)."""
    return _ProgramSpan(name) if enabled() else _NULL


def span(name, cat="host", args=None):
    """Context manager recording one timeline span (no-op when off)."""
    return timeline.span(name, cat=cat, args=args) if enabled() else _NULL


def instant(name, cat="event", args=None):
    """Record one timeline instant event (no-op when off)."""
    if enabled():
        timeline.instant(name, cat=cat, args=args)


# ---------------------------------------------------------------------------
# process-wide HTTP exporters — the registry/timeline are process-global,
# so one server per (host, port) is the correct cardinality; a second
# DecodeServer configured for the same port must REUSE the first server,
# not crash on EADDRINUSE
# ---------------------------------------------------------------------------
import threading as _threading

_servers = {}
_servers_lock = _threading.Lock()


def serve_metrics(port, host="127.0.0.1"):
    """Get-or-create the process-wide :class:`MetricsServer` bound to
    ``(host, port)``, serving the global registry and timeline."""
    key = (host, int(port))
    with _servers_lock:
        srv = _servers.get(key)
        if srv is None or srv._httpd is None:
            srv = MetricsServer(port=int(port), host=host).start()
            _servers[key] = srv
        return srv


# ---------------------------------------------------------------------------
# env-armed periodic JSON-lines export
# ---------------------------------------------------------------------------
_exporter = None


def _maybe_start_exporter():
    global _exporter
    path = _config.get("MXNET_METRICS_EXPORT")
    period = float(_config.get("MXNET_METRICS_EXPORT_PERIOD"))
    if _exporter is None and path and period > 0:
        _exporter = PeriodicExporter(registry, path, period).start()
    return _exporter


_maybe_start_exporter()
