"""Per-program roofline accounting — measured wall time vs static cost.

The telemetry subsystem's third layer (docs/observability.md): the
compiled-step dispatch wrappers (``CompiledTrainStep`` /
``CompiledEvalStep`` / ``DecodePredictor``) report host-observed wall
seconds per named program into one :class:`ProgramAccounting`, and each
program registers a LAZY static-cost prober
(:func:`mxnet_tpu.analysis.cost.program_cost`: dot FLOPs from the
lowered StableHLO, traffic bytes from arg+output avals through the
analysis width table).  :meth:`ProgramAccounting.table` joins the two
into the per-program MFU / achieved-bytes/s table ``bench.py`` publishes
in its JSON contract and ``tools/mxstat.py`` renders — the ROADMAP's
"track the roofline gap per kernel, not in aggregate".

Wall-time semantics: a program's ``wall_s`` is the host time spent
INSIDE its dispatch calls.  jax dispatch is asynchronous, so on a
backend with deep async queues this under-measures device time for a
single call — but ``fit()`` bounds in-flight steps on a fence
(``MXNET_MAX_STEPS_IN_FLIGHT``) and the decode loop reads each step's
tokens, so in the steady state the host is throttled by the device and
the accumulated dispatch wall converges to device wall.  The
interpretation caveats (and the ``host_wait`` cross-check) live in
docs/observability.md.  The probers trace+lower only (never compile,
never execute) and run at TABLE time, off every hot path.
"""
from __future__ import annotations

import threading

__all__ = ["ProgramAccounting", "PEAK_FLOPS", "peak_flops_for",
           "auto_peak", "render_mfu_table"]

# peak bf16 FLOP/s per chip by TPU generation (public spec sheets) —
# moved here from bench.py so the bench and the MFU table share one map
PEAK_FLOPS = {
    "TPU v2": 45e12 / 2,      # per-chip: 2 cores, 22.5T each
    "TPU v3": 123e12 / 2,
    "TPU v4": 275e12,
    "TPU v5e": 197e12,
    "TPU v5 lite": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 459e12,
    "TPU v6e": 918e12,
    "TPU v6 lite": 918e12,
    "TPU7x": 2307e12,
}


def peak_flops_for(device):
    """``(peak_flops_or_None, device_kind)`` for a jax device."""
    kind = getattr(device, "device_kind", "")
    for name, peak in PEAK_FLOPS.items():
        if kind.lower().startswith(name.lower()):
            return peak, kind
    return None, kind


def auto_peak():
    """The MFU denominator: ``MXNET_PEAK_FLOPS`` when set, else the spec
    peak of the first jax device, else ``None`` (CPU harness — the table
    still carries flops/bytes/wall, mfu reads null)."""
    from .. import config as _config

    override = float(_config.get("MXNET_PEAK_FLOPS"))
    if override > 0:
        return override
    try:
        import jax

        peak, _ = peak_flops_for(jax.devices()[0])
        return peak
    except Exception:
        return None


class ProgramAccounting:
    """Measured wall seconds + lazy static costs, per program name."""

    def __init__(self):
        self._lock = threading.Lock()
        self._timing = {}   # name -> [calls, wall_s]
        self._probers = {}  # name -> () -> {"flops", "bytes"} | None
        self._static = {}   # name -> resolved {"flops", "bytes"} | error row

    # ------------------------------------------------------------------
    def note(self, name, seconds):
        """One dispatch of ``name`` took ``seconds`` of host wall."""
        with self._lock:
            t = self._timing.get(name)
            if t is None:
                t = self._timing[name] = [0, 0.0]
            t[0] += 1
            t[1] += seconds

    def register_static(self, name, prober):
        """Attach a lazy static-cost prober (idempotent; the newest
        registration wins so a rebuilt program refreshes its cost).
        Producers register weakly-bound probers — a prober may return
        None (owner gone, or program not yet runnable) and the row then
        simply carries no static columns."""
        with self._lock:
            self._probers[name] = prober
            self._static.pop(name, None)

    def set_static(self, name, flops, bytes):
        """Directly record a program's static cost (mxstat --smoke, or a
        caller that already holds an artifact)."""
        with self._lock:
            self._static[name] = {"flops": int(flops), "bytes": int(bytes)}
            self._probers.pop(name, None)

    def reset(self, clear_static=False):
        """Zero the timings (a bench's measurement window starts here);
        static registrations survive unless ``clear_static``."""
        with self._lock:
            self._timing.clear()
            if clear_static:
                self._probers.clear()
                self._static.clear()

    # ------------------------------------------------------------------
    def _resolve_static(self, name):
        """Run (once) and cache ``name``'s prober.  A prober returning
        None (program not yet runnable) is retried next time; a raising
        prober is cached as an error so a broken lowering cannot re-pay
        its cost on every table."""
        with self._lock:
            hit = self._static.get(name)
            prober = self._probers.get(name)
        if hit is not None:
            return hit
        if prober is None:
            return None
        try:
            cost = prober()
        except Exception as exc:  # surfaced in the row, not raised
            cost = {"flops": None, "bytes": None, "error": str(exc)[:200]}
        if cost is None:
            return None
        with self._lock:
            self._static[name] = cost
            # resolved: drop the prober so it cannot pin its program's
            # owner (a model's whole parameter store) for process life
            self._probers.pop(name, None)
        return cost

    def table(self, peak_flops=None):
        """The joined per-program rows, sorted by wall share (largest
        first): ``{"program", "calls", "wall_s", "flops", "bytes",
        "achieved_tflops", "achieved_gbps", "mfu"}`` — flops/bytes are
        PER CALL; mfu is achieved FLOP/s over ``peak_flops`` (null
        without a peak)."""
        with self._lock:
            names = set(self._timing) | set(self._probers) \
                | set(self._static)
            timing = {n: tuple(v) for n, v in self._timing.items()}
        rows = []
        for name in names:
            calls, wall = timing.get(name, (0, 0.0))
            cost = self._resolve_static(name) or {}
            flops = cost.get("flops")
            nbytes = cost.get("bytes")
            row = {"program": name, "calls": calls,
                   "wall_s": round(wall, 6),
                   "flops": flops, "bytes": nbytes,
                   "achieved_tflops": None, "achieved_gbps": None,
                   "mfu": None}
            if cost.get("collective_bytes"):
                # programs with explicit exchanges (MoE all-to-all, ring
                # ppermute) break their wire traffic out of the floor
                row["collective_bytes"] = cost["collective_bytes"]
            if cost.get("gather_bytes"):
                # programs with materialized gather intermediates (the
                # einsum decode path's paged_gather view of the KV pool)
                # break them out too — the column the fused Pallas
                # flash-decoding kernel zeroes
                row["gather_bytes"] = cost["gather_bytes"]
            if cost.get("sort_scatter_bytes"):
                # programs with materialized sort/scatter intermediates
                # (the MoE sort-based dispatch's key sort + slot
                # scatter) — the column that prices the two
                # MXNET_MOE_DISPATCH algorithms against each other
                row["sort_scatter_bytes"] = cost["sort_scatter_bytes"]
            if cost.get("aot"):
                # programs dispatching an AOT-deserialized (or AOT-
                # compiled) executable carry their provenance — the
                # cold-start story made visible per program
                row["aot"] = cost["aot"]
            if cost.get("update_path"):
                # the opt_update row: which update path is armed, plus
                # both paths' priced bytes so the fused-vs-per-param
                # comparison travels with the table
                for k in ("update_path", "per_param_bytes",
                          "fused_bytes"):
                    row[k] = cost.get(k)
            if cost.get("fused_path"):
                # the lm_fused row: which LN->linear path the LM step's
                # FusedLNLinear segments dispatch, plus both paths'
                # priced bytes — the kernel's HBM diet vs the einsum
                # engine-op chain, per program
                for k in ("fused_path", "fused_kernel_bytes",
                          "fused_einsum_bytes", "fused_segments"):
                    row[k] = cost.get(k)
            if "error" in cost:
                row["error"] = cost["error"]
            if wall > 0 and calls > 0:
                if flops:
                    rate = flops * calls / wall
                    row["achieved_tflops"] = round(rate / 1e12, 6)
                    if peak_flops:
                        row["mfu"] = round(rate / peak_flops, 6)
                if nbytes:
                    row["achieved_gbps"] = round(nbytes * calls / wall / 1e9,
                                                 6)
            rows.append(row)
        rows.sort(key=lambda r: -r["wall_s"])
        return rows


def _fmt(v, unit=""):
    if v is None:
        return "-"
    if isinstance(v, float) and unit == "":
        return "%.4g" % v
    return "%s%s" % (v, unit)


def render_mfu_table(rows):
    """Fixed-width text rendering of :meth:`ProgramAccounting.table`
    rows (the ``tools/mxstat.py`` output).  The ``collective_bytes``
    column appears only when some program carries explicit exchanges
    (MoE all-to-all, ring ppermute)."""
    cols = ("program", "calls", "wall_s", "flops", "bytes",
            "achieved_tflops", "achieved_gbps", "mfu")
    if any(r.get("collective_bytes") for r in rows):
        cols = cols + ("collective_bytes",)
    if any(r.get("gather_bytes") for r in rows):
        cols = cols + ("gather_bytes",)
    if any(r.get("sort_scatter_bytes") for r in rows):
        cols = cols + ("sort_scatter_bytes",)
    if any(r.get("aot") for r in rows):
        cols = cols + ("aot",)
    table = [[str(c) for c in cols]]
    for r in rows:
        table.append([_fmt(r.get(c)) for c in cols])
    widths = [max(len(row[i]) for row in table) for i in range(len(cols))]
    lines = []
    for i, row in enumerate(table):
        lines.append("  ".join(c.rjust(w) for c, w in zip(row, widths)))
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)
