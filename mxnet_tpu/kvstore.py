"""KVStore — the data-parallel communication facade.

Reference: `src/kvstore/` + `python/mxnet/kvstore.py`.  The reference's
two-level stack (intra-node Comm reduce `src/kvstore/comm.h` + inter-node
ps-lite parameter server `src/kvstore/kvstore_dist.h`) collapses on TPU into
XLA collectives over the ICI mesh (SURVEY §5): gradients produced by a
mesh-sharded executor arrive **already all-reduced**, so `local`/`device`
push/pull degenerate to "apply optimizer, serve copies" — the same contract
`KVStoreLocal` exposes (`kvstore_local.h:22-127`), at ICI speed.

Multi-host (`dist_sync` / `dist_device_sync`): when `jax.distributed` is
initialized (the `tools/launch.py` analog is `mxnet_tpu.parallel.launch`),
push performs a cross-process psum over a global device mesh; `dist_async`
has no sane XLA analog and is accepted as an alias of `dist_sync` with a
logged deviation (SURVEY §7d).
"""
from __future__ import annotations

import logging
import pickle
import zlib

from .base import MXNetError
from . import ndarray as nd
from .ndarray import NDArray

__all__ = ["KVStore", "create"]


def _ensure_list(keys, vals):
    if isinstance(keys, (int, str)):
        return [keys], [vals]
    assert len(keys) == len(vals)
    return list(keys), list(vals)


class KVStore:
    """Key-value store for parameter synchronization (reference: kvstore.py:60)."""

    def __init__(self, kv_type="local"):
        self._type = kv_type
        self._store = {}
        self._updater = None
        self._optimizer = None
        self._heartbeat = None
        if kv_type.startswith("dist"):
            self._start_heartbeat()
            # reference parity: the dist store constructor rendezvouses all
            # workers (kvstore_dist.h:39) — unless this is a restarted
            # worker, whose peers are already past it
            self.barrier(startup=True)

    def _start_heartbeat(self):
        """Liveness stamps for failure detection (ps-lite heartbeat analog;
        see parallel.health).  Enabled by MXNET_HEARTBEAT_DIR — a directory
        every worker can reach.  One stamping thread per process however
        many dist stores exist; close() stops it."""
        import os

        directory = os.environ.get("MXNET_HEARTBEAT_DIR")
        if not directory:
            return
        from .parallel import health

        self._heartbeat = health.ensure_heartbeat(directory, self.rank)

    def close(self):
        """Stop this process's heartbeat (process-wide — affects every dist
        store sharing it)."""
        if self._heartbeat is not None:
            from .parallel import health

            health.stop_heartbeat(self._heartbeat.directory,
                                  self._heartbeat.rank)
            self._heartbeat = None

    def num_dead_node(self, node_id=0, timeout=None):
        """Count of workers with stale/missing heartbeats
        (reference: kvstore.h:235-244 get_num_dead_node; requires
        MXNET_HEARTBEAT_DIR, else 0)."""
        import os

        directory = os.environ.get("MXNET_HEARTBEAT_DIR")
        if not directory or not self._type.startswith("dist"):
            return 0
        from .parallel import health

        return health.num_dead_nodes(
            directory, self.num_workers,
            timeout if timeout is not None else health.DEFAULT_TIMEOUT)

    # -- core API ----------------------------------------------------------
    def init(self, key, value):
        keys, vals = _ensure_list(key, value)
        for k, v in zip(keys, vals):
            if k in self._store:
                raise MXNetError("Key %s already initialized" % str(k))
            self._store[k] = v.copy() if isinstance(v, NDArray) else v

    def push(self, key, value, priority=0):
        """Reduce value(s) into the stored weight; run updater if set.

        With a mesh-sharded executor the per-device grads are already
        globally summed by XLA psum, so `value` is typically a single
        array — matching reference semantics where Comm::Reduce has run.
        """
        keys, vals = _ensure_list(key, value)
        for k, v in zip(keys, vals):
            if isinstance(v, (list, tuple)):
                merged = v[0]
                for other in v[1:]:
                    merged = merged + other.as_in_context(merged.context)
            else:
                merged = v
            merged = self._allreduce(merged)
            if self._updater is not None:
                self._updater(self._str_to_int(k), merged, self._store[k])
            else:
                self._store[k]._set_data(merged.data.astype(self._store[k].dtype))

    def pull(self, key, out=None, priority=0):
        keys, outs = _ensure_list(key, out)
        for k, o in zip(keys, outs):
            if isinstance(o, (list, tuple)):
                for dst in o:
                    self._store[k].copyto(dst)
            else:
                self._store[k].copyto(o)

    def _allreduce(self, arr):
        """Cross-process sum when running multi-host."""
        import jax

        if jax.process_count() == 1 or self._type.startswith("local") \
                or self._type == "device":
            return arr
        from .parallel import collectives

        return NDArray(collectives.global_sum(arr.data), arr.context)

    @staticmethod
    def _str_to_int(k):
        # crc32 is stable across processes/runs (unlike str.__hash__, which is
        # salted per interpreter) so optimizer-state indices agree between
        # workers and across save/load.
        if isinstance(k, int):
            return k
        return zlib.crc32(k.encode("utf-8")) & 0x7FFFFFFF

    # -- updater / optimizer ----------------------------------------------
    def set_updater(self, updater):
        self._updater = updater

    _set_updater = set_updater

    def set_optimizer(self, optimizer):
        """Install optimizer server-side (reference pickles it to the PS,
        kvstore.py:226; here the 'server' is this process)."""
        if self._type.startswith("dist"):
            # exercise the pickle path for parity with the reference
            # protocol; a bound symbol holds op closures and cannot cross
            # the wire — detach it around the round-trip (its derived
            # lr/wd multiplier dicts are plain data and survive)
            import copy as _copy

            clone = _copy.copy(optimizer)     # never mutate the caller's
            had_sym = hasattr(clone, "sym")
            if had_sym:
                bound_sym = clone.sym
                clone.sym = None
            optimizer = pickle.loads(pickle.dumps(clone))
            if had_sym:
                optimizer.sym = bound_sym
        from .optimizer import get_updater

        self._optimizer = optimizer
        self.set_updater(get_updater(optimizer))

    # -- cluster topology --------------------------------------------------
    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        import jax

        return jax.process_index() if self._type.startswith("dist") else 0

    @property
    def num_workers(self):
        import jax

        return jax.process_count() if self._type.startswith("dist") else 1

    def barrier(self, startup=False):
        """Global barrier.  A restarted worker (MXNET_IS_RECOVERY=1) skips
        STARTUP barriers only — the peers it would rendezvous with are past
        them (reference: kvstore_dist.h:39,77 is_recovery branches)."""
        if startup:
            from .parallel.health import is_recovery

            if is_recovery():
                return
        if self.num_workers > 1:
            from .parallel import collectives

            collectives.barrier()

    _barrier = barrier

    def save_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("Cannot save states for distributed training")
        with open(fname, "wb") as fout:
            fout.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("Cannot load states for distributed training")
        with open(fname, "rb") as fin:
            self._updater.set_states(fin.read())

    def send_command_to_servers(self, head, body):
        logging.debug("kvstore command %s ignored (no parameter server on TPU)", head)

    _send_command_to_servers = send_command_to_servers


def create(name="local"):
    """Create a KVStore (reference: kvstore.py:373).

    Types: local | device | dist_sync | dist_device_sync | dist_async.
    On TPU `local` and `device` are the same store (XLA collectives do the
    reduce); `dist_async` degrades to sync with a warning.
    """
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    if name not in ("local", "device", "local_allreduce_cpu",
                    "local_allreduce_device", "dist_sync", "dist_device_sync",
                    "dist_async", "dist"):
        raise MXNetError("Unknown KVStore type %s" % name)
    if name == "dist_async":
        logging.warning("dist_async has no XLA analog; using synchronous "
                        "all-reduce semantics (documented deviation)")
    return KVStore(name)
