"""Retrace auditing — jit cache misses become a checked invariant.

A canonical program must trace exactly once per distinct input shape: a
second trace at the "same" shapes means the cache key drifted — a weak
type flipped, a dtype changed (x64 promotion, a float where an int
belonged), a python-hashable static changed identity.  Each retrace
recompiles the whole program mid-loop, which on a TPU rig turns a
microseconds step into seconds, silently.

:class:`RetraceAuditor` wraps a python callable BEFORE jitting: the
wrapper counts trace events (the python body runs only while tracing) and
records the abstract signature of every call, so after driving the
program the auditor can say not just *that* it retraced but *what
differed* between the colliding signatures.  The shipped step programs
(``CompiledTrainStep``, ``CompiledEvalStep``, ``DecodePredictor``) carry
the same counters built in; this class is the standalone tool for
auditing arbitrary jitted functions and the machinery behind the
dtype-drift tests.
"""
from __future__ import annotations

__all__ = ["RetraceAuditor", "arg_signature", "signature_diff"]


def arg_signature(args, kwargs=None):
    """Flatten a call's arguments into a hashable abstract signature:
    one ``(shape, dtype, weak_type)`` triple per array leaf."""
    import jax
    import jax.tree_util as jtu

    leaves = jtu.tree_leaves((args, kwargs or {}))
    sig = []
    for leaf in leaves:
        try:
            aval = jax.api_util.shaped_abstractify(leaf)
            sig.append((tuple(aval.shape), str(aval.dtype),
                        bool(getattr(aval, "weak_type", False))))
        except (TypeError, ValueError):
            # non-array static (python scalar in a static arg, string...)
            sig.append(("static", repr(leaf), False))
    return tuple(sig)


def signature_diff(a, b):
    """Human-readable leaf-wise differences between two signatures."""
    diffs = []
    if len(a) != len(b):
        diffs.append("leaf count %d != %d" % (len(a), len(b)))
    for i, (la, lb) in enumerate(zip(a, b)):
        if la == lb:
            continue
        parts = []
        for name, va, vb in zip(("shape", "dtype", "weak_type"), la, lb):
            if va != vb:
                parts.append("%s %s -> %s" % (name, va, vb))
        diffs.append("leaf %d: %s" % (i, "; ".join(parts)))
    return diffs


class RetraceAuditor:
    """Wrap a callable so its jit traces and call signatures are recorded.

    Usage::

        auditor = RetraceAuditor(step_impl)
        fn = jax.jit(auditor.wrapped, donate_argnums=(0,))
        fn(state, x); fn(state, x2)          # drive the program
        rec = auditor.record()
        assert rec["traces"] == len(rec["unique_signatures"])

    ``traces`` counts how many times the python body actually re-traced;
    ``signatures`` records one abstract signature per *call*.  More traces
    than unique signatures cannot happen (jax caches on the signature);
    more *unique signatures* than the program's expected shape variants is
    the drift the retrace pass reports, and ``diffs`` pinpoints which
    leaf's dtype/weak-type/shape moved between consecutive new signatures.
    """

    def __init__(self, fn, name=None):
        self._fn = fn
        self.name = name or getattr(fn, "__name__", "fn")
        self.traces = 0
        self.calls = 0
        self.signatures = []

        def wrapped(*args, **kwargs):
            self.traces += 1
            return fn(*args, **kwargs)

        wrapped.__name__ = getattr(fn, "__name__", "wrapped")
        self.wrapped = wrapped

    def observe(self, *args, **kwargs):
        """Record one call's signature (invoke right before the jitted
        call with the same arguments)."""
        self.calls += 1
        self.signatures.append(arg_signature(args, kwargs))

    def record(self, expected_traces=1):
        """Summary dict for ``ProgramArtifact.meta['retrace']``."""
        unique = []
        for sig in self.signatures:
            if sig not in unique:
                unique.append(sig)
        diffs = []
        for prev, cur in zip(unique, unique[1:]):
            diffs.append(signature_diff(prev, cur))
        return {
            "name": self.name,
            "traces": self.traces,
            "calls": self.calls,
            "unique_signatures": len(unique),
            "expected_traces": expected_traces,
            "diffs": diffs,
        }
