"""Static roofline cost of one compiled program — FLOPs + traffic bytes.

The join key of the telemetry subsystem's per-program MFU table
(``mxnet_tpu.obs.roofline``): the dispatch wrappers measure wall time,
this module prices the program —

* **FLOPs** from :func:`~mxnet_tpu.analysis.hlo_parse.dot_flops` over
  the LOWERED StableHLO (what the program asked for, before backend
  legalization — the same accounting the flop-dtype pass audits and the
  decode bench's O(1)-in-prefix assertion uses);
* **traffic bytes** as the sum of argument + output aval bytes through
  :func:`~mxnet_tpu.analysis.hlo_parse.shape_bytes`'s width table
  (f8/sub-byte aware — the same table that prices KV caches) PLUS the
  program's collective wire bytes
  (:func:`~mxnet_tpu.analysis.hlo_parse.stablehlo_collective_stats`
  over the same lowered text — the MoE all-to-all dispatch/combine,
  ring ppermutes and Megatron psums all land here, so an
  expert-parallel step's roofline row prices its exchanges).  This is
  the program's memory-traffic FLOOR: every operand read once, every
  result written once, every collective payload moved once;
  intermediates that spill past on-chip memory add to it, so
  achieved-bytes/s against HBM peak is a lower bound.

Everything here is trace+lower only — no compile, no execution, no
device work — and runs at table time, never on a hot path.
"""
from __future__ import annotations

__all__ = ["artifact_cost", "aval_bytes", "program_cost"]


def aval_bytes(tree):
    """Total bytes of every array leaf in ``tree`` (arrays or
    ShapeDtypeStructs), sized through the analysis width table."""
    import jax.tree_util as jtu

    from .hlo_parse import shape_bytes, shape_str

    return sum(shape_bytes(shape_str(leaf.shape, leaf.dtype))
               for leaf in jtu.tree_leaves(tree))


def program_cost(fn, args):
    """``{"flops", "bytes", "collective_bytes", "gather_bytes",
    "sort_scatter_bytes"}`` of a ``jax.jit``-wrapped callable at
    ``args`` (abstract or concrete): dot FLOPs from one trace→lower,
    arg+output bytes from the avals, collective wire bytes from the
    lowered StableHLO's explicit collectives, materialized-gather
    intermediate bytes
    (:func:`~mxnet_tpu.analysis.hlo_parse.stablehlo_gather_stats`:
    2x each gather result — one write, one re-read), and materialized
    sort/scatter intermediate bytes
    (:func:`~mxnet_tpu.analysis.hlo_parse.stablehlo_sort_scatter_stats`,
    same 2x rule).  The gather term is what prices the einsum decode
    path honestly: ``paged_gather``'s (B, M*page_tokens, E) dense-ring
    view of the KV pool is the largest intermediate in the serving
    system and is invisible to arg/output accounting, which understated
    decode bytes and OVERstated decode MFU until ISSUE-11.  The
    sort/scatter term does the same for the MoE dispatch algorithms
    (``MXNET_MOE_DISPATCH``): the sort path's key sort and slot scatter
    are priced, so the mfu_table compares it honestly against the
    one-hot cumsum pack it replaced.  All extras fold into ``bytes``
    and break out separately so the roofline table can show them.
    Callers holding trace-counting instrumentation must arm their
    probing flag around this (the trace here is a probe, same economics
    as ``artifact_from_jit``)."""
    import jax

    from .hlo_parse import (dot_flops, stablehlo_collective_stats,
                            stablehlo_gather_stats,
                            stablehlo_sort_scatter_stats)

    lowered = fn.trace(*args).lower().as_text()
    flops = dot_flops(lowered)
    coll = stablehlo_collective_stats(lowered)["total"]["bytes"]
    gath = stablehlo_gather_stats(lowered)["bytes"]
    srtsc = stablehlo_sort_scatter_stats(lowered)["total"]["bytes"]
    out = jax.eval_shape(fn, *args)
    return {"flops": int(flops),
            "bytes": int(aval_bytes((args, out))) + int(coll) + int(gath)
            + int(srtsc),
            "collective_bytes": int(coll),
            "gather_bytes": int(gath),
            "sort_scatter_bytes": int(srtsc)}


def artifact_cost(artifact):
    """Priced quantities of a BUILT artifact — one drift-snapshot row.

    Unlike :func:`program_cost` this needs no callable: everything is
    re-derived from the artifact's recorded text surfaces and metadata,
    so the drift gate (``analysis.passes.DriftPass`` + ``mxlint
    --record/--check``) compares exactly what the other passes audit.
    Quantities from a missing surface are simply absent — the pass
    reports the asymmetry instead of guessing zero."""
    from .hlo_parse import (collective_stats, dot_flops,
                            input_output_aliases, stablehlo_gather_stats,
                            stablehlo_sort_scatter_stats)

    row = {"donated": int(artifact.donated_leaves or 0)}
    if artifact.stablehlo_text is not None:
        row["dot_flops"] = int(dot_flops(artifact.stablehlo_text))
        row["gather_bytes"] = int(
            stablehlo_gather_stats(artifact.stablehlo_text)["bytes"])
        row["sort_scatter_bytes"] = int(stablehlo_sort_scatter_stats(
            artifact.stablehlo_text)["total"]["bytes"])
    if artifact.compiled_text is not None:
        stats = collective_stats(artifact.compiled_text)
        row["collective_count"] = int(stats["total"]["count"])
        row["collective_bytes"] = int(stats["total"]["bytes"])
        row["aliased"] = len({param for _, param in
                              input_output_aliases(artifact.compiled_text)})
    if artifact.meta.get("cache_bytes") is not None:
        row["cache_bytes"] = int(artifact.meta["cache_bytes"])
    return row
