"""ProgramArtifact — one canonical program's static surfaces, bundled.

An artifact carries every text form the passes inspect:

* ``jaxpr_text`` — the traced jaxpr (host-callback lint);
* ``stablehlo_text`` — the lowered, pre-optimization StableHLO (FLOP and
  dtype accounting: reflects what the program *asked for*, before backend
  legalization e.g. rewrites bf16 dots to f32 on CPU);
* ``compiled_text`` — the optimized HLO of the compiled executable
  (collective budgets, donation aliasing: what actually runs);

plus the metadata the passes check against: how many donated buffers the
program was traced with, the intended compute dtype, the mesh shape, and
the retrace instrumentation counters.

:func:`artifact_from_jit` builds all three surfaces from a jitted callable
in one ``trace -> lower -> compile`` chain — the uniform exposure used by
``CompiledTrainStep.artifact`` / ``CompiledEvalStep.artifact`` /
``DecodePredictor.*_artifact`` / ``Predictor.artifact``.
"""
from __future__ import annotations

from dataclasses import dataclass, field

__all__ = ["ProgramArtifact", "artifact_from_jit", "aval_of"]


def aval_of(x):
    """``jax.ShapeDtypeStruct`` mirror of an array, sharding preserved
    when it has one — the one helper behind every artifact probe, so the
    committed-vs-uncommitted handling stays in a single place."""
    import jax

    return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                sharding=getattr(x, "sharding", None))


@dataclass
class ProgramArtifact:
    """Static views + metadata of one canonical compiled program."""

    name: str
    jaxpr_text: str = None
    stablehlo_text: str = None
    compiled_text: str = None
    # donation contract: number of donated array buffers the program was
    # traced with (0 = nothing donated, the donation pass skips it)
    donated_leaves: int = 0
    # intended compute dtype of the program's hot math ("bfloat16" arms
    # the f32-upcast lint; None/"float32" disables it)
    compute_dtype: str = None
    # mesh axis sizes the program was built under, e.g. {"data": 2, ...}
    mesh_shape: dict = None
    # retrace contract: observed python-level trace count vs how many
    # distinct traces this program legitimately needs (shape variants)
    trace_count: int = None
    expected_traces: int = 1
    # content address of the compiled program when known
    # (ProgramSpec.fingerprint — the AOT cache key; equal fingerprints
    # prove two hosts run byte-identical programs)
    fingerprint: str = None
    meta: dict = field(default_factory=dict)

    def describe(self):
        return {
            "name": self.name,
            "has_jaxpr": self.jaxpr_text is not None,
            "has_stablehlo": self.stablehlo_text is not None,
            "has_compiled": self.compiled_text is not None,
            "donated_leaves": self.donated_leaves,
            "compute_dtype": self.compute_dtype,
            "mesh_shape": self.mesh_shape,
            "trace_count": self.trace_count,
            "expected_traces": self.expected_traces,
            "fingerprint": self.fingerprint,
        }


def artifact_from_jit(fn, args, name, donated_leaves=0, compute_dtype=None,
                      mesh_shape=None, trace_count=None, expected_traces=1,
                      compile_program=True, fingerprint=None, **meta):
    """Build a :class:`ProgramArtifact` from a ``jax.jit``-wrapped callable
    and the (abstract or concrete) arguments that select its trace.

    One ``fn.trace(*args)`` yields the jaxpr; its lowering yields the
    StableHLO; compiling the lowering yields the optimized HLO.  Tracing
    against ``jax.ShapeDtypeStruct`` avals keeps live buffers off the hook;
    the compile produces a throwaway executable (jit caches key on concrete
    arrays, not avals), so this is a probe, not a free read.
    """
    traced = fn.trace(*args)
    jaxpr_text = str(traced.jaxpr)
    lowered = traced.lower()
    stablehlo_text = lowered.as_text()
    compiled_text = lowered.compile().as_text() if compile_program else None
    return ProgramArtifact(
        name=name, jaxpr_text=jaxpr_text, stablehlo_text=stablehlo_text,
        compiled_text=compiled_text, donated_leaves=donated_leaves,
        compute_dtype=compute_dtype, mesh_shape=mesh_shape,
        trace_count=trace_count, expected_traces=expected_traces,
        fingerprint=fingerprint, meta=meta)
