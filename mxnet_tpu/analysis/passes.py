"""The shipped analysis passes (ten with ``schedule.SchedulePass``).

Each pass statically audits one performance invariant the framework's PRs
established, so a sharding-rule edit or a jit cache-key drift fails CI on
the 8-virtual-device CPU mesh instead of silently regressing a headline:

* :class:`DonationPass` — every donated buffer must survive to compiled
  ``input_output_alias`` (dropped donation = steady-state allocation).
* :class:`CollectiveBudgetPass` — collective counts/bytes per program
  stay within the committed ``benchmarks/budgets.json`` ceilings (a
  GSPMD-inserted all-gather from a sharding-spec regression trips it).
* :class:`RetracePass` — each canonical program traces exactly once per
  shape (weak-type/dtype drift = recompiles mid-loop).
* :class:`HostSyncPass` — no host-callback primitives inside device
  programs (the static half; ``fit()``'s ``MXNET_TRANSFER_GUARD`` runtime
  guard is the dynamic half).
* :class:`FlopDtypePass` — ``dot_flops`` coverage (uncounted dot-like ops
  are an error, not a silent zero) and f32 dots inside bf16 programs.
* :class:`CacheBytesPass` — decode KV-cache bytes (data + scale planes,
  sized through the f8/sub-byte-aware width table) stay within the
  committed ceiling, and a quantized config must actually store narrow
  data (an f32 data plane under MXNET_KV_DTYPE is an error — decode is
  bandwidth-bound on exactly these bytes).  Paged layouts are understood:
  the budget is the shared POOL's bytes (the whole serving HBM bill, not
  per-slot rings), and a dense-ring allocation under ``MXNET_KV_PAGED=1``
  is an error — the config promises paged memory management the program
  no longer performs.
* :class:`TunerCoveragePass` — every Pallas kernel module's block/split
  constants must be registered with the autotuner
  (:mod:`mxnet_tpu.ops.tuning`): a new hardcoded ``BLOCK_*`` that never
  joined its module's tunable space is a shape the tuning cache can
  never improve — exactly the silent plateau ISSUE-16 closes.
* :class:`ShardingCoveragePass` — partition-rule coverage over the bound
  param tree (``meta['sharding_coverage']``): every leaf resolves to a
  rule match or an *intentional* replicate; the placement degrade paths
  (rank mismatch / indivisible dims in ``programs/partition.py`` and the
  executor's TP rules) are errors naming the param, and the grouped-K/V
  degrade (``tp_rules._kv_head_axis``, ``meta['replicated_degrades']``)
  lints as a visible info row instead of a 4x HBM surprise.
* :class:`DriftPass` — the differential gate: each program's priced
  quantities (:func:`~mxnet_tpu.analysis.cost.artifact_cost`) compared
  against a content-addressed snapshot (``mxlint --record/--check``)
  within tolerance, so a PR that regresses dot FLOPs / collective bytes
  / cache bytes / donation without re-recording fails tier-1.

:class:`~mxnet_tpu.analysis.schedule.SchedulePass` (async-overlap
shadows) lives in :mod:`~mxnet_tpu.analysis.schedule` with its parser.
"""
from __future__ import annotations

from .framework import Pass
from .hlo_parse import (collective_stats, dot_flops_report,
                        input_output_aliases, shape_bytes_report)

__all__ = ["DonationPass", "CollectiveBudgetPass", "RetracePass",
           "HostSyncPass", "FlopDtypePass", "CacheBytesPass",
           "TunerCoveragePass", "ShardingCoveragePass", "DriftPass",
           "record_snapshot", "snapshot_hash"]


class DonationPass(Pass):
    """Donated buffers must appear in compiled ``input_output_alias``.

    The fused train step, eval step and decode step donate params / slots /
    caches so XLA updates them in place; a dtype or shape drift between a
    donated input and its updated output silently drops the alias and the
    steady-state step starts allocating (and copying) every call.  The
    artifact records how many buffers were donated at trace time; the
    compiled module header records how many XLA actually aliased.
    """

    name = "donation"
    requires = ("compiled",)

    def run(self, artifact, context):
        if not artifact.donated_leaves:
            return [self.finding(
                artifact, "info", "program donates nothing; pass skipped",
                code="no-donation")]
        aliases = input_output_aliases(artifact.compiled_text)
        aliased_params = {param for _, param in aliases}
        n = len(aliased_params)
        if n >= artifact.donated_leaves:
            return [self.finding(
                artifact, "info",
                "%d/%d donated buffers aliased" % (n, artifact.donated_leaves),
                code="aliased", aliased=n,
                donated=artifact.donated_leaves)]
        return [self.finding(
            artifact, "error",
            "dropped donation: %d buffers donated but only %d aliased in "
            "compiled input_output_alias — the step allocates fresh "
            "buffers (and copies) every call" % (artifact.donated_leaves, n),
            code="dropped-donation", aliased=n,
            donated=artifact.donated_leaves,
            alias_entries=[[list(path), param]
                           for path, param in aliases])]


class CollectiveBudgetPass(Pass):
    """Collective counts/bytes vs the committed budget ceilings.

    Budget layout (``benchmarks/budgets.json``)::

        {"programs": {"<program>": {"collectives": {
            "total": {"count": N, "bytes": B},
            "all-gather": {"count": N, "bytes": B}, ...}}},
         "suppressions": ["pass[:program[:code]]", ...]}

    Every ceiling is inclusive (measured == budget passes).  Collective
    ops present in the program but absent from its budget are errors —
    a GSPMD regression typically shows up as a brand-new all-gather, not
    as growth of an existing entry.  Byte ceilings more than 2x the
    measurement emit an info row suggesting the budget be re-tightened
    (``tools/mxlint.py --update-budgets``).
    """

    name = "collective-budget"
    requires = ("compiled",)

    def run(self, artifact, context):
        budget = context.budget_for(artifact.name) or {}
        ceilings = budget.get("collectives")
        stats = collective_stats(artifact.compiled_text)
        if ceilings is None:
            sev = "info" if stats["total"]["count"] == 0 else "warning"
            return [self.finding(
                artifact, sev,
                "no committed collective budget for this program "
                "(measured: %d collectives, %d bytes) — run "
                "tools/mxlint.py --update-budgets" %
                (stats["total"]["count"], stats["total"]["bytes"]),
                code="no-budget", measured=stats)]
        findings = []
        for op, measured in stats.items():
            if op == "overlappable":
                continue
            ceiling = ceilings.get(op)
            if ceiling is None:
                if op != "total" and measured["count"] > 0:
                    findings.append(self.finding(
                        artifact, "error",
                        "unbudgeted collective %r: %d op(s), %d bytes — a "
                        "sharding-spec regression inserted a collective "
                        "this program never had" %
                        (op, measured["count"], measured["bytes"]),
                        code="unbudgeted-op", op=op, measured=measured))
                continue
            for key in ("count", "bytes"):
                if key in ceiling and measured[key] > ceiling[key]:
                    findings.append(self.finding(
                        artifact, "error",
                        "collective %s %s over budget: %d > %d" %
                        (op, key, measured[key], ceiling[key]),
                        code="over-budget", op=op, kind=key,
                        measured=measured[key], budget=ceiling[key]))
            if "bytes" in ceiling and ceiling["bytes"] > 0 and \
                    ceiling["bytes"] > 2 * max(measured["bytes"], 1):
                findings.append(self.finding(
                    artifact, "info",
                    "collective %s byte budget %d is >2x the measured %d; "
                    "consider --update-budgets" %
                    (op, ceiling["bytes"], measured["bytes"]),
                    code="slack-budget", op=op))
        # ops budgeted but absent from the program: the ceiling is stale
        # headroom a future regression could silently refill — surface it
        for op, ceiling in ceilings.items():
            if op in stats or ceiling.get("count", 0) == 0:
                continue
            findings.append(self.finding(
                artifact, "info",
                "budgeted collective %r no longer appears in the program "
                "(%d op(s) / %d bytes of stale headroom); tighten with "
                "--update-budgets" %
                (op, ceiling.get("count", 0), ceiling.get("bytes", 0)),
                code="stale-budget", op=op, budget=ceiling))
        if not findings:
            findings.append(self.finding(
                artifact, "info",
                "within budget: %d collectives, %d bytes" %
                (stats["total"]["count"], stats["total"]["bytes"]),
                code="within-budget", measured=stats["total"]))
        return findings


class RetracePass(Pass):
    """Each canonical program traces exactly once per distinct shape.

    The artifact's ``trace_count`` comes from the step programs' built-in
    python-level trace counters (``CompiledTrainStep.trace_count``,
    ``DecodePredictor.trace_counts``) or a
    :class:`~mxnet_tpu.analysis.retrace.RetraceAuditor`; the builder
    drives every program at least twice at identical shapes before
    snapshotting, so a count above ``expected_traces`` is a cache miss at
    "the same" signature — dtype/weak-type drift.  The auditor's recorded
    signature diffs (``meta['retrace']``) say which leaf moved.
    """

    name = "retrace"
    requires = ()

    def run(self, artifact, context):
        if artifact.trace_count is None:
            return [self.finding(
                artifact, "info", "no retrace instrumentation on this "
                "artifact", code="no-instrumentation")]
        record = artifact.meta.get("retrace") or {}
        if artifact.meta.get("aot") and artifact.trace_count == 0:
            # AOT-prepared programs dispatch a deserialized (or
            # probe-compiled) executable: zero python-level traces is
            # the DESIGNED state, not missing instrumentation — surface
            # the provenance so "every host runs the canonical program,
            # not a local retrace" reads straight off the lint
            return [self.finding(
                artifact, "info",
                "0 traces: program dispatches an AOT %s executable "
                "(mxnet_tpu.programs.aot)" % artifact.meta["aot"],
                code="aot-loaded", source=artifact.meta["aot"])]
        if artifact.trace_count <= artifact.expected_traces:
            return [self.finding(
                artifact, "info",
                "traced %d time(s), %d expected" %
                (artifact.trace_count, artifact.expected_traces),
                code="no-retrace")]
        diffs = record.get("diffs") or []
        diff_text = "; ".join("|".join(d) for d in diffs if d) \
            or "no signature diff recorded"
        return [self.finding(
            artifact, "error",
            "retraced: %d traces for %d expected shape variant(s) — the "
            "jit cache key drifted (%s)" %
            (artifact.trace_count, artifact.expected_traces, diff_text),
            code="retrace", traces=artifact.trace_count,
            expected=artifact.expected_traces, record=record)]


# jaxpr primitives that round-trip through the host; any of them inside a
# hot-path program serializes the device on every step
_CALLBACK_PRIMS = ("pure_callback", "io_callback", "debug_callback")
# compiled-HLO ops that move data to/from the host mid-program.  send/recv
# are deliberately NOT listed: they also carry device-to-device channel
# traffic (cross-partition collectives can legalize through them).
_HLO_HOST_OPS = ("outfeed(", "infeed(")


class HostSyncPass(Pass):
    """No host round-trips inside device programs.

    Static scan: jaxpr callback primitives (``pure_callback`` /
    ``io_callback`` / ``debug_callback`` — a stray ``jax.debug.print``
    left in an op implementation lands here) and compiled-HLO host
    transfer ops.  The runtime half is ``MXNET_TRANSFER_GUARD``, which
    arms ``jax.transfer_guard_device_to_host`` around ``fit()``'s hot
    loop (docs/static_analysis.md).

    Sanctioned transfers: an artifact may carry
    ``meta['host_sync_allow']`` — a list of finding codes its owner
    declares intentional (the elastic checkpoint fence's d2h is the
    canonical case: the snapshot copies leave the program, the writer
    thread materializes them, and the sync-save fallback wraps its d2h in
    an explicit ``transfer_guard`` allow scope).  A matching finding is
    downgraded to an *info* row with a ``sanctioned:`` code prefix, so
    the waiver stays visible in reports instead of silently vanishing —
    the same philosophy as budget-file suppressions, but declared at the
    program, where the sanction's reason lives.
    """

    name = "host-sync"
    requires = ("jaxpr",)

    def run(self, artifact, context):
        findings = []
        sanctioned = set(artifact.meta.get("host_sync_allow") or ())

        def emit(code, message, **detail):
            if code in sanctioned:
                findings.append(self.finding(
                    artifact, "info",
                    "sanctioned host transfer (%s): %s" % (code, message),
                    code="sanctioned:" + code, **detail))
            else:
                findings.append(self.finding(artifact, "error", message,
                                             code=code, **detail))

        text = artifact.jaxpr_text
        for prim in _CALLBACK_PRIMS:
            n = text.count(prim)
            if n:
                emit(prim, "%d %s primitive(s) in the jaxpr: the program "
                     "round-trips through the host every step" % (n, prim),
                     count=n)
        if artifact.compiled_text is not None:
            for op in _HLO_HOST_OPS:
                n = sum(line.count(op)
                        for line in artifact.compiled_text.splitlines()
                        if "=" in line)
                if n:
                    emit("hlo-" + op.rstrip("("),
                         "%d %r op(s) in compiled HLO: host transfer "
                         "inside the program" % (n, op.rstrip("(")),
                         count=n)
        if not findings:
            findings.append(self.finding(
                artifact, "info", "no host callbacks or host transfers",
                code="clean"))
        return findings


class FlopDtypePass(Pass):
    """FLOP-counter coverage + unintended f32 upcasts in bf16 programs.

    Coverage: ``dot_flops`` underpins the O(1)-in-prefix decode assertion
    and the bench MFU numbers; a program containing dot-like ops the
    counter cannot parse (``uncounted_ops``) makes every one of those
    numbers a silent undercount — an error here.  Unknown element types
    in the program's shapes (the ``shape_bytes`` width table) are
    reported the same way.

    Dtype: in a program whose declared compute dtype is bfloat16/float16,
    every dot whose result element type is f32 is flagged (warning) — the
    classic symptom of a cast that re-promoted the MXU path.  Checked on
    the *lowered StableHLO*, which reflects what was asked for; backend
    legalization (XLA:CPU rewrites bf16 dots through f32) happens later
    and is out of scope.

    Pallas-decode tripwire: a decode/verify artifact built while
    ``MXNET_PALLAS_DECODE`` was armed carries ``meta['pallas_decode']``
    — the config PROMISED the fused flash-decoding kernel
    (``ops/pallas_decode.py``: gather + dequant + attention in one HBM
    pass).  The promise is checked at the artifact level: the traced
    jaxpr must contain a ``pallas_call`` (interpret or compiled) or the
    lowered StableHLO a TPU custom-call.  A program that quietly fell
    back to the three-pass ``paged_gather`` + einsum path — a shape
    gate, a dispatch regression — is an *error* here, so the fallback
    costs a red lint run instead of a silent 3x decode-bandwidth loss.
    """

    name = "flop-dtype"
    requires = ("stablehlo",)

    _PALLAS_PROMISES = (
        ("pallas_decode", "MXNET_PALLAS_DECODE", "pallas-decode",
         "fused Pallas flash-decoding kernel present "
         "(MXNET_PALLAS_DECODE honored)",
         "MXNET_PALLAS_DECODE promises the fused flash-decoding kernel "
         "but no pallas_call lowered into this program — decode "
         "attention silently fell back to the three-pass "
         "paged_gather+einsum path (shape gate or dispatch regression)"),
        ("pallas_update", "MXNET_PALLAS_UPDATE", "pallas-update",
         "fused multi-tensor Pallas optimizer-update kernel present "
         "(MXNET_PALLAS_UPDATE honored)",
         "MXNET_PALLAS_UPDATE promises the fused multi-tensor "
         "optimizer-update kernel but no pallas_call lowered into this "
         "program — the update silently fell back to the per-parameter "
         "XLA chain (plan gate or dispatch regression)"),
    )

    def run(self, artifact, context):
        findings = []
        for key, _knob, ok_code, ok_msg, fail_msg in self._PALLAS_PROMISES:
            if not artifact.meta.get(key):
                continue
            jaxpr = artifact.jaxpr_text or ""
            shlo = artifact.stablehlo_text or ""
            if "pallas_call" in jaxpr or "tpu_custom_call" in shlo:
                findings.append(self.finding(
                    artifact, "info", ok_msg, code=ok_code))
            else:
                findings.append(self.finding(
                    artifact, "error", fail_msg, code="pallas-fallback"))
        report = dot_flops_report(artifact.stablehlo_text)
        for rec in report["uncounted_ops"]:
            findings.append(self.finding(
                artifact, "error",
                "%d %r op(s) not modeled by dot_flops: FLOP totals for "
                "this program are undercounts" % (rec["count"], rec["op"]),
                code="uncounted:" + rec["op"], **rec))
        # unknown element types are scanned in the compiled HLO, whose
        # 'dtype[dims]' shape syntax is what shape_bytes parses (StableHLO
        # writes tensor<...> shapes)
        unknown = []
        if artifact.compiled_text is not None:
            _, unknown = shape_bytes_report(artifact.compiled_text)
        if unknown:
            findings.append(self.finding(
                artifact, "warning",
                "element types %s missing from the shape_bytes width "
                "table: byte accounting skips them" % (unknown,),
                code="unknown-dtype", dtypes=unknown))
        cd = (artifact.compute_dtype or "").lower()
        if cd in ("bfloat16", "bf16", "float16", "f16"):
            low = {"bfloat16": "bf16", "bf16": "bf16",
                   "float16": "f16", "f16": "f16"}[cd]
            bad = [d for d in report["dots"] if d["dtype"] == "f32"]
            if bad:
                findings.append(self.finding(
                    artifact, "warning",
                    "%d of %d dots compute in f32 inside a %s program — "
                    "an upcast re-promoted the matmul path (first: %s)" %
                    (len(bad), len(report["dots"]), low,
                     bad[0]["line"][:160]),
                    code="f32-dot", count=len(bad),
                    total_dots=len(report["dots"]),
                    lines=[d["line"][:160] for d in bad[:8]]))
        if not findings:
            findings.append(self.finding(
                artifact, "info",
                "%d dot(s), %d FLOPs, full coverage" %
                (len(report["dots"]), report["flops"]),
                code="covered", flops=report["flops"]))
        return findings


# dtypes a cache DATA plane may use under a quantized MXNET_KV_DTYPE; the
# fp32 scale plane rides separately and is counted in cache_bytes
_NARROW_CACHE_DTYPES = ("int8", "float8_e4m3fn", "float8_e5m2",
                        "float8_e4m3fnuz", "float8_e5m2fnuz", "int4")


class CacheBytesPass(Pass):
    """Decode KV-cache bytes vs the committed ceiling; quantized configs
    must store narrow data; paged configs must store pages.

    Decode is bandwidth-bound on the cache: every step streams the whole
    (B, C, E) K/V per layer, so cache bytes ARE the serving-cost
    denominator (``bench_decode.py``'s tokens/s/GB headline).  The
    decode-layer artifacts record ``meta['cache_bytes']`` — data plus
    per-(token, head) scale planes, sized statically through
    ``hlo_parse.shape_bytes``'s width table (f8/sub-byte aware) — plus
    ``meta['kv_dtype']``/``meta['cache_data_dtypes']`` and
    ``meta['cache_layout']`` ('dense' ring buffers per slot, or 'paged':
    shared page pools whose recorded bytes are the POOL total — the
    serving HBM bill a page-table regression would silently re-inflate).
    Budget layout::

        {"programs": {"<program>": {"cache_bytes": N}}}

    Findings: bytes over the ceiling = error (a dtype regression silently
    doubling the cache); a quantized ``kv_dtype`` whose data planes are
    full-precision = error (the quantize plumbing got dropped — the
    config promises narrow reads it no longer performs); a dense-ring
    allocation under a paged config (``meta['kv_paged']``) = error (the
    page-pool plumbing got dropped — HBM scales with slots x max-context
    again); no committed ceiling = warning nudging ``--update-budgets``
    hygiene.  Programs without cache metadata (training steps) skip with
    an info row.
    """

    name = "cache-bytes"
    requires = ()

    def run(self, artifact, context):
        cache_bytes = artifact.meta.get("cache_bytes")
        if cache_bytes is None:
            return [self.finding(
                artifact, "info", "no KV-cache metadata; pass skipped",
                code="no-cache")]
        findings = []
        kv_dtype = artifact.meta.get("kv_dtype")
        data_dtypes = artifact.meta.get("cache_data_dtypes") or []
        layout = artifact.meta.get("cache_layout")
        if artifact.meta.get("kv_paged") and layout == "dense":
            findings.append(self.finding(
                artifact, "error",
                "MXNET_KV_PAGED promises paged KV caches but this program "
                "allocates dense ring buffers — the page-pool plumbing "
                "was dropped and serving HBM scales with "
                "slots x max-context again",
                code="dense-under-paged", layout=layout))
        if kv_dtype:
            wide = [d for d in data_dtypes
                    if d not in _NARROW_CACHE_DTYPES]
            if wide:
                findings.append(self.finding(
                    artifact, "error",
                    "kv_dtype=%s promises quantized caches but data "
                    "planes store %s — the quantize path was dropped and "
                    "every decode step streams full-precision bytes"
                    % (kv_dtype, wide),
                    code="f32-cache", kv_dtype=kv_dtype, wide=wide))
        # grouped-K/V promise (meta['num_kv_heads'] from a GQA config):
        # every cache/pool plane must be H_kv head slices wide — an H_q-
        # wide allocation means the num_kv_heads plumbing was dropped and
        # the G× pool shrink silently forfeited
        if artifact.meta.get("num_kv_heads"):
            widths = artifact.meta.get("cache_kv_dims") or []
            for dims in artifact.meta.get("attn_dims") or []:
                q_dim = dims.get("q_dim")
                kv_dim = dims.get("kv_dim")
                if dims.get("num_kv_heads") == dims.get("num_heads") \
                        or q_dim == kv_dim or kv_dim is None:
                    continue
                if q_dim in widths:
                    findings.append(self.finding(
                        artifact, "error",
                        "config promises grouped K/V (num_kv_heads=%s < "
                        "num_heads=%s) but a cache/pool plane allocates "
                        "the full q width %d (expected %d) — the grouped "
                        "layout was dropped and the pool is G× too large"
                        % (dims.get("num_kv_heads"),
                           dims.get("num_heads"), q_dim, kv_dim),
                        code="mha-under-gqa", q_dim=q_dim, kv_dim=kv_dim))
        budget = context.budget_for(artifact.name) or {}
        ceiling = budget.get("cache_bytes")
        if ceiling is None:
            findings.append(self.finding(
                artifact, "warning",
                "no committed cache-byte budget for this program "
                "(measured: %d bytes) — run tools/mxlint.py "
                "--update-budgets" % cache_bytes,
                code="no-budget", measured=cache_bytes))
        elif cache_bytes > ceiling:
            findings.append(self.finding(
                artifact, "error",
                "cache bytes over budget: %d > %d — the per-token "
                "bandwidth bill grew (dtype or shape regression in the "
                "ring buffers)" % (cache_bytes, ceiling),
                code="over-budget", measured=cache_bytes, budget=ceiling))
        if not findings:
            findings.append(self.finding(
                artifact, "info",
                "cache within budget: %d <= %d bytes (kv_dtype=%s, %s)"
                % (cache_bytes, ceiling, kv_dtype or "full-precision",
                   layout or "dense"),
                code="within-budget", measured=cache_bytes,
                budget=ceiling, kv_dtype=kv_dtype, layout=layout))
        return findings


# the tunable-constant surface the tuner-coverage audit matches: block
# shapes (BLOCK*) and split counts (*SPLIT/SPLITS).  MIN_* floors are
# support gates (below them the kernels fall back to einsum), and LANES
# is the TPU register lane width — neither is a tunable, so neither
# needs a tuning-space registration.
_TUNABLE_CONST_RE = r"^(BLOCK[A-Z_0-9]*|[A-Z_0-9]*SPLITS?)$"


class TunerCoveragePass(Pass):
    """Every Pallas module's block/split constants registered with the
    autotuner.

    Static source audit, not an artifact property: each
    ``ops/pallas_*.py`` module is AST-scanned for module-level ALL_CAPS
    ``BLOCK*``/``*SPLITS`` assignments, and each found name must appear
    in that module's registered tuning space
    (``tuning.spaces()[module].constants``).  A constant outside the
    space is a block shape ``MXNET_PALLAS_TUNE`` can never sweep — the
    hardcoded-plateau regression this pass exists to catch — and reads
    as an error.  The scan is repo-global, so it runs ONCE per drive
    (findings land on the first artifact; later artifacts skip with an
    info row).
    """

    name = "tuner-coverage"
    requires = ()

    def __init__(self):
        self._ran = False

    def _scan(self):
        import ast
        import glob
        import os
        import re

        ops_dir = os.path.join(os.path.dirname(__file__), "..", "ops")
        pat = re.compile(_TUNABLE_CONST_RE)
        found = {}
        for path in sorted(glob.glob(os.path.join(ops_dir, "pallas_*.py"))):
            mod = os.path.splitext(os.path.basename(path))[0]
            with open(path) as f:
                tree = ast.parse(f.read(), filename=path)
            names = []
            for node in tree.body:
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, ast.AnnAssign):
                    targets = [node.target]
                for tgt in targets:
                    if isinstance(tgt, ast.Name) and pat.match(tgt.id) \
                            and not tgt.id.startswith("MIN_"):
                        names.append(tgt.id)
            found[mod] = names
        return found

    def run(self, artifact, context):
        if self._ran:
            return [self.finding(
                artifact, "info", "tuner coverage audited once per drive",
                code="already-ran")]
        self._ran = True
        from ..ops import tuning

        spaces = tuning.spaces()
        findings = []
        total = 0
        for mod, names in self._scan().items():
            if not names:
                continue
            space = spaces.get(mod)
            registered = set(space.constants) if space is not None else set()
            missing = [n for n in names if n not in registered]
            total += len(names)
            if space is None:
                findings.append(self.finding(
                    artifact, "error",
                    "ops/%s.py hardcodes block constants %s but registers "
                    "no tuning space at all — MXNET_PALLAS_TUNE cannot "
                    "sweep this kernel (ops/tuning.register_space)"
                    % (mod, names), code="no-space", module=mod,
                    constants=names))
            elif missing:
                findings.append(self.finding(
                    artifact, "error",
                    "ops/%s.py block constants %s are not governed by the "
                    "module's registered tuning space (constants=%s) — "
                    "the autotuner can never improve them"
                    % (mod, missing, sorted(registered)),
                    code="unregistered-constant", module=mod,
                    missing=missing, registered=sorted(registered)))
        if not findings:
            findings.append(self.finding(
                artifact, "info",
                "%d block/split constants across %d Pallas modules all "
                "registered with the autotuner"
                % (total, len([1 for n in self._scan().values() if n])),
                code="covered", constants=total))
        return findings


class ShardingCoveragePass(Pass):
    """Partition-rule coverage over the bound param tree.

    Mesh-bound programs stamp ``meta['sharding_coverage']`` — per-leaf
    records written at placement time by ``programs/partition.
    build_shardings`` (decode) and ``module/executor_group.
    _param_sharding`` (train)::

        {"mesh": {"data": 2, "model": 2},
         "leaves": {"<param>": {"shape": [...],
                                "source": "rule|plan|mesh_axes|naive|"
                                          "default|scalar",
                                "spec": [...],        # when sharded
                                "degrade": "rank-mismatch|indivisible"}}}

    Findings:

    * a leaf a rule/plan MATCHED but the divisibility guard silently
      replicated (``degrade``) is an **error naming the param** — the
      intended placement was lost, every shard now holds the whole
      tensor (the 4x-HBM surprise this pass exists to catch);
    * the grouped-K/V cache degrade (``meta['replicated_degrades']``
      from ``tp_rules._kv_head_axis`` — ``H_kv % model != 0``) is a
      visible *info* row: legitimate, but never silent;
    * an UNMATCHED >=2-D leaf replicating by default is an **error**
      when the program's budget opts into strict coverage
      (``{"sharding": {"strict": true}}``) and a visible *info*
      otherwise — scalars and 1-D per-feature vectors always count as
      intentional replicates.

    Programs without a mesh (or predating the stamping) skip with an
    info row.
    """

    name = "sharding-coverage"
    requires = ()

    def run(self, artifact, context):
        findings = []
        for rec in artifact.meta.get("replicated_degrades") or []:
            findings.append(self.finding(
                artifact, "info",
                "%s degraded to replicated K/V sharding: %s — each "
                "model shard holds the full grouped K/V (visible "
                "degrade, see parallel/tp_rules._kv_head_axis)"
                % (rec.get("site", "kv sharding"),
                   rec.get("reason", "?")),
                code="kv-replicated-degrade", **rec))
        cov = artifact.meta.get("sharding_coverage")
        if cov is None:
            if not findings:
                return [self.finding(
                    artifact, "info",
                    "no sharding-coverage metadata (unmeshed program); "
                    "pass skipped", code="no-mesh")]
            return findings
        mesh = cov.get("mesh") or {}
        leaves = cov.get("leaves") or {}
        strict = bool(((context.budget_for(artifact.name) or {})
                       .get("sharding") or {}).get("strict"))
        meshed = any(int(v) > 1 for v in mesh.values())
        matched = unmatched_big = intentional = 0
        for name in sorted(leaves):
            rec = leaves[name]
            shape = rec.get("shape") or []
            degrade = rec.get("degrade")
            source = rec.get("source")
            if degrade:
                findings.append(self.finding(
                    artifact, "error",
                    "param %r matched a partition rule but DEGRADED to "
                    "full replication (%s, shape %s on mesh %s) — every "
                    "shard holds the whole tensor; fix the rule or the "
                    "shape, or waive it explicitly in the budget file"
                    % (name, degrade, shape, mesh),
                    code="replicated-degrade", param=name,
                    degrade=degrade, shape=shape))
            elif source in ("rule", "plan", "mesh_axes", "naive") \
                    and rec.get("spec"):
                matched += 1
            elif source == "default" and meshed \
                    and sum(1 for d in shape if int(d) > 1) >= 2:
                # effective rank counts dims > 1: a [1, 1, 16] LN gain
                # is a per-feature vector (always an intentional
                # replicate), a [1, 16, 16] embedding table is not
                unmatched_big += 1
                findings.append(self.finding(
                    artifact, "error" if strict else "info",
                    "param %r (shape %s) matched NO partition rule and "
                    "fully replicates on mesh %s — declare a rule or an "
                    "intentional replicate%s"
                    % (name, shape, mesh,
                       "" if strict else " (info: budget has no "
                       "{'sharding': {'strict': true}})"),
                    code="unmatched-param", param=name, shape=shape))
            else:
                intentional += 1
        if not findings:
            findings.append(self.finding(
                artifact, "info",
                "%d leaves covered: %d sharded by rule, %d intentional "
                "replicates, 0 degrades on mesh %s"
                % (len(leaves), matched, intentional, mesh),
                code="covered", leaves=len(leaves), sharded=matched,
                replicated=intentional))
        return findings


# ---------------------------------------------------------------------------
# drift snapshots (mxlint --record / --check)
# ---------------------------------------------------------------------------

# quantities compared EXACTLY (structural integers: a donation map or a
# collective count has no tolerance band)
_DRIFT_EXACT = ("donated", "aliased", "collective_count")
# quantities compared within the snapshot's relative tolerance
_DRIFT_PRICED = ("dot_flops", "collective_bytes", "gather_bytes",
                 "sort_scatter_bytes", "cache_bytes")
_SNAPSHOT_VERSION = 1


def snapshot_hash(snapshot):
    """Content address of a drift snapshot: a digest over its canonical
    JSON minus the hash field itself.  ``load_snapshot`` refuses a file
    whose recorded hash no longer matches — hand-edited baselines must
    go through ``mxlint --record``, not a text editor."""
    import hashlib
    import json

    body = {k: v for k, v in snapshot.items() if k != "content_hash"}
    blob = json.dumps(body, sort_keys=True, default=str)
    return hashlib.blake2b(blob.encode(), digest_size=16).hexdigest()


def record_snapshot(artifacts, report=None, tolerance=0.02):
    """Build a drift snapshot dict over built artifacts.

    Per program: the priced quantities
    (:func:`~mxnet_tpu.analysis.cost.artifact_cost`), the program
    fingerprint when known, and the pass-finding severity counts from
    ``report`` (so a baseline records what lint state it was taken in).
    ``tolerance`` is the relative band the check applies to priced
    quantities; structural integers compare exactly."""
    from .cost import artifact_cost

    per_prog = {}
    if report is not None:
        for f in report.findings:
            row = per_prog.setdefault(f.program,
                                      {"errors": 0, "warnings": 0})
            if f.severity != "info" and not f.suppressed:
                row[f.severity + "s"] += 1
    programs = {}
    for art in artifacts:
        row = artifact_cost(art)
        row["fingerprint"] = art.fingerprint
        if art.name in per_prog:
            row["findings"] = per_prog[art.name]
        programs[art.name] = row
    snap = {"version": _SNAPSHOT_VERSION, "tolerance": tolerance,
            "programs": programs}
    snap["content_hash"] = snapshot_hash(snap)
    return snap


class DriftPass(Pass):
    """The differential gate: priced quantities vs a recorded snapshot.

    ``mxlint --record <snapshot.json>`` writes the content-addressed
    baseline; ``mxlint --check <snapshot.json>`` loads it into the
    context and this pass compares each program's measured
    :func:`~mxnet_tpu.analysis.cost.artifact_cost` row against it:

    * a priced quantity (dot FLOPs, collective/gather/sort-scatter
      bytes, cache bytes) GROWN beyond the snapshot's relative
      tolerance is an **error naming the program and the quantity** —
      the regression gate the bench trajectory never had;
    * a structural integer (donated, aliased, collective count) compares
      exactly;
    * a quantity that SHRANK beyond tolerance is an *info* row (an
      improvement to bank: re-record so the gate tightens);
    * a program missing from the snapshot (or a snapshot program that
      was not built) is a **warning** — the baseline is stale and must
      be re-recorded;
    * a changed fingerprint alone is an *info* row (fingerprints move
      with any intentional retrace; the priced quantities decide).

    No snapshot loaded -> one info row per program.
    """

    name = "drift"
    requires = ()

    def run(self, artifact, context):
        from .cost import artifact_cost

        snap = context.snapshot
        if not snap:
            return [self.finding(
                artifact, "info",
                "no drift snapshot loaded; record one with "
                "tools/mxlint.py --record <snapshot.json>",
                code="no-snapshot")]
        findings = []
        recorded = snap.get("programs", {})
        row = recorded.get(artifact.name)
        if row is None:
            findings.append(self.finding(
                artifact, "warning",
                "program absent from the drift snapshot — re-record "
                "the baseline (tools/mxlint.py --record)",
                code="new-program"))
            return findings
        measured = artifact_cost(artifact)
        tol = float(snap.get("tolerance", 0.02))
        drifted = []
        for key in _DRIFT_EXACT + _DRIFT_PRICED:
            was, now = row.get(key), measured.get(key)
            if was is None and now is None:
                continue
            if was is None or now is None:
                findings.append(self.finding(
                    artifact, "warning",
                    "quantity %r %s the snapshot but %s this run — "
                    "surfaces changed; re-record the baseline"
                    % (key, "missing from" if was is None else "in",
                       "measured" if was is None else "unmeasured"),
                    code="asymmetric-quantity", quantity=key,
                    recorded=was, measured=now))
                continue
            if key in _DRIFT_EXACT:
                if now != was:
                    drifted.append((key, was, now, "error"))
                continue
            band = tol * max(abs(was), 1)
            if now > was + band:
                drifted.append((key, was, now, "error"))
            elif now < was - band:
                drifted.append((key, was, now, "info"))
        for key, was, now, sev in drifted:
            pct = 100.0 * (now - was) / max(abs(was), 1)
            if sev == "error":
                findings.append(self.finding(
                    artifact, "error",
                    "%s drifted %+.1f%% (%d -> %d) beyond the %.0f%% "
                    "tolerance without a re-recorded baseline — an "
                    "intentional change ships with tools/mxlint.py "
                    "--record, a regression gets fixed"
                    % (key, pct, was, now, 100 * tol),
                    code="drift:" + key, quantity=key, recorded=was,
                    measured=now, tolerance=tol))
            else:
                findings.append(self.finding(
                    artifact, "info",
                    "%s improved %+.1f%% (%d -> %d); re-record so the "
                    "gate banks the win" % (key, pct, was, now),
                    code="improved:" + key, quantity=key, recorded=was,
                    measured=now))
        if row.get("fingerprint") and artifact.fingerprint \
                and row["fingerprint"] != artifact.fingerprint:
            findings.append(self.finding(
                artifact, "info",
                "program fingerprint changed (%s -> %s); priced "
                "quantities decide whether it matters"
                % (row["fingerprint"][:12], artifact.fingerprint[:12]),
                code="fingerprint-changed"))
        if not findings:
            findings.append(self.finding(
                artifact, "info",
                "all priced quantities within %.0f%% of the snapshot"
                % (100 * tol), code="within-tolerance"))
        return findings
