"""The pass framework: findings, suppressions, and the driver.

Each of the framework's headline invariants (fewer collective bytes than
GSPMD, async-pair overlap, zero per-step host syncs, O(1)-in-prefix decode
FLOPs) used to be asserted ad hoc by one test reading ``hlo_stats`` output.
This module gives them a common shape: a :class:`Pass` inspects a
:class:`~mxnet_tpu.analysis.artifact.ProgramArtifact` (jaxpr + lowered
StableHLO + compiled HLO + metadata) and emits structured
:class:`Finding`\\ s; :func:`run_passes` drives every pass over every
artifact and folds the results into a :class:`Report` with severity
ordering and suppression support.

Suppression syntax (budget file ``suppressions`` list, the
``MXNET_ANALYSIS_SUPPRESS`` env var, or the ``suppressions=`` argument):
``pass-name``, ``pass-name:program``, or ``pass-name:program:code`` —
``*`` wildcards any segment.  Suppressed findings stay in the report
(marked ``suppressed``) so an audit can see what was waived.
"""
from __future__ import annotations

import fnmatch
import json
from dataclasses import dataclass, field

__all__ = ["Finding", "Pass", "Report", "run_passes", "SEVERITIES"]

# severity order: index = badness.  "info" never fails a run.
SEVERITIES = ("info", "warning", "error")


@dataclass
class Finding:
    """One structured result of a pass over a program."""

    pass_name: str
    program: str
    severity: str           # "error" | "warning" | "info"
    message: str
    code: str = ""          # stable machine key for suppressions
    detail: dict = field(default_factory=dict)
    suppressed: bool = False

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError("severity %r not in %s"
                             % (self.severity, SEVERITIES))

    def to_dict(self):
        return {"pass": self.pass_name, "program": self.program,
                "severity": self.severity, "code": self.code,
                "message": self.message, "suppressed": self.suppressed,
                "detail": self.detail}

    def __str__(self):
        tag = " [suppressed]" if self.suppressed else ""
        code = ":" + self.code if self.code else ""
        return "%s%s %s(%s)%s: %s" % (self.severity.upper(), tag,
                                      self.pass_name, self.program, code,
                                      self.message)


class Pass:
    """Base class for analysis passes.

    Subclasses set ``name`` and implement :meth:`run`, returning a list of
    findings for one artifact.  ``requires`` names the artifact text
    surfaces the pass reads (``"jaxpr"``, ``"stablehlo"``, ``"compiled"``);
    the driver emits an *info* finding instead of calling :meth:`run` when
    a required surface is missing, so a partially-built artifact degrades
    visibly rather than silently passing.
    """

    name = "pass"
    requires = ()

    def run(self, artifact, context):
        raise NotImplementedError

    def finding(self, artifact, severity, message, code="", **detail):
        return Finding(pass_name=self.name, program=artifact.name,
                       severity=severity, message=message, code=code,
                       detail=detail)


@dataclass
class AnalysisContext:
    """Shared state the driver hands every pass."""

    budgets: dict = field(default_factory=dict)
    # parsed drift snapshot (``mxlint --check``); None = drift pass
    # reports "no snapshot loaded" info rows instead of comparing
    snapshot: dict = None

    def budget_for(self, program):
        return self.budgets.get("programs", {}).get(program)


class Report:
    """All findings of one :func:`run_passes` drive."""

    def __init__(self, findings, programs=(), passes=()):
        self.findings = list(findings)
        self.programs = list(programs)
        self.passes = list(passes)

    def _active(self):
        return [f for f in self.findings if not f.suppressed]

    @property
    def errors(self):
        return [f for f in self._active() if f.severity == "error"]

    @property
    def warnings(self):
        return [f for f in self._active() if f.severity == "warning"]

    @property
    def unsuppressed(self):
        """Actionable findings: unsuppressed errors + warnings (info rows
        are advisory and never fail a run)."""
        return [f for f in self._active() if f.severity != "info"]

    @property
    def suppressed(self):
        return [f for f in self.findings if f.suppressed]

    def ok(self):
        return not self.errors

    def summary(self):
        return {
            "programs": len(self.programs),
            "passes": len(self.passes),
            "findings": len(self.findings),
            "errors": len(self.errors),
            "warnings": len(self.warnings),
            "suppressed": len(self.suppressed),
            "unsuppressed": len(self.unsuppressed),
        }

    def to_json(self):
        return json.dumps({"summary": self.summary(),
                           "findings": [f.to_dict() for f in self.findings]})

    def format_text(self, include_info=True):
        lines = []
        order = {s: -i for i, s in enumerate(SEVERITIES)}
        for f in sorted(self.findings,
                        key=lambda f: (f.suppressed, order[f.severity],
                                       f.pass_name, f.program)):
            if not include_info and f.severity == "info":
                continue
            lines.append(str(f))
        s = self.summary()
        lines.append("%(errors)d error(s), %(warnings)d warning(s), "
                     "%(suppressed)d suppressed over %(programs)d "
                     "program(s) x %(passes)d pass(es)" % s)
        return "\n".join(lines)


def _parse_suppressions(spec):
    """Normalize a suppression spec (iterable or comma string) into
    (pass, program, code) glob triples."""
    if spec is None:
        return []
    if isinstance(spec, str):
        spec = [t for t in spec.split(",") if t.strip()]
    triples = []
    for token in spec:
        parts = [p.strip() or "*" for p in str(token).split(":")]
        while len(parts) < 3:
            parts.append("*")
        triples.append(tuple(parts[:3]))
    return triples


def _is_suppressed(finding, triples):
    for pat_pass, pat_prog, pat_code in triples:
        if fnmatch.fnmatchcase(finding.pass_name, pat_pass) \
                and fnmatch.fnmatchcase(finding.program, pat_prog) \
                and fnmatch.fnmatchcase(finding.code or "*", pat_code):
            return True
    return False


def default_passes():
    """Fresh instances of the ten shipped passes, in run order."""
    from .passes import (CacheBytesPass, CollectiveBudgetPass, DonationPass,
                         DriftPass, FlopDtypePass, HostSyncPass,
                         RetracePass, ShardingCoveragePass,
                         TunerCoveragePass)
    from .schedule import SchedulePass

    return [DonationPass(), CollectiveBudgetPass(), RetracePass(),
            HostSyncPass(), FlopDtypePass(), CacheBytesPass(),
            TunerCoveragePass(), SchedulePass(), ShardingCoveragePass(),
            DriftPass()]


_SURFACE_ATTR = {"jaxpr": "jaxpr_text", "stablehlo": "stablehlo_text",
                 "compiled": "compiled_text"}


def run_passes(artifacts, passes=None, budgets=None, suppressions=None,
               snapshot=None):
    """Drive ``passes`` (default: all shipped passes) over
    ``artifacts`` and return a :class:`Report`.

    ``budgets`` is the parsed budget file (``benchmarks/budgets.json``
    layout); its ``suppressions`` list, the ``MXNET_ANALYSIS_SUPPRESS``
    env var, and the ``suppressions`` argument all apply.  ``snapshot``
    is a parsed drift snapshot (``mxlint --check``) handed to the drift
    pass through the context.

    A budget-file suppression that matches NO finding of the run emits
    a ``stale-suppression`` info row (pass name ``suppressions``): the
    waived issue stopped firing, so the waiver is dead weight that
    would silently swallow the next regression of the same shape.
    Env/argument suppressions are session-local and exempt.
    """
    from .. import config as _config

    if passes is None:
        passes = default_passes()
    budgets = budgets or {}
    budget_triples = _parse_suppressions(budgets.get("suppressions"))
    triples = list(budget_triples)
    triples += _parse_suppressions(_config.get("MXNET_ANALYSIS_SUPPRESS"))
    triples += _parse_suppressions(suppressions)

    context = AnalysisContext(budgets=budgets, snapshot=snapshot)
    findings = []
    for artifact in artifacts:
        for p in passes:
            missing = [s for s in p.requires
                       if getattr(artifact, _SURFACE_ATTR[s], None) is None]
            if missing:
                findings.append(p.finding(
                    artifact, "info",
                    "skipped: artifact lacks %s text" % "/".join(missing),
                    code="missing-surface", missing=missing))
                continue
            findings.extend(p.run(artifact, context))
    for f in findings:
        f.suppressed = _is_suppressed(f, triples)
    for triple in budget_triples:
        if any(_is_suppressed(f, [triple]) for f in findings):
            continue
        stale = Finding(
            pass_name="suppressions", program="*", severity="info",
            message="budget-file suppression %r matched no finding this "
            "run — the waived issue stopped firing; remove it from the "
            "budget file's suppressions list" % ":".join(triple),
            code="stale-suppression", detail={"pattern": list(triple)})
        stale.suppressed = _is_suppressed(stale, triples)
        findings.append(stale)
    return Report(findings, programs=[a.name for a in artifacts],
                  passes=[p.name for p in passes])
