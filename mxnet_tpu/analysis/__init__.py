"""Static program analysis over jaxprs, lowered StableHLO and compiled HLO.

The framework's performance headlines are *program-level invariants* —
fewer collective bytes than the GSPMD baseline, donated buffers aliased in
place, zero host syncs per step, O(1)-in-prefix decode FLOPs, one trace
per program shape.  This package turns each into a static audit that runs
on the 8-virtual-device CPU mesh, so a sharding-rule edit or a jit
cache-key drift fails CI instead of waiting for the TPU rig:

* :mod:`~mxnet_tpu.analysis.hlo_parse` — the text parsing layer (grown
  out of ``parallel/hlo_stats.py``, which re-exports it);
* :mod:`~mxnet_tpu.analysis.artifact` — :class:`ProgramArtifact`, one
  canonical program's jaxpr/StableHLO/HLO surfaces + metadata;
* :mod:`~mxnet_tpu.analysis.framework` — :class:`Pass`,
  :class:`Finding`, suppression matching and :func:`run_passes`;
* :mod:`~mxnet_tpu.analysis.passes` — the shipped passes (donation,
  collective budget, retrace, host sync, FLOP/dtype, cache bytes, tuner
  coverage, sharding coverage, drift) plus the drift-snapshot
  record/hash helpers;
* :mod:`~mxnet_tpu.analysis.schedule` — the compiled-HLO schedule model
  (async start/done pairing + compute shadows) and the schedule pass;
* :mod:`~mxnet_tpu.analysis.retrace` — :class:`RetraceAuditor` for
  instrumenting arbitrary jitted functions;
* :mod:`~mxnet_tpu.analysis.programs` — builders for the five canonical
  programs ``tools/mxlint.py`` audits.

Entry point: ``tools/mxlint.py`` (CLI, bench JSON contract, ``--smoke``
tier-1 hook); library use::

    from mxnet_tpu import analysis
    report = analysis.run_passes([module.program_artifacts()["train_step"]],
                                 budgets=analysis.load_budgets())
    assert report.ok(), report.format_text()
"""
from __future__ import annotations

import json
import os

from .artifact import ProgramArtifact, artifact_from_jit
from .cost import artifact_cost, aval_bytes, program_cost
from .framework import (Finding, Pass, Report, SEVERITIES, default_passes,
                        run_passes)
from .passes import (CacheBytesPass, CollectiveBudgetPass, DonationPass,
                     DriftPass, FlopDtypePass, HostSyncPass, RetracePass,
                     ShardingCoveragePass, TunerCoveragePass,
                     record_snapshot, snapshot_hash)
from .retrace import RetraceAuditor, arg_signature, signature_diff
from .schedule import ScheduleModel, SchedulePass, parse_schedule

__all__ = [
    "CacheBytesPass", "CollectiveBudgetPass", "DonationPass", "DriftPass",
    "Finding", "FlopDtypePass", "HostSyncPass", "Pass", "ProgramArtifact",
    "Report", "RetraceAuditor", "RetracePass", "SEVERITIES",
    "ScheduleModel", "SchedulePass", "ShardingCoveragePass",
    "TunerCoveragePass", "arg_signature", "artifact_cost",
    "artifact_from_jit", "aval_bytes", "default_passes", "load_budgets",
    "load_snapshot", "parse_schedule", "program_cost", "record_snapshot",
    "resolve_budgets_path", "run_passes", "signature_diff",
    "snapshot_hash",
]

_DEFAULT_BUDGETS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "benchmarks", "budgets.json")


def resolve_budgets_path(path=None):
    """The budget file location: explicit ``path`` argument, the
    ``MXNET_ANALYSIS_BUDGETS`` env knob, the repo default — the ONE
    resolution rule, shared by :func:`load_budgets` and
    ``tools/mxlint.py --update-budgets`` so reads and writes cannot
    diverge."""
    from .. import config as _config

    return path or _config.get("MXNET_ANALYSIS_BUDGETS") or _DEFAULT_BUDGETS


def load_budgets(path=None):
    """Parse the committed budget file (``benchmarks/budgets.json``).

    Resolved via :func:`resolve_budgets_path`.  A missing file returns
    ``{}`` — the budget pass then reports per-program "no committed
    budget" findings rather than crashing.
    """
    path = resolve_budgets_path(path)
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        return json.load(f)


def load_snapshot(path):
    """Parse a drift snapshot (``mxlint --record`` output) and verify
    its content hash.

    A mismatch raises ``ValueError``: the baseline was hand-edited, and
    a gate whose baseline can be quietly nudged is no gate — intentional
    changes re-record through the tool.
    """
    from .passes import snapshot_hash

    with open(path) as f:
        snap = json.load(f)
    want = snap.get("content_hash")
    have = snapshot_hash(snap)
    if want != have:
        raise ValueError(
            "drift snapshot %s content hash mismatch (recorded %s, "
            "computed %s) — the file was edited by hand; re-record it "
            "with tools/mxlint.py --record" % (path, want, have))
    return snap
