"""Builders for the canonical programs the lint audits.

``tools/mxlint.py`` (and the tier-1 smoke) checks thirteen programs —
the compiled surfaces behind every headline number so far:

* ``train_step``  — the fused forward+backward+optimizer program
  (bfloat16 compute, donated params/slots/aux);
* ``eval_step``   — the forward+device-metric-accumulate program
  ``score()`` arms (donated accumulator state);
* ``prefill``     — the KV-cache prefill program;
* ``decode_step`` — the donated one-token decode program;
* ``decode_step_q`` — the same decode step over int8-quantized KV caches
  (per-head scale planes; the cache-bytes pass checks the data planes
  really are narrow);
* ``draft_step``  — the DRAFT model's donated decode step inside the
  speculative serving loop (a second, smaller DecodePredictor);
* ``verify_step`` — the speculative verify program: k+1 positions scored
  in one pass against the quantized caches, acceptance-rejection inside;
* ``paged_decode_step`` / ``paged_verify_step`` — the same decode and
  verify programs over SHARED page pools: per-slot page tables and
  active masks ride in as data (zero retraces across admissions, COW
  forks and retirements), appends scatter through the tables, and
  attention runs through the FUSED Pallas flash-decoding kernel
  (``MXNET_PALLAS_DECODE`` armed for the drive; interpret mode off-TPU)
  — the flop-dtype pass's ``pallas-fallback`` tripwire proves the
  kernel lowered instead of the three-pass einsum fallback; their
  cache-bytes meta is the POOL total (the paged serving HBM bill the
  cache-bytes pass budgets);
* ``gqa_decode_step`` — the paged decode program under a grouped-query
  layout (num_kv_heads < num_heads): pools allocate H_kv head slices,
  and the cache-bytes pass's ``mha-under-gqa`` tripwire proves the G×
  pool shrink actually happened;
* ``ring_tp_step`` — the attention-LM fused step on the composed
  (data, seq, model) mesh: ring attention with head groups sharded on
  'model' (needs >= 4 devices; the smoke forces the 8-virtual-device
  CPU platform, same trick as tests/conftest.py);
* ``moe_train_step`` — the MoE attention-LM fused step on the composed
  (data, expert, model) mesh: top-2 capacity-slot routing dispatched
  through the explicit all-to-all ``shard_map`` program
  (``ops/moe.py``), expert stacks sharded on 'expert', the FFN hidden
  dim Megatron-split on 'model' — the collective-budget pass pins the
  dispatch/combine all-to-all count and bytes (forward AND the
  custom-VJP backward's reversed exchanges) so a sharding regression
  that silently degrades the exchange to all-gathers of the full slot
  table fails CI (needs >= 4 devices, like ``ring_tp_step``);
* ``ckpt_train_step`` — the fused step of a ``fit()`` run UNDER async
  fenced checkpointing (``mxnet_tpu.elastic``): fences snapshot the
  donated chain and a writer thread lands committed orbax steps while
  the loop keeps dispatching, and the host-sync pass then proves the
  checkpoint machinery added no callback primitives or host-transfer
  ops to the compiled program — the fence d2h lives on the writer
  thread, OUTSIDE the program (the sanctioned-transfer story in
  docs/static_analysis.md).

Every program is driven at least twice at identical shapes before its
artifact is snapshotted, so the retrace pass checks a real "second call
hit the jit cache" fact, not a vacuous first-trace count.  The three
speculative/quantized programs are driven by an actual MIXED-LENGTH
:class:`~mxnet_tpu.decode.DecodeServer` run (draft-model proposer,
prompts of different lengths, slot reuse), so their one-trace-each
retrace audit covers the real serving schedule, not a synthetic drive.
The two paged programs are likewise driven by a real SHARED-PREFIX paged
serve — chunked prefill, prefix-cache hits, copy-on-write forks and
immediate retirement all exercised before the trace counters snapshot.
Dims are tiny: the point is the *program structure* (collectives,
aliasing, callbacks, dot dtypes, cache bytes), which does not depend on
size.

The same artifacts feed the three history/placement passes: the meshed
programs (``ring_tp_step``, ``moe_train_step``) stamp per-leaf
``sharding_coverage`` meta at placement time for the sharding-coverage
audit, the drift gate (``mxlint --record/--check``) snapshots every
program's priced quantities against ``benchmarks/mxlint_snapshot.json``,
and the schedule pass reads each compiled text — a ``sync-backend`` info
on this CPU harness, with the async-overlap contract pinned on the
canned TPU corpus under ``tests/data/hlo/``.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..programs import registry as _registry

__all__ = ["CANONICAL_PROGRAMS", "build_canonical_artifacts"]

# tiny-but-structured dims shared by every builder
_MLP = dict(batch=8, features=32, hidden=32, classes=8)
_LM = dict(vocab=32, seq_len=16, embed=16, heads=4, ffn=32, layers=1,
           batch=2)
# the draft model: same vocabulary, narrower/shallower stack
_DRAFT = dict(embed=8, heads=2, ffn=16, layers=1)
_SPEC_K = 3


def _mlp_module(compute_dtype="bfloat16"):
    """A classifier Module with the fused train step armed (bfloat16
    compute so the dtype lint audits a mixed-precision program)."""
    import mxnet_tpu as mx
    from mxnet_tpu import ndarray as nd
    from mxnet_tpu.io import DataBatch

    d = _MLP
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=d["hidden"], name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=d["classes"], name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu(), compute_dtype=compute_dtype)
    mod.bind(data_shapes=[("data", (d["batch"], d["features"]))],
             label_shapes=[("softmax_label", (d["batch"],))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1,
                                         "momentum": 0.9})
    rng = np.random.RandomState(0)
    x = nd.array(rng.uniform(-1, 1, (d["batch"], d["features"]))
                 .astype(np.float32))
    y = nd.array(rng.randint(0, d["classes"], (d["batch"],))
                 .astype(np.float32))
    return mod, DataBatch([x], [y])


def _lm_symbol(**moe_kwargs):
    from mxnet_tpu.models import attention_lm

    d = _LM
    return attention_lm.get_symbol(
        vocab_size=d["vocab"], seq_len=d["seq_len"],
        num_layers=d["layers"], embed=d["embed"], heads=d["heads"],
        ffn_hidden=d["ffn"], **moe_kwargs)


def _lm_mesh_module(mesh_cfg, symbol=None):
    """The attention LM bound on a mesh — the ring×TP composition's
    training program (or, with a MoE ``symbol``, the expert-parallel
    one)."""
    import mxnet_tpu as mx
    from mxnet_tpu import ndarray as nd
    from mxnet_tpu.io import DataBatch, DataDesc

    import jax

    d = _LM
    contexts = [mx.cpu(i) for i in range(len(jax.devices()))]
    mod = mx.mod.Module(symbol if symbol is not None else _lm_symbol(),
                        context=contexts, mesh_config=mesh_cfg)
    data_desc = DataDesc("data", (d["batch"], d["seq_len"]), layout="NT")
    label_desc = DataDesc("softmax_label", (d["batch"], d["seq_len"]),
                          layout="NT")
    mod.bind(data_shapes=[data_desc], label_shapes=[label_desc])
    mod.init_params(mx.initializer.Xavier(rnd_type="gaussian"))
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.01,
                                         "momentum": 0.9})
    rng = np.random.RandomState(0)
    x = rng.randint(0, d["vocab"], size=(d["batch"], d["seq_len"])) \
        .astype(np.float32)
    y = np.concatenate([x[:, 1:], np.zeros((d["batch"], 1), np.float32)],
                       axis=1)
    batch = DataBatch([nd.array(x)], [nd.array(y)],
                      provide_data=[data_desc],
                      provide_label=[label_desc])
    return mod, batch


def _drive_fused(mod, batch, steps=2):
    """Run the fused step twice at one shape (retrace ground truth)."""
    for _ in range(steps):
        mod.forward_backward(batch)
        mod.update()
    if mod._fused_step is None:
        raise MXNetError("fused train step did not arm; cannot build its "
                         "artifact (check MXNET_FUSED_TRAIN_STEP)")
    return mod._fused_step


def _eval_artifact(mod, batch):
    from mxnet_tpu import metric as metric_mod
    from mxnet_tpu.train_step import CompiledEvalStep

    m = metric_mod.create("acc")
    step = CompiledEvalStep(mod._exec_group, m)
    try:
        step.run(batch)
        step.run(batch)
        return step.artifact(name="eval_step")
    finally:
        step.finish()


def _lm_params(sym, batch, seq_len, seed=0, scale=0.02):
    rng = np.random.RandomState(seed)
    arg_shapes, _, aux_shapes = sym.infer_shape(
        data=(batch, seq_len), softmax_label=(batch, seq_len))
    params = {}
    for name, shape in zip(sym.list_arguments(), arg_shapes):
        if name in ("data", "softmax_label"):
            continue
        params[name] = rng.normal(0, scale, shape).astype(np.float32)
    for name, shape in zip(sym.list_auxiliary_states(), aux_shapes):
        params["aux:" + name] = np.zeros(shape, np.float32)
    return params


def _decode_artifacts():
    from mxnet_tpu.decode import DecodePredictor

    import jax

    d = _LM
    rng = np.random.RandomState(0)
    sym = _lm_symbol()
    params = _lm_params(sym, d["batch"], d["seq_len"])
    pred = DecodePredictor(sym, params, cache_len=d["seq_len"],
                           temperature=0.0, kv_dtype="")
    prompt_len = d["seq_len"] // 2
    prompts = rng.randint(0, d["vocab"],
                          size=(d["batch"], d["seq_len"])) \
        .astype(np.float32)
    prompts[:, prompt_len:] = 0.0
    key = jax.random.PRNGKey(0)
    state, _ = pred.prefill(prompts, prompt_len, key)
    state, _ = pred.prefill(prompts, prompt_len, key)
    state, _ = pred.step(state, key)
    state, _ = pred.step(state, key)
    return (pred.prefill_artifact(d["batch"], d["seq_len"]),
            pred.decode_artifact(state))


def _speculative_artifacts():
    """decode_step_q / draft_step / verify_step, driven by a real
    mixed-length speculative serve.

    An int8-quantized target and a smaller draft model run a
    :class:`~mxnet_tpu.decode.DecodeServer` queue of different-length
    prompts (slot reuse included) — the mixed-length serve run the
    retrace acceptance criterion names; each program's trace counter must
    then read exactly one when its artifact snapshots.
    """
    from mxnet_tpu.decode import DecodePredictor, DecodeServer, DraftProposer
    from mxnet_tpu.models import attention_lm

    import jax

    d = _LM
    dd = _DRAFT
    rng = np.random.RandomState(1)
    target = DecodePredictor(_lm_symbol(), _lm_params(
        _lm_symbol(), d["batch"], d["seq_len"]), cache_len=d["seq_len"],
        temperature=0.0, kv_dtype="int8")
    draft_sym = attention_lm.get_symbol(
        vocab_size=d["vocab"], seq_len=d["seq_len"],
        num_layers=dd["layers"], embed=dd["embed"], heads=dd["heads"],
        ffn_hidden=dd["ffn"])
    draft = DecodePredictor(
        draft_sym, _lm_params(draft_sym, d["batch"], d["seq_len"], seed=2),
        cache_len=d["seq_len"], temperature=0.0, kv_dtype="")
    proposer = DraftProposer(draft, _SPEC_K)
    server = DecodeServer(target, max_prefill=d["seq_len"] // 2,
                          slots=d["batch"], max_new_tokens=4,
                          proposer=proposer)
    for n in (3, 5, 7, 4):          # mixed-length trace, 2x slot reuse
        server.submit(rng.randint(0, d["vocab"], size=(n,)))
    results = server.run()
    if len(results) != 4 or server.spec_steps == 0:
        raise MXNetError("speculative serve drive did not exercise the "
                         "verify program (results=%d, spec_steps=%d)"
                         % (len(results), server.spec_steps))

    # the plain quantized decode step is the serve loop's near-wrap
    # fallback; drive it twice at the serve batch shape for its artifact
    key = jax.random.PRNGKey(0)
    prompts = rng.randint(0, d["vocab"],
                          size=(d["batch"], d["seq_len"] // 2)) \
        .astype(np.float32)
    state, _ = target.prefill(prompts, d["seq_len"] // 2, key)
    state, _ = target.step(state, key)
    state, _ = target.step(state, key)
    return (target.decode_artifact(state, name="decode_step_q"),
            proposer.predictor.decode_artifact(proposer._state,
                                               name="draft_step"),
            target.verify_artifact(state, _SPEC_K, name="verify_step"))


def _paged_artifacts():
    """paged_decode_step / paged_verify_step, driven by a real
    shared-prefix paged serve WITH THE FUSED KERNEL ON.

    Four requests sharing a 6-token prefix drain through a
    :class:`~mxnet_tpu.decode.DecodeServer` over a paged predictor
    (chunked prefill, n-gram speculation): chunk admissions, prefix-cache
    hits, a COW-relevant partial-page publish, speculative verify over
    page tables and immediate retirement all run before the artifacts
    snapshot — each program's trace counter must then read exactly one.

    The drive arms ``MXNET_PALLAS_DECODE`` (interpret mode off-TPU), so
    the canonical paged programs are audited as they SERVE: decode/verify
    attention through the fused flash-decoding kernel
    (``ops/pallas_decode.py``), with the flop-dtype pass's
    ``pallas-fallback`` tripwire proving the kernel actually lowered —
    a dispatch regression that silently fell back to the einsum path is
    a red lint run, not a quiet 3x decode-bandwidth loss.
    """
    from mxnet_tpu import config as _config
    from mxnet_tpu.decode import DecodePredictor, DecodeServer

    import jax

    knobs = {"MXNET_PALLAS_DECODE": "1"}
    if jax.default_backend() != "tpu":
        knobs["MXNET_PALLAS_INTERPRET"] = "1"
    with _config.overrides(**knobs):
        d = _LM
        rng = np.random.RandomState(3)
        pred = DecodePredictor(
            _lm_symbol(), _lm_params(_lm_symbol(), d["batch"],
                                     d["seq_len"]),
            cache_len=d["seq_len"], temperature=0.0, kv_dtype="",
            paged=True, page_tokens=4, prefill_chunk=4)
        server = DecodeServer(pred, max_prefill=12, slots=d["batch"],
                              max_new_tokens=3, spec_k=_SPEC_K)
        prefix = rng.randint(0, d["vocab"], size=(6,))
        for n in (3, 5, 2, 4):          # shared prefix, mixed tails
            server.submit(np.concatenate(
                [prefix, rng.randint(0, d["vocab"], size=(n,))]))
        results = server.run()
        stats = server.stats()
        if len(results) != 4 or server.spec_steps == 0 \
                or stats.get("prefix_cache_hit_rate", 0) <= 0:
            raise MXNetError(
                "paged serve drive did not exercise the paged programs "
                "(results=%d, spec_steps=%d, hit_rate=%s)"
                % (len(results), server.spec_steps,
                   stats.get("prefix_cache_hit_rate")))
        # a fresh batch state at the same sizing lowers the SAME traces
        state = pred.paged_batch_state(d["batch"])
        return (pred.decode_artifact(state, name="paged_decode_step"),
                pred.verify_artifact(state, _SPEC_K,
                                     name="paged_verify_step"))


def _gqa_artifacts():
    """gqa_decode_step: the paged decode program under a GROUPED-QUERY
    layout (num_kv_heads < num_heads), driven by a real grouped paged
    serve with the fused kernel armed.

    The grouped config (G = heads/kv_heads = 4 here) allocates pools
    H_kv heads wide — the cache-bytes meta carries the grouped promise
    (``num_kv_heads``/``attn_dims``/``cache_kv_dims``), so the
    cache-bytes pass's ``mha-under-gqa`` tripwire proves the pool really
    shrank by G and a dropped num_kv_heads is a red lint run."""
    from mxnet_tpu import config as _config
    from mxnet_tpu.decode import DecodePredictor, DecodeServer
    from mxnet_tpu.models import attention_lm

    import jax

    knobs = {"MXNET_PALLAS_DECODE": "1"}
    if jax.default_backend() != "tpu":
        knobs["MXNET_PALLAS_INTERPRET"] = "1"
    with _config.overrides(**knobs):
        d = _LM
        rng = np.random.RandomState(5)
        sym = attention_lm.get_symbol(
            vocab_size=d["vocab"], seq_len=d["seq_len"],
            num_layers=d["layers"], embed=d["embed"], heads=d["heads"],
            ffn_hidden=d["ffn"], num_kv_heads=1)
        pred = DecodePredictor(
            sym, _lm_params(sym, d["batch"], d["seq_len"]),
            cache_len=d["seq_len"], temperature=0.0, kv_dtype="",
            paged=True, page_tokens=4, prefill_chunk=4)
        server = DecodeServer(pred, max_prefill=12, slots=d["batch"],
                              max_new_tokens=3)
        prefix = rng.randint(0, d["vocab"], size=(6,))
        for n in (3, 5, 2, 4):          # shared prefix, mixed tails
            server.submit(np.concatenate(
                [prefix, rng.randint(0, d["vocab"], size=(n,))]))
        results = server.run()
        if len(results) != 4:
            raise MXNetError(
                "grouped paged serve drive did not complete "
                "(results=%d)" % (len(results),))
        state = pred.paged_batch_state(d["batch"])
        art = pred.decode_artifact(state, name="gqa_decode_step")
        if not art.meta.get("num_kv_heads"):
            raise MXNetError(
                "gqa_decode_step artifact carries no grouped-K/V meta; "
                "the mha-under-gqa tripwire would be vacuous")
        return (art,)


def _ckpt_train_step_artifact():
    """The fused step of a real ``fit()`` under async fenced
    checkpointing.

    A small MLP fit runs with an :class:`~mxnet_tpu.elastic.Checkpointer`
    armed (period 3, async writer): fence snapshots dispatch device
    copies and a background thread commits orbax step directories while
    the loop keeps stepping.  The artifact snapshots AFTER at least one
    commit, so the host-sync pass audits a program that demonstrably
    coexisted with live checkpointing — any callback primitive or
    host-transfer op the checkpoint path leaked into the step would land
    here as an error."""
    import shutil
    import tempfile

    import mxnet_tpu as mx
    from mxnet_tpu import elastic
    from mxnet_tpu.io import NDArrayIter

    d = _MLP
    rng = np.random.RandomState(4)
    X = rng.uniform(-1, 1, (d["batch"] * 6, d["features"])) \
        .astype(np.float32)
    y = rng.randint(0, d["classes"], (d["batch"] * 6,)).astype(np.float32)
    it = NDArrayIter(X, y, batch_size=d["batch"])

    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=d["hidden"], name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=d["classes"], name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu(), compute_dtype="bfloat16")

    tmp = tempfile.mkdtemp(prefix="mxlint_ckpt_")
    try:
        ctl = elastic.ElasticController(checkpointer=elastic.Checkpointer(
            tmp, period=3, async_write=True))
        mod.fit(it, num_epoch=2, eval_metric="acc", optimizer="sgd",
                optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
                initializer=mx.initializer.Xavier(), elastic=ctl)
        if ctl.checkpointer.writes < 1:
            raise MXNetError("fit-under-checkpoint drive committed no "
                             "fence checkpoint; the ckpt_train_step "
                             "artifact would not cover live checkpointing")
        if mod._fused_step is None:
            raise MXNetError("fused train step did not arm under "
                             "checkpointing")
        return mod._fused_step.artifact(name="ckpt_train_step")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _ring_mesh_config(n_dev):
    from mxnet_tpu.parallel import MeshConfig

    if n_dev >= 8:
        return MeshConfig(data=2, seq=2, model=2)
    if n_dev >= 4:
        return MeshConfig(data=1, seq=2, model=2)
    return None


def _moe_mesh_config(n_dev):
    from mxnet_tpu.parallel import MeshConfig

    if n_dev >= 8:
        return MeshConfig(data=2, expert=2, model=2)
    if n_dev >= 4:
        return MeshConfig(data=1, expert=2, model=2)
    return None


def _moe_train_step_artifact():
    """The expert-parallel MoE LM fused step on the composed
    (data, expert, model) mesh.

    A 4-expert top-2 capacity-routed attention LM trains two steps at
    one shape; the explicit all-to-all dispatch (``ops/moe.py``
    shard_map path) must actually have been taken — a silent fallback
    to the GSPMD-hint path would let the collective budget drift
    meaninglessly — so the MOE_PATH tripwire is checked before the
    artifact snapshots."""
    from mxnet_tpu.ops.moe import MOE_DISPATCH, MOE_PATH

    import jax

    cfg = _moe_mesh_config(len(jax.devices()))
    sym = _lm_symbol(moe_experts=4, moe_capacity_factor=1.25, moe_top_k=2)
    mod, batch = _lm_mesh_module(cfg, symbol=sym)
    step = _drive_fused(mod, batch)
    if MOE_PATH["last"] != "sparse_a2a":
        raise MXNetError(
            "MoE fused step did not take the explicit all-to-all "
            "dispatch (MOE_PATH=%r); the moe_train_step budget would "
            "not cover the exchange" % (MOE_PATH["last"],))
    if MOE_DISPATCH["last"] != "sort":
        raise MXNetError(
            "MoE capacity dispatch did not take the default sort-based "
            "algorithm (MOE_DISPATCH=%r); the moe_train_step budget "
            "would price the wrong pack" % (MOE_DISPATCH["last"],))
    return step.artifact(name="moe_train_step")


# ---------------------------------------------------------------------------
# registry registrations — this module IS the canonical catalog now:
# each builder group registers once with mxnet_tpu.programs.registry,
# mxlint enumerates registry.canonical_names(), and adding the 13th
# canonical program is one register_canonical call
# ---------------------------------------------------------------------------
def _train_eval_builder(want):
    # the canonical train_step is audited WITH the fused multi-tensor
    # Pallas optimizer update armed (interpret off-TPU), so the
    # flop-dtype pass's pallas-fallback tripwire proves the kernel
    # lowered — the same arming story as the paged decode programs
    from .. import config as _config

    import jax as _jax

    knobs = {"MXNET_PALLAS_UPDATE": "1"}
    if _jax.default_backend() != "tpu":
        knobs["MXNET_PALLAS_INTERPRET"] = "1"
    out = []
    with _config.overrides(**knobs):
        mod, batch = _mlp_module()
        if "train_step" in want:
            # the eval program needs only the bound group; driving (and
            # compiling) the fused step is the train artifact's cost
            step = _drive_fused(mod, batch)
            if step._plan is None:
                raise MXNetError(
                    "MXNET_PALLAS_UPDATE armed but the canonical "
                    "MLP step built no update plan (SGD-momentum "
                    "f32 masters must be in scope)")
            out.append(("train_step", step.artifact(name="train_step")))
        if "eval_step" in want:
            out.append(("eval_step", _eval_artifact(mod, batch)))
    return out


def _decode_builder(want):
    prefill, decode = _decode_artifacts()
    return [("prefill", prefill), ("decode_step", decode)]


def _speculative_builder(want):
    decode_q, draft, verify = _speculative_artifacts()
    return [("decode_step_q", decode_q), ("draft_step", draft),
            ("verify_step", verify)]


def _paged_builder(want):
    paged_decode, paged_verify = _paged_artifacts()
    return [("paged_decode_step", paged_decode),
            ("paged_verify_step", paged_verify)]


def _mesh_note(kind):
    import jax

    return ("needs >= 4 devices for a %s mesh; %d present — run under "
            "the 8-virtual-device CPU platform (tools/mxlint.py --smoke "
            "does this)" % (kind, len(jax.devices())))


def _ring_available():
    import jax

    return None if _ring_mesh_config(len(jax.devices())) is not None \
        else _mesh_note("(seq, model)")


def _ring_builder(want):
    import jax

    mod, batch = _lm_mesh_module(_ring_mesh_config(len(jax.devices())))
    step = _drive_fused(mod, batch)
    return [("ring_tp_step", step.artifact(name="ring_tp_step"))]


def _moe_available():
    import jax

    return None if _moe_mesh_config(len(jax.devices())) is not None \
        else _mesh_note("(expert, model)")


def _moe_builder(want):
    return [("moe_train_step", _moe_train_step_artifact())]


def _gqa_builder(want):
    (art,) = _gqa_artifacts()
    return [("gqa_decode_step", art)]


def _ckpt_builder(want):
    return [("ckpt_train_step", _ckpt_train_step_artifact())]


if "train_step" not in _registry.canonical_names():
    # registered once per process (module reloads must not re-register)
    _registry.register_canonical(("train_step", "eval_step"),
                                 _train_eval_builder)
    _registry.register_canonical(("prefill", "decode_step"),
                                 _decode_builder)
    _registry.register_canonical(
        ("decode_step_q", "draft_step", "verify_step"),
        _speculative_builder)
    _registry.register_canonical(
        ("paged_decode_step", "paged_verify_step"), _paged_builder)
    _registry.register_canonical(("gqa_decode_step",), _gqa_builder)
    _registry.register_canonical(("ring_tp_step",), _ring_builder,
                                 availability=_ring_available)
    _registry.register_canonical(("moe_train_step",), _moe_builder,
                                 availability=_moe_available)
    _registry.register_canonical(("ckpt_train_step",), _ckpt_builder)

# the catalog, enumerated from the registry (kept as a module constant
# for existing importers)
CANONICAL_PROGRAMS = _registry.canonical_names()


def build_canonical_artifacts(names=None):
    """Build the requested canonical artifacts (default: all thirteen) —
    a registry enumeration now (``programs.registry.build_canonical``).

    Returns ``(artifacts, notes)`` — ``notes`` maps a program that could
    not be built on this host (e.g. ``ring_tp_step`` without >= 4
    devices) to the reason, so the caller can surface the gap instead of
    silently auditing a smaller set.
    """
    return _registry.build_canonical(names)
