"""Static parsing of lowered StableHLO and compiled HLO text.

The parsing layer of the analysis pass framework — grown out of
``parallel/hlo_stats.py`` (which now re-exports from here): under XLA the
collectives, dots and buffer-donation aliases are explicit in the program
text, so every performance invariant the framework establishes (collective
budgets, O(1)-in-prefix decode FLOPs, donation round-trips) is *statically*
checkable from ``jit(...).lower(...)`` output, no accelerator required.

Three families of entry points:

* byte accounting — :func:`shape_bytes` / :func:`shape_bytes_report` /
  :func:`collective_stats`;
* FLOP accounting — :func:`dot_flops` / :func:`dot_flops_report` (the
  report carries ``uncounted_ops`` so dot-like ops the counter cannot
  parse are a signal, not a silent zero);
* program metadata — :func:`input_output_aliases` (compiled-HLO donation
  aliasing).
"""
from __future__ import annotations

import re

__all__ = [
    "collective_stats",
    "dot_flops",
    "dot_flops_report",
    "input_output_aliases",
    "shape_bytes",
    "shape_bytes_report",
    "shape_str",
    "stablehlo_collective_stats",
    "stablehlo_gather_stats",
    "stablehlo_sort_scatter_stats",
]

# Bit widths per HLO/StableHLO element type.  Sub-byte types (s4/u4, the
# fp4/fp8 menagerie) are sized in bits and rounded up per-shape, matching
# XLA's packed layouts closely enough for budget accounting.
_DTYPE_BITS = {
    "f64": 64, "f32": 32, "f16": 16, "bf16": 16,
    "f8e4m3": 8, "f8e4m3fn": 8, "f8e4m3fnuz": 8, "f8e4m3b11fnuz": 8,
    "f8e5m2": 8, "f8e5m2fnuz": 8, "f8e3m4": 8, "f8e8m0fnu": 8,
    "f4e2m1fn": 4,
    "s64": 64, "u64": 64, "s32": 32, "u32": 32, "s16": 16, "u16": 16,
    "s8": 8, "u8": 8, "s4": 4, "u4": 4, "s2": 2, "u2": 2,
    "pred": 8, "c64": 64, "c128": 128,
    # StableHLO spells integers signless (i8, not s8) and bools i1 —
    # the lowered-dialect byte accounting (collective payloads, gather
    # intermediates) reads these; compiled HLO never produces them.
    # i1 is stored one byte per element, like pred.
    "i64": 64, "i32": 32, "i16": 16, "i8": 8, "i4": 4, "i2": 2, "i1": 8,
    "ui64": 64, "ui32": 32, "ui16": 16, "ui8": 8, "ui4": 4, "ui2": 2,
}

# dtype-shaped names only — 'pred', 'bf16', or letter-digit-led tokens
# like f32/s4/u8/c64/f8e4m3fn — so identifier[index] strings in HLO
# metadata (op_name="params[0]", arg names) never read as shapes
_SHAPE_RE = re.compile(r"\b(pred|bf16|[fsuc][0-9][0-9a-z]*)\[([0-9,]*)\]")

# an instruction line: '%name = SHAPE op(...)'.  SHAPE is extracted with a
# balanced-paren scan, not a depth-limited regex: tuple shapes nest (grouped
# async collectives carry tuples of buffers) and TPU layout annotations like
# {1,0:T(8,128)} add parens at arbitrary depth inside them.
_INSTR_RE = re.compile(r"=\s*")
_OP_RE = re.compile(
    r"\s*(all-reduce|all-gather|reduce-scatter|all-to-all|collective-permute)"
    r"(-start|-done)?\(")

def _scan_shape(line, start):
    """Return (shape_str, end_index) for the shape beginning at `start` —
    either a balanced parenthesized tuple or a single whitespace-free
    token."""
    if start < len(line) and line[start] == "(":
        depth = 0
        for i in range(start, len(line)):
            if line[i] == "(":
                depth += 1
            elif line[i] == ")":
                depth -= 1
                if depth == 0:
                    return line[start:i + 1], i + 1
        return line[start:], len(line)
    m = re.match(r"\S+", line[start:])
    if m is None:
        return "", start
    return m.group(0), start + m.end()


def shape_bytes_report(shape_str):
    """(total_bytes, unknown_dtypes) over every 'dtype[dims]' shape in the
    string (tuples ok).  Element types missing from the width table land in
    ``unknown_dtypes`` (sorted, deduped) instead of silently contributing
    zero — the analysis FLOP/byte passes turn a non-empty list into a
    recorded finding."""
    total = 0
    unknown = set()
    for dtype, dims in _SHAPE_RE.findall(shape_str):
        bits = _DTYPE_BITS.get(dtype)
        if bits is None:
            unknown.add(dtype)
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += (n * bits + 7) // 8
    return total, sorted(unknown)


def shape_bytes(shape_str):
    """Total bytes of every 'dtype[dims]' shape in the string (tuples ok).
    Unknown dtypes contribute zero here — use :func:`shape_bytes_report`
    when the caller needs them surfaced."""
    return shape_bytes_report(shape_str)[0]


# numpy/ml_dtypes names -> HLO element-type codes, the inverse direction of
# _SHAPE_RE: renders python-side array metadata into the same 'dtype[dims]'
# strings shape_bytes sizes, so static byte budgets (the decode cache-bytes
# pass) share one width table with the program-text parsers.
_NP_TO_HLO = {
    "float64": "f64", "float32": "f32", "float16": "f16",
    "bfloat16": "bf16", "bool": "pred",
    "int64": "s64", "int32": "s32", "int16": "s16", "int8": "s8",
    "uint64": "u64", "uint32": "u32", "uint16": "u16", "uint8": "u8",
    "int4": "s4", "uint4": "u4", "int2": "s2", "uint2": "u2",
    "float8_e4m3": "f8e4m3", "float8_e4m3fn": "f8e4m3fn",
    "float8_e4m3fnuz": "f8e4m3fnuz", "float8_e4m3b11fnuz": "f8e4m3b11fnuz",
    "float8_e5m2": "f8e5m2", "float8_e5m2fnuz": "f8e5m2fnuz",
    "float8_e3m4": "f8e3m4", "float8_e8m0fnu": "f8e8m0fnu",
    "float4_e2m1fn": "f4e2m1fn",
    "complex64": "c64", "complex128": "c128",
}


def shape_str(shape, dtype):
    """Render ``(shape, dtype)`` as the HLO ``'dtype[dims]'`` string the
    byte accountants parse — e.g. ``shape_str((2, 16, 8), jnp.int8)`` ->
    ``'s8[2,16,8]'``.  Unknown dtypes raise (a silent zero would defeat
    the budget)."""
    import numpy as _np

    name = _np.dtype(dtype).name
    code = _NP_TO_HLO.get(name)
    if code is None:
        raise KeyError("no HLO element-type code for dtype %r" % name)
    return "%s[%s]" % (code, ",".join(str(int(d)) for d in shape))


def _split_top_level(tuple_str):
    """Split '(a, (b, c), d)' into top-level elements ['a', '(b, c)', 'd']."""
    s = tuple_str.strip()
    if not (s.startswith("(") and s.endswith(")")):
        return [s]
    s = s[1:-1]
    parts, depth, start = [], 0, 0
    for i, ch in enumerate(s):
        if ch in "({[":
            depth += 1
        elif ch in ")}]":
            depth -= 1
        elif ch == "," and depth == 0:
            parts.append(s[start:i])
            start = i + 1
    parts.append(s[start:])
    return [p.strip() for p in parts if p.strip()]


def _start_bytes(op, shape_s):
    """Result payload of an async '-start' tuple shape.

    The tuple layout is op-specific (verified against compiled HLO):
    ``all-reduce-start`` has the SAME shape as the sync op — a flat tuple
    of results when XLA combined several all-reduces — so every buffer
    counts.  ``all-gather-start`` / ``reduce-scatter-start`` /
    ``collective-permute-start`` carry
    ``(operand(s), result(s), [u32 context scalars...])`` — count only
    the result element (itself possibly a tuple for grouped ops).
    Summing naively would double those (reduce-scatter-start used to fall
    into the generic fallback and did exactly that, inflating absolute
    KiB/step); taking the single largest buffer (the old rule)
    undercounts any grouped form.
    """
    parts = _split_top_level(shape_s)
    parts = [p for p in parts
             if not re.fullmatch(r"[su]32\[\]\S*", p)]  # context scalars
    if not parts:
        return 0
    if op == "all-reduce":
        return sum(shape_bytes(p) for p in parts)
    if op in ("all-gather", "reduce-scatter", "collective-permute") \
            and len(parts) >= 2:
        return shape_bytes(parts[1])
    # generic async wrapper: ((operands...), results, ctx) — a leading
    # tuple element marks the operand pack; otherwise flat results
    if len(parts) >= 2 and parts[0].startswith("("):
        return shape_bytes(parts[1])
    return sum(shape_bytes(p) for p in parts)


# stablehlo: '%3 = stablehlo.dot_general %1, %2, batching_dims = [0] x [0],
#   contracting_dims = [1] x [0] ... : (tensor<8x128xf32>, ...) -> tensor<...>'
_SH_DOT_GENERAL_RE = re.compile(
    r"dot_general\b.*?contracting_dims\s*=\s*"
    r"\[([0-9,\s]*)\]\s*x\s*\[[0-9,\s]*\]"
    r".*?:\s*\(tensor<([^>]+)>.*?->\s*tensor<([^>]+)>")
# stablehlo non-general dot: '%3 = stablehlo.dot %1, %2 {...} :
#   (tensor<8x128xf32>, tensor<128x32xf32>) -> tensor<8x32xf32>' — matrix /
#   matrix-vector / dot-product semantics: the contraction is always the
#   lhs LAST dimension against the rhs first.
_SH_DOT_RE = re.compile(
    r"stablehlo\.dot\s+[^:]*:\s*\(tensor<([^>]+)>\s*,\s*tensor<([^>]+)>\s*\)"
    r"\s*->\s*tensor<([^>]+)>")
# HLO: '%dot.3 = f32[8,512]{1,0} dot(f32[8,128]{1,0} %a, ...),
#   lhs_contracting_dims={1}, rhs_contracting_dims={0}'
_HLO_DOT_RE = re.compile(
    r"=\s*([a-z][a-z0-9]+\[[0-9,]*\])\S*\s+dot\(\s*([a-z][a-z0-9]+\[[0-9,]*\])"
    r".*?lhs_contracting_dims=\{([0-9,]*)\}")
# stablehlo convolution: '%4 = stablehlo.convolution(%1, %2)
#   dim_numbers = [b, 0, 1, f]x[0, 1, i, o]->[b, 0, 1, f], window = {...}
#   {feature_group_count = 1 : i64, ...} : (tensor<1x8x8x3xf32>,
#   tensor<3x3x3x16xf32>) -> tensor<1x6x6x16xf32>'.  The FLOP model reads
# the RHS (kernel) dim roles from the middle dim_numbers group: per output
# element the contraction is i x spatial (the kernel's i dim is already
# C_in/groups in the IR, so feature_group_count needs no special casing).
_SH_CONV_RE = re.compile(
    r"stablehlo\.convolution\b.*?dim_numbers\s*=\s*\[[^\]]*\]\s*x\s*"
    r"\[([^\]]*)\]\s*->"
    r".*?:\s*\(tensor<([^>]+)>\s*,\s*tensor<([^>]+)>\s*\)"
    r"\s*->\s*tensor<([^>]+)>")
# HLO convolution: '%conv = f32[1,16,6,6]{...} convolution(
#   f32[1,3,8,8]{...} %x, f32[16,3,3,3]{...} %w), window={size=3x3},
#   dim_labels=bf01_oi01->bf01' — kernel dim roles from the middle
# dim_labels group (chars: o, i, spatial digits).
_HLO_CONV_RE = re.compile(
    r"=\s*([a-z][a-z0-9]+\[[0-9,]*\])\S*\s+convolution\("
    r"[^(]*?,\s*([a-z][a-z0-9]+\[[0-9,]*\])"
    r".*?dim_labels=[a-z0-9]+_([a-z0-9]+)->")
# label-less convolution fallbacks: the shapes alone, for lines whose
# dim_labels/dim_numbers metadata was stripped (debug dumps, minimized
# repros).  dim-role parsing stays the PREFERRED path — these only match
# after it fails, and the contraction is inferred from the conventional
# kernel layout (HLO 'oi01': output features FIRST; StableHLO
# '[0, 1, i, o]': output features LAST), cross-checked against the
# result shape before counting.
_HLO_CONV_NOLABEL_RE = re.compile(
    r"=\s*([a-z][a-z0-9]+\[[0-9,]*\])\S*\s+convolution\("
    r"[^(]*?,\s*([a-z][a-z0-9]+\[[0-9,]*\])")
_SH_CONV_NOLABEL_RE = re.compile(
    r"stablehlo\.convolution\b"
    r".*?:\s*\(tensor<([^>]+)>\s*,\s*tensor<([^>]+)>\s*\)"
    r"\s*->\s*tensor<([^>]+)>")

# dot-like ops the counter knows it does NOT model: any appearance goes to
# the report's uncounted_ops so a program using them cannot silently read
# as zero FLOPs.  HLO 'dot(' lines missing contracting-dims metadata,
# convolutions whose shapes defeat even the label-less fallback, and
# unparseable stablehlo dot forms are appended dynamically.
_UNCOUNTED_RE = re.compile(
    r"(stablehlo\.convolution\b"
    r"|(?<![-\w])convolution\("
    r"|stablehlo\.dot_general\b"
    r"|stablehlo\.dot\b"
    r"|(?<![-\w.])dot\()")
_UNCOUNTED_NAMES = {
    "stablehlo.convolution": "stablehlo.convolution",
    "convolution(": "convolution",
    "stablehlo.dot_general": "stablehlo.dot_general",
    "stablehlo.dot": "stablehlo.dot",
    "dot(": "dot",
}


def _tensor_dims(spec):
    """'2x4x64xf32' -> [2, 4, 64] (scalar 'f32' -> [])."""
    return [int(d) for d in spec.split("x")[:-1]]


def _tensor_dtype(spec):
    """'2x4x64xf32' -> 'f32'."""
    return spec.split("x")[-1]


def _bracket_dims(spec):
    """'f32[8,128]' -> [8, 128]."""
    inner = spec[spec.index("[") + 1:spec.index("]")]
    return [int(d) for d in inner.split(",") if d]


def _bracket_dtype(spec):
    """'f32[8,128]' -> 'f32'."""
    return spec[:spec.index("[")]


def _prod(dims):
    n = 1
    for d in dims:
        n *= d
    return n


def _conv_contraction(rhs_dims, rhs_spec):
    """Per-output-element multiply count of a convolution: the kernel's
    ``i`` dim (already C_in / feature_group_count in both dialects) times
    its spatial dims.  ``rhs_spec`` is the kernel dim-role string — a
    stablehlo ``dim_numbers`` group like ``'0, 1, i, o'`` or an HLO
    ``dim_labels`` group like ``'oi01'``.  Returns None (-> uncounted)
    when the roles don't line up with the shape."""
    roles = [t for t in re.split(r"[,\s]+", rhs_spec.strip()) if t] \
        if "," in rhs_spec or " " in rhs_spec else list(rhs_spec.strip())
    if len(roles) != len(rhs_dims) or "i" not in roles:
        return None
    contraction = 1
    for role, dim in zip(roles, rhs_dims):
        if role != "o":
            contraction *= dim
    return contraction


def _conv_contraction_from_shapes(rhs_dims, out_dims, o_first):
    """Per-output-element multiply count of a LABEL-LESS convolution,
    inferred from the kernel and result shapes alone: contraction =
    prod(kernel dims) / output-feature dim.  The output-feature dim is
    taken from the conventional kernel layout of the dialect
    (``o_first`` True for HLO's ``oi01``, False for StableHLO's
    ``[0, 1, i, o]``), cross-checked against the result shape — a
    candidate ``o`` absent from the result dims falls back to the other
    end, and None (-> uncounted) when neither lines up.  Exact when the
    layout convention holds; a floor (never an overcount of the honest
    per-element work) otherwise, since every kernel element multiplies
    at most once per output element."""
    if not rhs_dims or not out_dims:
        return None
    ends = (0, -1) if o_first else (-1, 0)
    for end in ends:
        o = rhs_dims[end]
        if o in out_dims:
            return _prod(rhs_dims) // o
    return None


def dot_flops_report(program_text):
    """Structured matmul-FLOP accounting of a lowered program.

    Returns ``{"flops": int, "dots": [...], "uncounted_ops": [...]}``:

    * ``flops`` — total 2 * result elements * contraction size over every
      parsed dot (StableHLO ``dot_general`` and non-general ``dot``, HLO
      ``dot(`` lines; fusion bodies included) and convolution (either
      dialect: contraction = kernel i-dim x spatial dims, read from
      ``dim_numbers``/``dim_labels`` — grouped convs need no special
      casing, the IR kernel's i dim is already C_in/groups);
    * ``dots`` — one record per parsed line: ``{"op", "dtype"
      (result element type), "flops", "line"}`` — the dtype-lint pass
      reads these to flag f32 dots inside bf16 programs;
    * ``uncounted_ops`` — dot-like ops the counter saw but could not
      model (malformed dot lines, convolutions whose shapes defeat even
      the label-less fallback), as ``{"op", "count"}`` aggregates.  A
      non-empty list means ``flops`` is a floor, not a total — the
      FLOP-coverage pass turns it into an error.

    Convolutions parse through dim-role metadata first
    (``dim_numbers``/``dim_labels``); a LABEL-LESS conv falls back to
    shape inference (:func:`_conv_contraction_from_shapes` — contraction
    = prod(kernel dims) / output-feature dim under the dialect's
    conventional kernel layout) and its dot record carries
    ``"inferred": True`` so audits can tell exact from inferred counts.
    """
    total = 0
    dots = []
    uncounted = {}

    def _count_uncounted(name):
        uncounted[name] = uncounted.get(name, 0) + 1

    for line in program_text.splitlines():
        m = _SH_DOT_GENERAL_RE.search(line)
        if m is not None:
            cdims = [int(d) for d in m.group(1).replace(" ", "").split(",")
                     if d]
            lhs = _tensor_dims(m.group(2))
            out = _tensor_dims(m.group(3))
            flops = 2 * _prod(out) * _prod(lhs[d] for d in cdims)
            total += flops
            dots.append({"op": "stablehlo.dot_general",
                         "dtype": _tensor_dtype(m.group(3)),
                         "flops": flops, "line": line.strip()})
            continue
        m = _SH_DOT_RE.search(line)
        if m is not None:
            lhs = _tensor_dims(m.group(1))
            out = _tensor_dims(m.group(3))
            # stablehlo.dot contracts lhs's last dim; a scalar-shaped lhs
            # (pure dot product) contracts its only dim
            contract = lhs[-1] if lhs else 1
            flops = 2 * _prod(out) * contract
            total += flops
            dots.append({"op": "stablehlo.dot",
                         "dtype": _tensor_dtype(m.group(3)),
                         "flops": flops, "line": line.strip()})
            continue
        m = _HLO_DOT_RE.search(line)
        if m is not None:
            out = _bracket_dims(m.group(1))
            lhs = _bracket_dims(m.group(2))
            cdims = [int(d) for d in m.group(3).split(",") if d]
            flops = 2 * _prod(out) * _prod(lhs[d] for d in cdims)
            total += flops
            dots.append({"op": "dot", "dtype": _bracket_dtype(m.group(1)),
                         "flops": flops, "line": line.strip()})
            continue
        m = _SH_CONV_RE.search(line)
        if m is not None:
            contraction = _conv_contraction(_tensor_dims(m.group(3)),
                                            m.group(1))
            if contraction is not None:
                out = _tensor_dims(m.group(4))
                flops = 2 * _prod(out) * contraction
                total += flops
                dots.append({"op": "stablehlo.convolution",
                             "dtype": _tensor_dtype(m.group(4)),
                             "flops": flops, "line": line.strip()})
                continue
        m = _HLO_CONV_RE.search(line)
        if m is not None:
            contraction = _conv_contraction(_bracket_dims(m.group(2)),
                                            m.group(3))
            if contraction is not None:
                out = _bracket_dims(m.group(1))
                flops = 2 * _prod(out) * contraction
                total += flops
                dots.append({"op": "convolution",
                             "dtype": _bracket_dtype(m.group(1)),
                             "flops": flops, "line": line.strip()})
                continue
        # label-less fallbacks: contraction from operand/result shapes
        # when the dim-role metadata is absent or unparsable (the
        # preferred labeled paths above already failed on this line)
        m = _SH_CONV_NOLABEL_RE.search(line)
        if m is not None and "stablehlo.convolution" in line:
            contraction = _conv_contraction_from_shapes(
                _tensor_dims(m.group(2)), _tensor_dims(m.group(3)),
                o_first=False)
            if contraction is not None:
                out = _tensor_dims(m.group(3))
                flops = 2 * _prod(out) * contraction
                total += flops
                dots.append({"op": "stablehlo.convolution",
                             "dtype": _tensor_dtype(m.group(3)),
                             "flops": flops, "inferred": True,
                             "line": line.strip()})
                continue
        m = _HLO_CONV_NOLABEL_RE.search(line)
        if m is not None:
            contraction = _conv_contraction_from_shapes(
                _bracket_dims(m.group(2)), _bracket_dims(m.group(1)),
                o_first=True)
            if contraction is not None:
                out = _bracket_dims(m.group(1))
                flops = 2 * _prod(out) * contraction
                total += flops
                dots.append({"op": "convolution",
                             "dtype": _bracket_dtype(m.group(1)),
                             "flops": flops, "inferred": True,
                             "line": line.strip()})
                continue
        m = _UNCOUNTED_RE.search(line)
        if m is not None:
            _count_uncounted(_UNCOUNTED_NAMES[m.group(1)])
    return {
        "flops": total,
        "dots": dots,
        "uncounted_ops": [{"op": k, "count": v}
                          for k, v in sorted(uncounted.items())],
    }


def dot_flops(program_text):
    """Total matmul FLOPs (2 * result elements * contraction size) of every
    dot in a lowered program — StableHLO ``dot_general`` / ``dot`` and HLO
    ``dot(`` lines all count, fusion bodies included.

    The decode benchmark's O(1)-in-prefix assertion rests on this: a
    KV-cached decode step's dot FLOPs are a constant while the
    recompute-the-prefix program's grow linearly with T.  Static counting
    (like :func:`collective_stats`) — no execution, backend-independent
    when fed ``jit(...).lower(...).as_text()``.  Dot-like ops the counter
    cannot parse contribute zero here; :func:`dot_flops_report` surfaces
    them as ``uncounted_ops``.
    """
    return dot_flops_report(program_text)["flops"]


_ALIAS_ENTRY_RE = re.compile(
    r"\{([0-9,\s]*)\}:\s*\(\s*([0-9]+)\s*,\s*\{[0-9,\s]*\}")


def input_output_aliases(compiled_text):
    """Donation aliases of a compiled HLO module.

    Parses the module header's ``input_output_alias={ {out}: (param,
    {index}, kind), ... }`` block into a list of ``(output_index_path,
    parameter_number)`` tuples.  An empty list means XLA aliased nothing —
    for a program traced with ``donate_argnums`` that is a dropped
    donation (the donation-auditor pass's error condition).
    """
    # the block lives on the HloModule header line (nested braces, so a
    # balanced scan, not a regex); only that line is consulted so a string
    # constant elsewhere cannot fake a header
    for line in compiled_text.splitlines():
        if "HloModule" not in line:
            continue
        key = "input_output_alias={"
        at = line.find(key)
        if at < 0:
            return []
        depth, start = 1, at + len(key)
        end = start
        for i in range(start, len(line)):
            if line[i] == "{":
                depth += 1
            elif line[i] == "}":
                depth -= 1
                if depth == 0:
                    end = i
                    break
        entries = []
        for out_idx, param in _ALIAS_ENTRY_RE.findall(line[start:end]):
            path = tuple(int(d) for d in out_idx.split(",") if d.strip())
            entries.append((path, int(param)))
        return entries
    return []


# StableHLO collectives (the LOWERED dialect, before backend
# legalization): explicit shard_map collectives — the MoE all-to-all
# dispatch, ring ppermutes, Megatron psums — appear here by name, so the
# roofline traffic accounting (analysis/cost.py) can price a program's
# wire bytes with trace+lower only, no compile.  Result types live on
# the op line (`-> tensor<...>`) except for region-bearing ops
# (all_reduce / reduce_scatter carry a reduction block), whose signature
# lands on the region's closing `}) : (...) -> ...` line.
_SH_COLLECTIVE_RE = re.compile(
    r"\"?stablehlo\.(all_to_all|all_gather|all_reduce|collective_permute"
    r"|collective_broadcast|reduce_scatter)\"?\b")
_SH_RESULT_RE = re.compile(r"->\s*(.+?)\s*$")
_SH_TENSOR_RE = re.compile(r"tensor<([^>]+)>")

# stablehlo op -> the compiled-HLO spelling, so budget files and reports
# share one collective vocabulary across both dialects
_SH_TO_HLO_OP = {
    "all_to_all": "all-to-all", "all_gather": "all-gather",
    "all_reduce": "all-reduce", "collective_permute": "collective-permute",
    "collective_broadcast": "collective-broadcast",
    "reduce_scatter": "reduce-scatter",
}


def _sh_result_bytes(line):
    """Total bytes of every tensor<> in the line's `-> ...` result type
    (tuples sum); None when the line carries no arrow."""
    m = _SH_RESULT_RE.search(line)
    if m is None:
        return None
    total = 0
    for spec in _SH_TENSOR_RE.findall(m.group(1)):
        dims = _tensor_dims(spec)
        bits = _DTYPE_BITS.get(_tensor_dtype(spec))
        if bits is None:
            continue
        total += (_prod(dims) * bits + 7) // 8
    return total


def stablehlo_collective_stats(stablehlo_text):
    """Count collectives and sum their result payloads in LOWERED
    StableHLO text — the same report shape as :func:`collective_stats`
    ({op: {"count", "bytes"}} + "total"), with ops named in the
    compiled-HLO spelling so the two dialects share a vocabulary.
    Region-bearing ops (all_reduce) print their type signature on the
    region's closing line; a pending queue matches them up (reduction
    bodies never nest further collectives)."""
    stats = {}
    pending = []

    def _note(op, nbytes):
        entry = stats.setdefault(op, {"count": 0, "bytes": 0})
        entry["count"] += 1
        entry["bytes"] += nbytes or 0

    for line in stablehlo_text.splitlines():
        m = _SH_COLLECTIVE_RE.search(line)
        if m is not None:
            op = _SH_TO_HLO_OP[m.group(1)]
            nbytes = _sh_result_bytes(line)
            if nbytes is None:
                pending.append(op)     # region op: signature comes later
            else:
                _note(op, nbytes)
            continue
        if pending and line.lstrip().startswith("})") and "->" in line:
            _note(pending.pop(0), _sh_result_bytes(line))
    total = {"count": sum(e["count"] for e in stats.values()),
             "bytes": sum(e["bytes"] for e in stats.values())}
    stats["total"] = total
    return stats


# Materialized-gather traffic: stablehlo.gather (jnp.take / advanced
# indexing — the decode path's paged_gather walks the whole KV pool
# through one of these) writes its result tensor to memory and the
# consumer reads it back, so each gather's HONEST traffic floor is
# 2x its result bytes ON TOP of the operand reads the arg/output
# accounting already covers.  dynamic_slice is deliberately excluded:
# its results are register/VMEM-sized views a fusion almost never
# materializes, while a gather's data-dependent indices defeat fusion
# into the consumer on every backend we target.
_SH_GATHER_RE = re.compile(r"\"?stablehlo\.(?:dynamic_)?gather\"?\b")


def stablehlo_gather_stats(stablehlo_text):
    """``{"count", "bytes"}`` of materialized gather intermediates in
    LOWERED StableHLO text: ``bytes`` is 2x the summed gather-result
    bytes (one write, one re-read by the consumer).

    This is what makes :func:`~mxnet_tpu.analysis.cost.program_cost`
    price the einsum decode path honestly — ``ops.attention.paged_gather``
    materializes a full (B, M*page_tokens, E) dense-ring view of the KV
    pool per K and V per layer, the single largest intermediate in the
    serving system, which pure arg+output accounting cannot see.  The
    fused Pallas flash-decoding kernel has no such gather (the page walk
    happens inside the kernel), so the paged decode step's priced bytes
    visibly drop when ``MXNET_PALLAS_DECODE`` engages — the mfu_table
    delta the ISSUE-11 acceptance line pins."""
    count = 0
    nbytes = 0
    for line in stablehlo_text.splitlines():
        if _SH_GATHER_RE.search(line) is None:
            continue
        count += 1
        nbytes += 2 * (_sh_result_bytes(line) or 0)
    return {"count": count, "bytes": nbytes}


# Materialized sort/scatter traffic: stablehlo.sort (jnp.argsort /
# lax.sort — the MoE sort-based dispatch's (expert, priority) key sort)
# and stablehlo.scatter (jnp .at[].set/add — the capacity-slot pack)
# write their result tensors to memory and the consumer reads them back,
# so each op's HONEST traffic floor is 2x its result bytes on top of the
# operand reads the arg/output accounting covers — the same rule (and
# reason) as :func:`stablehlo_gather_stats`.  Both ops are REGION-
# BEARING in the pretty dialect (sort carries a comparator block,
# scatter an update computation), so their type signature lands on the
# region's closing ``}) : (...) -> ...`` line, matched with the same
# pending-queue trick as :func:`stablehlo_collective_stats`.  The op
# name is matched exactly (``stablehlo.sort`` / ``stablehlo.scatter``),
# so ``select_and_scatter`` (pooling backward — a windowed op with
# different materialization behavior) never counts here.
_SH_SORT_SCATTER_RE = re.compile(r"\"?stablehlo\.(sort|scatter)\"?\b")


def stablehlo_sort_scatter_stats(stablehlo_text):
    """Per-op ``{"count", "bytes"}`` for materialized sort/scatter
    intermediates in LOWERED StableHLO text, plus a ``"total"`` entry:
    ``bytes`` is 2x the summed result bytes (one write, one re-read by
    the consumer; a multi-result sort — argsort's (keys, payload) pair —
    sums every result tensor).

    This is what lets the roofline table compare the MoE dispatch
    algorithms honestly (``MXNET_MOE_DISPATCH``): the sort path's
    intermediates are O(k*N) key/payload vectors plus the slot scatter,
    where the one-hot cumsum pack materializes (k*N, E) int32 one-hot
    and cumsum planes — invisible to arg/output accounting, visible
    here (the cumsum itself lowers to elementwise/reduce-window ops that
    fuse; the one-hot's cost shows up as the E-times-wider scatter and
    iota compares priced into the program's other terms, so the
    comparison floor is conservative for onehot — it can only
    UNDERSTATE the sort path's win)."""
    stats = {}
    pending = []

    def _note(op, nbytes):
        entry = stats.setdefault(op, {"count": 0, "bytes": 0})
        entry["count"] += 1
        entry["bytes"] += 2 * (nbytes or 0)

    for line in stablehlo_text.splitlines():
        m = _SH_SORT_SCATTER_RE.search(line)
        if m is not None:
            op = m.group(1)
            nbytes = _sh_result_bytes(line)
            if nbytes is None:
                pending.append(op)     # region op: signature comes later
            else:
                _note(op, nbytes)
            continue
        if pending and line.lstrip().startswith("})") and "->" in line:
            _note(pending.pop(0), _sh_result_bytes(line))
    total = {"count": sum(e["count"] for e in stats.values()),
             "bytes": sum(e["bytes"] for e in stats.values())}
    stats["total"] = total
    return stats


def collective_stats(hlo_text):
    """Count collectives and sum their result payloads.

    Async start/done pairs count once (the -start carries the shape).
    Returns {op_name: {"count": int, "bytes": int}} plus two aggregate
    entries: "total" over every op, and "overlappable" — the count/bytes
    of collectives the backend emitted as async ``-start``/``-done``
    pairs, i.e. communication the scheduler can overlap with compute
    between the pair (the double-buffered ring's collective-permutes on
    TPU land here; backends that keep sync collectives report 0).
    """
    stats = {}
    overlappable = {"count": 0, "bytes": 0}
    matches = []
    for line in hlo_text.splitlines():
        em = _INSTR_RE.search(line)
        if em is None:
            continue
        shape_s, end = _scan_shape(line, em.end())
        om = _OP_RE.match(line, end)
        if om is None:
            continue
        matches.append((shape_s, om.group(1), om.group(2)))
    for shape_s, op, suffix in matches:
        if suffix == "-done":
            continue
        if suffix == "-start":
            nbytes = _start_bytes(op, shape_s)
            overlappable["count"] += 1
            overlappable["bytes"] += nbytes
        else:
            nbytes = shape_bytes(shape_s)
        entry = stats.setdefault(op, {"count": 0, "bytes": 0})
        entry["count"] += 1
        entry["bytes"] += nbytes
    total = {"count": sum(e["count"] for e in stats.values()),
             "bytes": sum(e["bytes"] for e in stats.values())}
    stats["total"] = total
    stats["overlappable"] = overlappable
    return stats
